"""Serving-pool HA: health-routed routing over N engines, planned drain
with live KV migration, unplanned failover with re-prefill.

One :class:`~hetu_tpu.serve.server.InferenceServer` survives an engine
crash (PR 3: requeue + re-prefill + ``restart_engine``), but a pool of
them is what preemptible capacity actually needs: requests route to the
healthiest member, a PLANNED preemption (``serve_preempt`` fault or an
operator calling :meth:`ServingPool.drain_member`) migrates the member's
live KV slots and mid-decode requests to a peer over the van blob
channel — the peer continues token-for-token with ZERO re-prefill — and
an UNPLANNED death (``serve_engine_kill``: the engine is gone, state and
all) falls back to PR 3's fold-and-re-prefill on a surviving peer.  The
client-visible contract either way: every accepted request completes.

Topology: the pool owns ONE van server; members are
``InferenceServer``\\ s with ``max_clients=0`` (engine loop + failover
machinery, no wire listeners — the pool is the front door and routes
in-process).  Each member's engine sits behind a kill-switch proxy so
chaos runs can SIGKILL-alike it deterministically.  Recovery spans:
planned drains record ``serve.migrate``, unplanned failovers
``serve.failover`` — :data:`hetu_tpu.telemetry.timeline.RECOVERY_FOR`
pairs them with the injected ``fault.serve_*`` instants so a chaos run
reports per-kind detection/recovery percentiles.
"""

from __future__ import annotations

import itertools
import threading
import time
import traceback
from typing import Optional

from hetu_tpu.serve import migrate as _migrate
from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.serve.scheduler import (
    ContinuousBatchingScheduler, Request, cancel_detached, finish_request,
)
from hetu_tpu.serve.server import InferenceServer
from hetu_tpu.telemetry import trace

# migration transfers use their own channel-id namespace, ~1e8 ids BELOW
# the serve request/response namespace (SERVE_CHANNEL_BASE = 0x53525645
# in server.py — this base counts upward toward that gap); each transfer
# gets a fresh id so seqs never collide
MIGRATE_CHANNEL_BASE = 0x4D494752  # 'MIGR'

# PROCESS-GLOBAL transfer counter: the van server is process-wide and
# ``own_van=False`` explicitly supports several pools attaching to one
# van — pool-local counters would hand two concurrent drains the SAME
# channel id, and each receiver would consume the other's (individually
# CRC-valid) chunks.  Pools in DIFFERENT processes sharing a van port
# must instead be given disjoint ``migrate_channel_base`` values.
_MIG_SEQ = itertools.count(1)


class EngineKilled(RuntimeError):
    """The pool's kill switch fired: this member's engine is gone."""


class _GuardedEngine:
    """Kill-switch proxy over a ServeEngine.

    ``kill()`` makes every subsequent engine VERB raise — the in-process
    analog of SIGKILLing a member's accelerator process: unannounced and
    state-losing (the KV arrays become unreachable through the proxy's
    verbs; the raw cache stays readable so a dead member's slots can
    still be freed and its telemetry read)."""

    def __init__(self, inner):
        self.inner = inner
        self.killed = False

    @property
    def cache(self):
        return self.inner.cache

    @property
    def metrics(self):
        return self.inner.metrics

    def kill(self) -> None:
        self.killed = True

    def _check(self) -> None:
        if self.killed:
            raise EngineKilled("pool member engine killed")

    def alloc_slot(self):
        self._check()
        return self.inner.alloc_slot()

    def release(self, slot):
        self._check()
        self.inner.release(slot)

    def prefill(self, slot, prompt):
        self._check()
        return self.inner.prefill(slot, prompt)

    def decode(self):
        self._check()
        return self.inner.decode()

    def export_slots(self, slot_ids):
        self._check()
        return self.inner.export_slots(slot_ids)

    def adopt_slots(self, snapshots):
        self._check()
        return self.inner.adopt_slots(snapshots)

    def resume_slots(self, slot_ids):
        self._check()
        self.inner.resume_slots(slot_ids)


class PoolMember:
    """One engine + scheduler + (listener-less) server in the pool.

    ``fresh_engine`` builds a new GUARDED engine from the member's
    factory — ``revive_member`` goes through it so a custom member kind
    (the CTR members ``member_factory`` builds in serve/recsys.py) revives
    with ITS guard class, not the LLM one."""

    def __init__(self, name: str, factory, scheduler, server, *,
                 fresh_engine=None):
        self.name = name
        self.factory = factory
        self.scheduler = scheduler
        self.server = server
        self.fresh_engine = fresh_engine if fresh_engine is not None \
            else (lambda: _GuardedEngine(factory()))
        self.draining = False  # planned drain in progress / completed
        self.dead = False      # failed over or drained-and-closed
        self.pending = 0       # submits routed here, not yet queued

    @property
    def engine(self):
        return self.scheduler.engine

    @property
    def available(self) -> bool:
        return (not self.draining and not self.dead and
                self.server.healthy)


class ServingPool:
    """Router + supervisor over N serving members.

    ``engine_factories``: ``{name: factory}`` (or a list; names become
    ``m0..mN``) where each factory builds a fresh ``ServeEngine`` — the
    same factory revives a member after death.  The pool starts one van
    server for the whole process (``own_van=False`` + ``port`` attaches
    to an existing one) — members share it for migration transfers.

    Health: a poll thread watches ``member.server.healthy`` and fails a
    dead member's queue over to surviving peers automatically
    (``health_poll_s``; pass ``start_poll=False`` to drive :meth:`poll`
    manually in tests).
    """

    def __init__(self, engine_factories, *, port: int = 0,
                 own_van: bool = True, token_budget: Optional[int] = None,
                 max_requeues: int = 5, max_loop_errors: int = 2,
                 failover_grace_s: float = 30.0,
                 health_poll_s: float = 0.05,
                 request_timeout_s: float = 60.0,
                 chunk_bytes: int = _migrate.DEFAULT_CHUNK_BYTES,
                 migrate_codec: str = "none",
                 migrate_channel_base: int = MIGRATE_CHANNEL_BASE,
                 metrics: Optional[ServeMetrics] = None,
                 member_factory=None,
                 shed: bool = False, shed_headroom: float = 1.0,
                 start_poll: bool = True):
        from hetu_tpu.ps import van
        # member_factory(pool, name, engine_factory) -> PoolMember lets a
        # different serving workload (the CTR members of
        # serve/recsys.RecsysPool) ride the SAME routing/drain/failover
        # machinery; None = the LLM member (engine + continuous-batching
        # scheduler + listener-less InferenceServer)
        self._member_factory = member_factory
        items = list(engine_factories.items()) \
            if isinstance(engine_factories, dict) \
            else [(f"m{i}", f) for i, f in enumerate(engine_factories)]
        if not items:
            # validate BEFORE starting the van: raising after serve()
            # would leak the process-wide van server with no owner
            raise ValueError("a serving pool needs at least one member")
        self._van = van
        self._own_van = own_van
        if own_van:
            self.port = van.serve(port)
        else:
            if not port:
                raise ValueError("own_van=False needs the running van's port")
            self.port = port
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.request_timeout_s = float(request_timeout_s)
        self._token_budget = token_budget
        self._max_requeues = int(max_requeues)
        self._max_loop_errors = int(max_loop_errors)
        self._failover_grace_s = float(failover_grace_s)
        self._chunk_bytes = int(chunk_bytes)
        # overload shedding per member scheduler (serve/scheduler.py):
        # a deadline-doomed submit resolves 'shed' instantly instead of
        # queueing into collapse; pool.submit does NOT re-route a shed
        # (every member sees the same overload — re-routing would just
        # tour the pool before failing slower)
        self._shed = bool(shed)
        self._shed_headroom = float(shed_headroom)
        # wire codec for drain payloads ("bf16"/"int8", see migrate.pack;
        # "auto" picks per drain from the measured link rate)
        self.migrate_codec = _migrate.check_codec(migrate_codec)
        self._lock = threading.RLock()
        # see _MIG_SEQ: ids are drawn process-globally; the base is only
        # caller-assignable for pools in SEPARATE processes on one van
        self._mig_base = int(migrate_channel_base)
        self.members: dict = {}
        try:
            for name, factory in items:
                self.members[str(name)] = self._make_member(str(name),
                                                            factory)
        except Exception:
            self.close()
            raise
        self._stop = threading.Event()
        self._poll_thread = None
        if start_poll:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, args=(float(health_poll_s),),
                daemon=True)
            self._poll_thread.start()

    def _make_member(self, name: str, factory) -> PoolMember:
        if self._member_factory is not None:
            return self._member_factory(self, name, factory)
        engine = _GuardedEngine(factory())
        sched = ContinuousBatchingScheduler(
            engine, token_budget=self._token_budget,
            max_requeues=self._max_requeues,
            shed=self._shed, shed_headroom=self._shed_headroom)
        srv = InferenceServer(
            sched, port=self.port, own_van=False, max_clients=0,
            request_timeout_s=self.request_timeout_s,
            max_loop_errors=self._max_loop_errors,
            failover_grace_s=self._failover_grace_s)
        return PoolMember(name, factory, sched, srv)

    # ---- routing ----
    @property
    def healthy(self) -> bool:
        with self._lock:
            return any(m.available for m in self.members.values())

    def pick(self, *, exclude=()) -> Optional[PoolMember]:
        """Least-loaded available member, or None.  The load signal
        counts submits already routed to a member but not yet visible in
        its queue (``member.pending``): the queue append happens outside
        the pool lock, so without it N concurrent submits all read the
        same stale count and pile onto one member — leaving its peers
        idle, which under chaos means a killed idle peer whose death
        nothing ever detects."""
        with self._lock:
            cands = [m for m in self.members.values()
                     if m.available and m.name not in exclude]
            if not cands:
                return None
            return min(cands, key=lambda m: m.scheduler.load + m.pending)

    def submit(self, request: Request) -> Request:
        """Route to the healthiest member; with no member available the
        request completes immediately with status 'error' (fail fast —
        nothing would ever serve it).

        The member's ``scheduler.submit`` runs OUTSIDE the pool lock: it
        takes that member's scheduler lock, which its engine loop holds
        across whole decode steps — submitting under the pool lock would
        stall all routing (and failover detection) behind one busy or
        wedged member.  The cost is a race with a concurrent
        drain/failover of the picked member, resolved by re-routing: a
        rejected submit (terminal status, zero tokens) retries the next
        member."""
        for _ in range(len(self.members) + 1):
            with self._lock:
                m = self.pick()
                if m is not None:
                    m.pending += 1  # claim the routing slot under the lock
            if m is None:
                break
            try:
                m.scheduler.submit(request, resolve_on_reject=False)
            finally:
                with self._lock:
                    m.pending -= 1
            if not request.rejected:
                self.metrics.inc("pool_requests")
                return request
            # the picked member drained between pick and submit — its
            # scheduler flagged the EXPLICIT reject (an accepted request
            # that genuinely failed with zero tokens must NOT re-route:
            # a member already finished it) without resolving the
            # request (resolve_on_reject=False), so a waiter already
            # parked on request.done sleeps through the re-route — no
            # event swap, no transient terminal state for it to misread.
            # Clear the flag and try another member
            request.rejected = False
        self._finish_unrouted(request, "error")
        self.metrics.inc("requests_rejected_no_member")
        return request

    def _finish_unrouted(self, req: Request, status: str) -> None:
        # same terminal bookkeeping as a scheduler finish, against the
        # POOL's metrics — the requests the HA layer itself resolves
        # must not vanish from the requests_<status> counters a chaos
        # dashboard reads
        finish_request(req, status, self.metrics)

    def generate(self, prompt, *, max_tokens: int = 16, eos_id=None,
                 timeout_s: Optional[float] = None) -> dict:
        """Blocking convenience: submit + wait; the response dict matches
        the wire server's shape."""
        req = Request(prompt=[int(t) for t in prompt],
                      max_tokens=int(max_tokens), eos_id=eos_id,
                      timeout_s=float(timeout_s if timeout_s is not None
                                      else self.request_timeout_s))
        self.submit(req)
        # generous backstop over the serving deadline: a mid-flight
        # migration/failover must not strand the waiter
        if not req.done.wait(timeout=req.timeout_s + 15.0):
            # resolve 'timeout', not 'cancelled' — unless the request
            # finished in the race, in which case the cancel keeps its
            # real terminal status
            self._cancel(req, "timeout")
        return {"id": req.rid, "status": req.status or "ok",
                "tokens": list(req.tokens), "ttft_s": req.ttft_s}

    def _cancel(self, req: Request, status: str = "cancelled") -> None:
        # go straight to the request's stamped owner instead of scanning
        # every member with owns(): the scan takes each scheduler's lock
        # in turn, so ONE wedged member (engine stuck mid-step, loop
        # thread alive and 'healthy') would block cancelling a request
        # served by a healthy peer forever — the exact backstop this
        # cancel exists to provide.  cancel_detached resolves the waiter
        # WITHOUT the owner's scheduler lock (the owner itself may be
        # the wedged member) and detaches the dequeue/slot cleanup.  A
        # stale owner read (the request migrated underneath us) still
        # resolves the request, and finish_request's per-request guard
        # keeps the racing finishers single-charged.
        owner = req.owner
        if owner is not None:
            cancel_detached(owner, req, status)
            return
        if not req.done.is_set():  # in transit between members
            self._finish_unrouted(req, status)

    # ---- health / unplanned failover ----
    def _poll_loop(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            try:
                self.poll()
            except Exception:
                traceback.print_exc()  # the poll must survive anything

    def poll(self) -> int:
        """One health sweep: members whose engine loop died hand their
        surviving queue to peers (the unplanned path).  Returns how many
        members failed over."""
        with self._lock:
            down = [m for m in self.members.values()
                    if not m.dead and not m.draining
                    and not m.server.healthy]
        n = 0
        for m in down:
            self.failover(m.name)
            n += 1
        return n

    def failover(self, name: str) -> int:
        """Unplanned failover: the member's engine is gone (KV state and
        all), so its queue — including requests the dying engine loop
        already requeued — re-prefills on surviving peers.  Records a
        ``serve.failover`` recovery span.  Returns requests moved."""
        m = self.members[name]
        with self._lock:
            # a member mid-drain belongs to drain_member: ripping its
            # scheduler's intake out from under the drain would make the
            # drain's failure ROLLBACK impossible (adopt-back onto a
            # drained scheduler raises, terminally 'error'-ing accepted
            # requests a peer could still serve).  If the drain fails it
            # clears `draining` and the next health sweep lands here.
            if m.dead or m.draining:
                return 0
            m.dead = True
        with trace.span("serve.failover", cat="serve") as sp:
            sp.set("member", name)
            # the dead member's grace timer must not fire later and
            # 'error'-drain bookkeeping we are about to hand to a peer.
            # Nothing here may abort the failover: m.dead is already
            # claimed, so an exception would strand the queue forever
            # (the disarm itself is the event set, which cannot fail)
            try:
                m.server.cancel_failover_grace()
            except Exception:
                traceback.print_exc()
            # close intake BEFORE the export: a submit that lost the
            # pick-vs-failover race is then REJECTED (and re-routed by
            # pool.submit) — were intake still open, it could be
            # admitted AFTER the export into a queue nothing will ever
            # serve and be terminally drained by the member's close
            m.scheduler.stop_intake("error")
            pairs = m.scheduler.export_inflight(fold=True)
            moved = self._rehome(pairs, tried={name})
            sp.set("requests", moved)
        self.metrics.inc("pool_failovers")
        self.metrics.inc("requests_failed_over", moved)
        return moved

    def _rehome(self, pairs, *, tried: set) -> int:
        """Adopt exported ``(request, None)`` pairs onto surviving peers
        (the re-prefill path); requests nothing can serve resolve
        'error' — never stranded.  The whole batch adopts in ONE
        ``adopt_inflight`` call per picked peer (all-or-nothing for
        slotless pairs): the target's scheduler lock is held across
        whole decode steps, so per-request adopts would make failover
        wall-clock O(requests x decode_step).  ``tried`` carries across
        attempts: a peer that failed the adopt (drained/dead) is no
        home for ANY of this batch.  Returns how many requests found a
        peer."""
        remaining = [req for req, _ in pairs if not req.done.is_set()]
        # done-in-transit: over-cap requests finished 'error' in the export
        moved = 0
        while remaining:
            with self._lock:
                tgt = self.pick(exclude=tuple(tried))
            if tgt is None:
                break
            try:
                # count what the target ACTUALLY attached: a request
                # that finished in transit (cancel/backstop-timeout
                # racing the failover) is skipped by adopt_inflight and
                # must not inflate requests_failed_over / the
                # serve.failover span
                _, moved = tgt.scheduler.adopt_inflight(
                    [(req, None) for req in remaining], return_count=True)
            except Exception:
                # the peer drained between pick and adopt: try next
                tried.add(tgt.name)
                continue
            remaining = []
        for req in remaining:
            self._finish_unrouted(req, "error")
            self.metrics.inc("requests_lost_no_peer")
        return moved

    # ---- planned drain (live migration) ----
    def drain_member(self, name: str, *, close: bool = True,
                     wire: bool = True,
                     codec: Optional[str] = None) -> dict:
        """Planned drain (operator signal or ``serve_preempt`` fault):
        migrate every live KV slot and in-flight request to a surviving
        peer — the peer continues mid-decode sequences token-for-token
        with zero re-prefill — then take the member out of service
        (``close=True``: shut its server down, the migrate-then-exit a
        preemption notice wants).  Records a ``serve.migrate`` recovery
        span.  Returns ``{source_slot: dest_slot}``.

        ``wire=True`` sends the K/V payload over the pool's van as
        CRC-checked chunks (the same path a cross-process pool takes);
        ``wire=False`` hands the host arrays over directly.

        ``codec`` overrides the pool-level ``migrate_codec`` for THIS
        drain only (PR 7 residual): a preemption-deadline drain can pick
        "int8" (~4x smaller payload, near-lossless) while routine drains
        stay on the pool default — the codec is a per-eviction-notice
        decision, not a pool property.  ``None`` = the pool default.

        On failure the member re-adopts everything and KEEPS SERVING
        (the error re-raises) — unless its engine is already dead, in
        which case the caller's health poll takes the failover path.
        """
        codec = self.migrate_codec if codec is None \
            else _migrate.check_codec(codec)
        m = self.members[name]
        if codec == "auto":
            # per-drain resolution from the measured link rate (netem
            # cap if one is installed, else the op-span-derived rate)
            # and THIS member's live payload — the crossover model
            # `bench.py migrate --quant` measures, applied at drain time
            codec = _migrate.resolve_codec("auto", m.scheduler.engine)
        with self._lock:
            if m.dead or m.draining:
                return {}
            m.draining = True  # stops routing before the export
        tried = {name}
        try:
            with trace.span("serve.migrate", cat="serve") as sp:
                sp.set("member", name)
                while True:
                    with self._lock:
                        tgt = self.pick(exclude=tuple(tried))
                    if tgt is None:
                        raise RuntimeError(
                            f"no surviving peer to drain '{name}' into")
                    sp.set("target", tgt.name)
                    chs: list = []
                    try:
                        # a queued-only / idle member has no K/V to ship:
                        # migrate_inflight would never touch the wire, so
                        # don't connect (and burn a channel id) for
                        # nothing.  Lock-free read; a request admitted to
                        # running in the window just takes the in-process
                        # hand-over (wire=None), which is equally exact
                        if wire and m.scheduler.running_count:
                            # each channel tracked as constructed, so a
                            # failure building the SECOND one still
                            # closes the first — and a wire-layer setup
                            # failure aborts the drain instead of
                            # blaming (and excluding) a healthy target
                            ch_id = self._mig_base + next(_MIG_SEQ)
                            for _ in range(2):
                                chs.append(self._van.BlobChannel(
                                    "127.0.0.1", self.port, ch_id))
                    except Exception:
                        for ch in chs:
                            try:
                                ch.close()
                            except Exception:
                                pass
                        raise
                    try:
                        slot_map = _migrate.migrate_inflight(
                            m.scheduler, tgt.scheduler,
                            wire=tuple(chs) if chs else None,
                            codec=codec,
                            chunk_bytes=self._chunk_bytes)
                        break
                    except _migrate.MigrationTargetError:
                        # migrate_inflight rolled everything back onto
                        # the source, so retrying elsewhere is safe — a
                        # TARGET that failed the adoption (e.g. its
                        # engine was killed but not yet detected) is no
                        # home for this member's work; try the next
                        # peer.  Source-side/wire failures propagate
                        # instead: re-exporting against another peer
                        # would fail identically.
                        tried.add(tgt.name)
                        if len(tried) >= len(self.members):
                            # every member tried: re-raise THIS error —
                            # looping once more would pick() None and
                            # bury the real adoption failure under the
                            # generic 'no surviving peer'
                            raise
                    finally:
                        for ch in chs:
                            try:
                                ch.close()
                            except Exception:
                                pass
                sp.set("slots", len(slot_map))
        except Exception:
            with self._lock:
                m.draining = False  # back in service (or the poll's hands)
            raise
        self.metrics.inc("pool_migrations")
        self.metrics.inc("slots_migrated", len(slot_map))
        if close:
            # a submit that raced pick-vs-drain may have been admitted
            # AFTER the export: close intake first (late submits now
            # reject and pool.submit re-routes them), then sweep
            # anything that landed in the window onto the peers — the
            # close below must never terminally 'shutdown' an accepted
            # request
            m.scheduler.stop_intake("shutdown")
            stragglers = m.scheduler.export_inflight(fold=True)
            if stragglers:
                swept = self._rehome(stragglers, tried={name})
                self.metrics.inc("requests_swept_on_drain", swept)
            m.server.close()
            self._close_engine(m)
            with self._lock:
                m.dead = True
        return slot_map

    # ---- membership ----
    def kill_member(self, name: str) -> None:
        """Flip the member's engine kill switch (the ``serve_engine_kill``
        chaos fault): the engine loop strikes out, ``healthy`` drops, and
        the health poll fails its queue over to a peer."""
        self.members[name].engine.kill()
        self.metrics.inc("members_killed")

    def revive_member(self, name: str) -> None:
        """Bring a dead/drained member back with a fresh engine from its
        factory; it rejoins routing immediately."""
        m = self.members[name]
        self._close_engine(m)  # the dead engine's resources (e.g. a CTR
        # member's serving caches, whose open degrade window must be
        # recorded, not dropped) are released before the replacement
        if m.server._stop.is_set():
            # drained-and-closed: the old server is gone; rebuild whole
            self.members[name] = self._make_member(name, m.factory)
        else:
            m.server.restart_engine(m.fresh_engine())
            with self._lock:
                m.dead = False
                m.draining = False
        self.metrics.inc("members_revived")

    # ---- chaos integration ----
    def apply_fault(self, kind: str, member_idx: int) -> None:
        """Route an injected serve fault at a member by index (modulo the
        pool size, insertion order): ``serve_preempt`` = planned drain
        (migrate-then-exit), ``serve_engine_kill`` = abrupt engine death
        (the health poll then fails it over)."""
        names = list(self.members)
        name = names[int(member_idx) % len(names)]
        if kind == "serve_preempt":
            try:
                self.drain_member(name)
            except Exception:
                # no peer / engine already dead: the failover path (or
                # the operator) owns it now — a chaos injection must not
                # kill the driver
                traceback.print_exc()
        elif kind == "serve_engine_kill":
            self.kill_member(name)
        else:
            raise ValueError(f"unknown serve fault kind {kind!r}")

    def run_fault_events(self, events) -> None:
        """Apply events drained from
        ``FaultInjector.pop_serve_events()``."""
        for kind, idx in events:
            self.apply_fault(kind, idx)

    # ---- lifecycle ----
    @staticmethod
    def _close_engine(m: PoolMember) -> None:
        """Best-effort engine close where the engine kind has one (the
        LLM ServeEngine does not; a CTR engine closes its serving
        caches, recording any still-open degrade span)."""
        close = getattr(m.scheduler.engine, "close", None)
        if close is None:
            return
        try:
            close()
        except Exception:
            traceback.print_exc()

    def close(self, timeout_s: float = 10.0) -> None:
        stop = getattr(self, "_stop", None)
        if stop is not None:
            stop.set()
        t = getattr(self, "_poll_thread", None)
        if t is not None:
            t.join(timeout_s)
        for m in self.members.values():
            try:
                m.server.close(timeout_s)
            except Exception:
                traceback.print_exc()
            self._close_engine(m)
        if self._own_van:
            self._van.stop()
