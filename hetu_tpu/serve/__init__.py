"""TPU-native inference serving: KV-cache decode + continuous batching.

The serving layer the ROADMAP's "heavy traffic" north star needs on top
of the training-only models:

  * :mod:`kv_cache` — preallocated slot-based GQA-aware K/V cache with
    alloc/free so finished sequences release memory to queued requests;
  * :mod:`engine` — bucketed jit-compiled prefill + fixed-shape
    single-token decode (bounded executable count) over the existing
    GPT/Llama forwards, optionally tp-sharded over a mesh;
  * :mod:`scheduler` — continuous batching: admit into free slots every
    decode step, evict on EOS/max_tokens/deadline, token-budget
    backpressure;
  * :mod:`server` — blob-channel front-end over the van transport with
    per-request timeouts and graceful shutdown;
  * :mod:`metrics` — TTFT / tokens-per-sec / queue depth / occupancy /
    recompile counters, reportable through ``utils/logger.MetricLogger``.

See examples/gpt_serve.py for the end-to-end path.
"""

from hetu_tpu.serve.engine import ServeEngine
from hetu_tpu.serve.kv_cache import KVCache, KVCacheSpec
from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.serve.scheduler import ContinuousBatchingScheduler, Request
from hetu_tpu.serve.server import (
    InferenceClient, InferenceServer, request_channel, response_channel,
)

__all__ = [
    "ServeEngine", "KVCache", "KVCacheSpec", "ServeMetrics",
    "ContinuousBatchingScheduler", "Request",
    "InferenceClient", "InferenceServer",
    "request_channel", "response_channel",
]
