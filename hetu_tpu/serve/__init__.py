"""TPU-native inference serving: KV-cache decode + continuous batching.

The serving layer the ROADMAP's "heavy traffic" north star needs on top
of the training-only models:

  * :mod:`kv_cache` — GQA-aware K/V caches: the slot allocator
    (:class:`KVCache`) and the PAGED allocator (:class:`PagedKVCache`)
    with refcounted prefix sharing + copy-on-write, so finished
    sequences release memory to queued requests and identical system
    prompts dedup to one physical copy;
  * :mod:`engine` — bucketed jit-compiled prefill + fixed-shape
    single-token decode (bounded executable count) over the existing
    GPT/Llama forwards, optionally tp-sharded over a mesh; the paged
    variant (:class:`PagedServeEngine`) adds page-table gather/scatter
    steps and page-aligned chunked prefill;
  * :mod:`scheduler` — continuous batching: admit into free slots every
    decode step, evict on EOS/max_tokens/deadline, token-budget (slot)
    or page-budget (paged) backpressure, chunked-prefill interleave;
  * :mod:`server` — blob-channel front-end over the van transport with
    per-request timeouts, idempotent resubmission dedup, and graceful
    shutdown;
  * :mod:`metrics` — TTFT / tokens-per-sec / queue depth / occupancy /
    recompile counters, reportable through ``utils/logger.MetricLogger``;
  * :mod:`migrate` — live KV-cache slot migration: chunked CRC-checked
    slot transfer over the van, scheduler hand-off with zero re-prefill;
  * :mod:`pool` — :class:`ServingPool`: health-routed routing over N
    members, planned drain (migrate-then-exit) and unplanned failover;
  * :mod:`crosshost` — :class:`CrossProcessServingPool`: the pool
    across REAL process boundaries — member processes, membership
    leases over the van, two-phase cross-process KV drain;
  * :mod:`recsys` — the SECOND serving workload: online CTR inference
    (WideDeep/DeepFM/DCN) behind the same van front-end and pool
    machinery, with a staleness-bounded hot-embedding serving cache
    over the PS (HET) and a micro-batching scheduler.

See examples/gpt_serve.py, examples/gpt_serve_pool.py and
examples/ctr_serve.py for the end-to-end paths.
"""

from hetu_tpu.serve.crosshost import CrossProcessServingPool
from hetu_tpu.serve.engine import PagedServeEngine, ServeEngine
from hetu_tpu.serve.kv_cache import (
    KVCache, KVCacheSpec, KVSlotSnapshot, PagedKVCache,
)
from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.serve.migrate import MigrationError
from hetu_tpu.serve.pool import ServingPool
from hetu_tpu.serve.scheduler import ContinuousBatchingScheduler, Request
from hetu_tpu.serve.recsys import (
    RecsysBatcher, RecsysClient, RecsysEngine, RecsysPool, RecsysRequest,
    RecsysServer, ServingEmbeddingCache,
)
from hetu_tpu.serve.server import (
    InferenceClient, InferenceServer, request_channel, response_channel,
)

__all__ = [
    "ServeEngine", "PagedServeEngine", "KVCache", "PagedKVCache",
    "KVCacheSpec", "KVSlotSnapshot",
    "ServeMetrics", "MigrationError", "ServingPool",
    "CrossProcessServingPool",
    "ContinuousBatchingScheduler", "Request",
    "InferenceClient", "InferenceServer",
    "request_channel", "response_channel",
    "ServingEmbeddingCache", "RecsysEngine", "RecsysBatcher",
    "RecsysRequest", "RecsysServer", "RecsysClient", "RecsysPool",
]
