"""Cross-process serving pool: members are real OS processes.

PR 5's :class:`~hetu_tpu.serve.pool.ServingPool` proved the HA
machinery — health routing, live KV drain, fold re-prefill failover —
but its members share one Python process, so "member death" was a kill
switch, not a kill.  This module promotes the pool across the process
boundary: each member is a SEPARATE process running a listener-less
:class:`~hetu_tpu.serve.server.InferenceServer` (engine loop + requeue
machinery, ``own_van=False``) attached to the controller's van, and the
control plane crosses the wire:

* **membership** — members join and heartbeat through the van
  blackboard (:mod:`hetu_tpu.ps.membership`); the controller's lease
  state machine (alive → suspect → lost) replaces in-process
  ``server.healthy`` polling.  A SIGSTOPped member goes *suspect*
  (unroutable, state presumed intact) and CLEARS when its beats resume
  — never double-counted as a loss plus a rejoin;
* **requests** — the controller routes each accepted request to the
  least-loaded alive member over a per-process submit channel and
  resolves it from the member's completion events; member death
  (SIGKILL → lease expiry) re-routes every outstanding request to a
  survivor, which re-prefills from the original prompt — greedy decode
  makes the re-served tokens exactly the tokens the dead member would
  have produced;
* **drain** — a planned preemption ships the member's live KV slots AND
  its in-flight request records to a peer process over the existing
  chunked-CRC migrate wire (:func:`hetu_tpu.serve.migrate.
  export_payload` / :func:`~hetu_tpu.serve.migrate.adopt_payload`),
  two-phase: the source holds its export until the target confirms
  adoption, so a failed transfer rolls back to a still-serving source.
  The adopting process continues mid-decode sequences token-for-token
  with zero re-prefill.

Channel topology on the ONE shared van: each member process gets a
fresh (submit, event) blob-channel pair allocated by the controller
(never reused across member incarnations — blob seqs are per-channel
and a revived process must start clean), migration transfers draw ids
from their own base (disjoint from the in-process pool's
``MIGRATE_CHANNEL_BASE`` — several pools can share one van), and the
membership blackboard is a small f32 table.  Recovery spans mirror the
in-process pool (``serve.migrate`` / ``serve.failover``) plus the new
retroactive ``serve.member_suspect`` for a partition that healed.
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
import time
import traceback
from dataclasses import asdict, dataclass, field
from typing import Optional

from hetu_tpu.ps import membership as _mb
from hetu_tpu.serve import migrate as _migrate
from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.serve.pool import _MIG_SEQ
from hetu_tpu.telemetry import trace

# controller-allocated control channels ('CHCT'); migration transfers get
# their own base ('MIG3'), disjoint from serve/pool.py's in-process base
# so a mixed deployment sharing one van cannot cross streams
CONTROL_CHANNEL_BASE = 0x43484354
CROSSHOST_MIGRATE_BASE = 0x4D494733

_xfer_ids = itertools.count(1)


@dataclass
class MemberSpec:
    """Everything a member process needs to build its engine and find
    the control plane — JSON-serialized into the spawn config so the
    member re-derives the SAME model weights (deterministic seeded
    init) the controller and its peers hold."""

    port: int
    slot: int
    n_slots: int
    submit_ch: int
    event_ch: int
    membership_table: int = _mb.SERVE_MEMBERSHIP_TABLE
    hb_ms: int = 100
    request_timeout_s: float = 60.0
    max_loop_errors: int = 2
    failover_grace_s: float = 5.0
    model: dict = field(default_factory=dict)
    # overload shedding in the member's scheduler (serve/scheduler.py):
    # deadline-doomed submits resolve 'shed' instantly instead of
    # queueing into collapse
    shed: bool = False
    shed_headroom: float = 1.0
    # netem link emulation applied at process start: {"seed": int,
    # "links": [[direction, policy_dict], ...]} — the static half; the
    # dynamic half arrives over the wire as a "netem" command
    netem: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "MemberSpec":
        return cls(**json.loads(s))


DEFAULT_MODEL = {
    "vocab_size": 97, "hidden_size": 64, "num_layers": 2, "num_heads": 4,
    "ffn_size": 128, "max_position": 64, "seed": 0,
    "num_slots": 4, "max_len": 48, "min_bucket": 8,
}


def build_engine(model_spec: dict):
    """Deterministic engine construction shared by member processes and
    in-test reference engines: same spec → same weights everywhere, the
    property that makes cross-process failover token-exact."""
    import jax

    from hetu_tpu.models.gpt import GPTConfig, GPTModel
    from hetu_tpu.serve.engine import ServeEngine
    spec = {**DEFAULT_MODEL, **(model_spec or {})}
    cfg = GPTConfig(
        vocab_size=int(spec["vocab_size"]),
        hidden_size=int(spec["hidden_size"]),
        num_layers=int(spec["num_layers"]),
        num_heads=int(spec["num_heads"]),
        ffn_size=int(spec["ffn_size"]),
        max_position=int(spec["max_position"]), dropout_rate=0.0)
    model = GPTModel(cfg)
    variables = model.init(jax.random.PRNGKey(int(spec["seed"])))
    return model, variables, ServeEngine(
        model, variables, num_slots=int(spec["num_slots"]),
        max_len=int(spec["max_len"]), min_bucket=int(spec["min_bucket"]))


# ---------------------------------------------------------------------------
# member process
# ---------------------------------------------------------------------------

class MemberHarness:
    """The member-process half of the control plane.

    Wraps a listener-less :class:`InferenceServer` (its engine loop,
    crash requeue, and failover-grace machinery are reused unchanged)
    with three wire surfaces on the shared van: a command loop on the
    submit channel (submit / drain two-phase / adopt / shutdown — ONE
    reader thread, so a drain command is naturally ordered after every
    submit the controller sent before it), an outbound event queue
    (completions, drain acks) on the event channel, and a membership
    heartbeat carrying load + engine health."""

    def __init__(self, spec: MemberSpec):
        from hetu_tpu.ps import van
        from hetu_tpu.serve.scheduler import ContinuousBatchingScheduler
        from hetu_tpu.serve.server import InferenceServer
        self.spec = spec
        self._van = van
        _, _, engine = build_engine(spec.model)
        self.scheduler = ContinuousBatchingScheduler(
            engine, shed=spec.shed, shed_headroom=spec.shed_headroom)
        # the member's half of the gray-failure plane: one emulator per
        # process, installed up front (policies arrive via spec.netem
        # and/or "netem" commands; an empty emulator is a transparent
        # wire)
        from hetu_tpu.ps.netem import LinkPolicy, NetEm
        self.netem = NetEm(local=f"m{spec.slot}", peer="van",
                           seed=int(spec.netem.get("seed", 0)))
        for direction, pol in spec.netem.get("links", ()):
            self.netem.set_link(LinkPolicy.from_dict(pol),
                                direction=direction)
        self.netem.install()
        self.server = InferenceServer(
            self.scheduler, port=spec.port, own_van=False, max_clients=0,
            request_timeout_s=spec.request_timeout_s,
            max_loop_errors=spec.max_loop_errors,
            failover_grace_s=spec.failover_grace_s)
        self.member = _mb.MembershipClient(
            "127.0.0.1", spec.port, table_id=spec.membership_table,
            slot=spec.slot, n_slots=spec.n_slots)
        self._stop = threading.Event()
        self._events: queue.Queue = queue.Queue()
        self._migrated: set = set()   # rids handed to a peer (no event)
        self._pending_drain = None    # (xfer_id, pairs) awaiting commit
        self._in = van.BlobChannel("127.0.0.1", spec.port, spec.submit_ch)
        self._out = van.BlobChannel("127.0.0.1", spec.port, spec.event_ch)
        self.member.join()
        self._threads = [
            threading.Thread(target=self._beat_loop, daemon=True),
            threading.Thread(target=self._event_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()

    # ---- outbound ----
    def _emit(self, ev: dict) -> None:
        self._events.put(ev)

    def _event_loop(self) -> None:
        seq = 1
        while not self._stop.is_set():
            try:
                ev = self._events.get(timeout=0.1)
            except queue.Empty:
                continue
            payload = json.dumps(ev).encode()
            while not self._stop.is_set():
                try:
                    # idempotent same-seq resend: a timeout retries the
                    # SAME slot until the controller drains it.
                    # ConnectionError covers a netem-partitioned egress
                    # (NetemDrop): a one-way-partitioned member must
                    # QUEUE its completions and flush them at heal, not
                    # lose its event thread to the partition
                    self._out.put(payload, seq, timeout_s=2.0)
                    seq += 1
                    break
                except (TimeoutError, ConnectionError, RuntimeError):
                    time.sleep(0.05)

    def _beat_loop(self) -> None:
        period = max(self.spec.hb_ms, 10) / 1000.0
        while not self._stop.wait(period):
            try:
                self.member.heartbeat(
                    load=float(self.scheduler.load),
                    healthy=self.server.healthy)
            except Exception:
                # a transiently unreachable van must not kill the beat
                # thread — silence IS the loss signal, so keep trying
                time.sleep(period)

    def _watch(self, req) -> None:
        """Report the request's terminal state to the controller once it
        resolves — unless it migrated away (the adopter reports it)."""
        def run():
            req.done.wait()
            if req.status == "migrated" or req.rid in self._migrated:
                return
            self._emit({"type": "done", "rid": int(req.rid),
                        "status": req.status or "ok",
                        "tokens": [int(t) for t in req.tokens],
                        "ttft_s": req.ttft_s})
        threading.Thread(target=run, daemon=True).start()

    # ---- command dispatch (single reader: ordering is the protocol) ----
    def run(self) -> None:
        seq = 1
        while not self._stop.is_set():
            try:
                raw = self._in.get(seq, timeout_s=0.25)
            except (TimeoutError, ConnectionError):
                continue  # idle poll / netem-partitioned ingress: the
                # command loop outlives a transiently unreachable wire
            except RuntimeError:
                break  # van gone under us
            seq += 1
            try:
                msg = json.loads(raw)
                if not self._dispatch(msg):
                    break
            except Exception:
                traceback.print_exc()  # one bad command must not kill
                # the member — the controller's lease would misread a
                # parse error as a death
        self.close()

    def _dispatch(self, msg: dict) -> bool:
        from hetu_tpu.serve.scheduler import Request
        cmd = msg.get("cmd")
        if cmd == "submit":
            req = Request(prompt=[int(t) for t in msg["prompt"]],
                          max_tokens=int(msg.get("max_tokens", 16)),
                          eos_id=msg.get("eos_id"),
                          timeout_s=float(msg.get(
                              "timeout_s", self.spec.request_timeout_s)))
            req.rid = int(msg["rid"])  # controller-global id: completion
            # events and cross-process drains correlate on it
            self._watch(req)
            self.scheduler.submit(req)
        elif cmd == "recv_migration":
            self._recv_migration(int(msg["ch"]), int(msg["xfer"]),
                                 float(msg.get("timeout_s", 30.0)))
        elif cmd == "drain":
            self._drain(int(msg["ch"]), int(msg["xfer"]),
                        str(msg.get("codec", "none")),
                        float(msg.get("timeout_s", 30.0)))
        elif cmd == "drain_commit":
            self._drain_commit(int(msg["xfer"]), leave=bool(msg.get("exit")))
            if msg.get("exit"):
                return False
        elif cmd == "drain_abort":
            self._drain_abort(int(msg["xfer"]))
        elif cmd == "netem":
            self._apply_netem(msg)
        elif cmd == "shutdown":
            return False
        return True

    def _apply_netem(self, msg: dict) -> None:
        """Install (or clear) a link policy on this member's van wire.
        The policy usually carries ``duration_s`` so a PARTITION heals
        itself — a heal command could never cross the very link it is
        supposed to heal."""
        from hetu_tpu.ps.netem import LinkPolicy
        direction = str(msg.get("direction", "both"))
        pol = msg.get("policy")
        if pol is None:
            self.netem.clear_link(direction=direction)
        else:
            self.netem.set_link(LinkPolicy.from_dict(pol),
                                direction=direction)

    # ---- migration (two-phase, source side holds until commit) ----
    def _drain(self, ch_id: int, xfer: int, codec: str,
               timeout_s: float) -> None:
        pairs = None
        try:
            payload, pairs = _migrate.export_payload(self.scheduler,
                                                     codec=codec)
            tx = self._van.BlobChannel("127.0.0.1", self.spec.port, ch_id)
            try:
                _migrate.send_payload(tx, payload, timeout_s=timeout_s)
            finally:
                tx.close()
        except Exception as e:
            traceback.print_exc()
            if pairs is not None:
                try:
                    self.scheduler.adopt_inflight(pairs)  # resume serving
                except Exception:
                    traceback.print_exc()
            self._emit({"type": "drain_failed", "xfer": xfer,
                        "error": repr(e)})
            return
        self._pending_drain = (xfer, pairs)
        self._emit({"type": "drained", "xfer": xfer, "n": len(pairs)})

    def _drain_commit(self, xfer: int, *, leave: bool = True) -> None:
        from hetu_tpu.serve.scheduler import finish_request
        if self._pending_drain is None or self._pending_drain[0] != xfer:
            return
        _, pairs = self._pending_drain
        self._pending_drain = None
        for req, _slot in pairs:
            # resolve locally as 'migrated' so the watcher stays silent —
            # the ADOPTER owns the client-visible completion now
            self._migrated.add(req.rid)
            finish_request(req, "migrated", None)
        _migrate.release_exported(self.scheduler, pairs)
        if leave:
            try:
                self.member.leave()  # planned exit: never grieved
            except Exception:
                pass

    def _drain_abort(self, xfer: int) -> None:
        if self._pending_drain is None or self._pending_drain[0] != xfer:
            return
        _, pairs = self._pending_drain
        self._pending_drain = None
        try:
            self.scheduler.adopt_inflight(pairs)  # back in service
        except Exception:
            traceback.print_exc()

    def _recv_migration(self, ch_id: int, xfer: int,
                        timeout_s: float) -> None:
        # ack FIRST: the controller must not start the source's send
        # before this member is committed to receiving
        self._emit({"type": "mig_ready", "xfer": xfer})
        try:
            rx = self._van.BlobChannel("127.0.0.1", self.spec.port, ch_id)
            try:
                got = _migrate.recv_payload(rx, timeout_s=timeout_s)
            finally:
                rx.close()
            reqs, slot_map = _migrate.adopt_payload(self.scheduler, got)
        except Exception as e:
            traceback.print_exc()
            self._emit({"type": "adopt_failed", "xfer": xfer,
                        "error": repr(e)})
            return
        for req in reqs:
            self._watch(req)
        self._emit({"type": "adopted", "xfer": xfer, "n": len(reqs),
                    "slots": len(slot_map)})

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self.member.leave()
        except Exception:
            pass
        try:
            self.server.close(5.0)
        except Exception:
            traceback.print_exc()
        for ch in (self._in, self._out):
            try:
                ch.close()
            except Exception:
                pass
        self.member.close()
        self.netem.uninstall()


def member_main(config_path: str) -> int:
    """Entry point for a spawned member process: build the harness,
    announce READY (the spawner's handshake), serve until told to stop."""
    spec = MemberSpec.from_json(open(config_path).read())
    harness = MemberHarness(spec)
    print("READY", spec.slot, flush=True)
    harness.run()
    return 0


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class PoolRequest:
    """Controller-side request record: the original message (the
    failover resubmission source), current route, and the waiter's
    completion event.  Response dict shape matches the in-process
    pool's ``generate``."""

    __slots__ = ("rid", "msg", "member", "retries", "tokens", "status",
                 "ttft_s", "done")

    def __init__(self, rid: int, msg: dict):
        self.rid = rid
        self.msg = msg
        self.member: Optional[int] = None
        self.retries = 0
        self.tokens: list = []
        self.status: Optional[str] = None
        self.ttft_s = None
        self.done = threading.Event()


class CrossProcessServingPool:
    """Controller over N serving-member PROCESSES on one van.

    Construction starts the van, creates the membership blackboard,
    spawns ``n_members`` member processes (each builds the same seeded
    model), and waits for them to join.  ``generate``/``submit`` route
    over the wire; the poll thread runs the lease state machine and the
    failover/suspect handling; ``drain_member`` runs the two-phase
    cross-process KV migration.  ``procs`` holds the live ``Popen``
    handles — exactly what the chaos harness's ``member_kill`` /
    ``member_suspend`` faults target.
    """

    def __init__(self, n_members: int = 2, *, workdir, model: dict = None,
                 port: int = 0, own_van: bool = True,
                 hb_ms: int = 80, lease_s: float = 0.6,
                 suspect_grace_s: float = 0.5,
                 poll_s: float = 0.05,
                 request_timeout_s: float = 60.0,
                 max_retries: int = 3,
                 migrate_codec: str = "none",
                 membership_table: Optional[int] = None,
                 metrics: Optional[ServeMetrics] = None,
                 member_env: Optional[dict] = None,
                 spawn_timeout_s: float = 120.0,
                 shed: bool = False, shed_headroom: float = 1.0,
                 rtt_degraded_x: float = 5.0,
                 start_poll: bool = True):
        from hetu_tpu.ps import van
        if n_members < 1:
            raise ValueError("a serving pool needs at least one member")
        migrate_codec = _migrate.check_codec(migrate_codec)
        self._van = van
        self._own_van = own_van
        if own_van:
            self.port = van.serve(port)
        else:
            if not port:
                raise ValueError("own_van=False needs the running van's port")
            self.port = port
        self.workdir = workdir
        self.model = {**DEFAULT_MODEL, **(model or {})}
        self.n_members = int(n_members)
        self.hb_ms = int(hb_ms)
        self.request_timeout_s = float(request_timeout_s)
        self.max_retries = int(max_retries)
        self.migrate_codec = migrate_codec
        # fresh by default: the native table registry outlives van.stop(),
        # and two pools in one process must not share a blackboard
        self._membership_table = int(membership_table) \
            if membership_table is not None else _mb.fresh_table_id()
        self._spawn_timeout_s = float(spawn_timeout_s)
        # e.g. {"JAX_PLATFORMS": "cpu"} — a bench on an accelerator box
        # keeps member processes off the chip the controller holds
        self._member_env = dict(member_env) if member_env else None
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self._lock = threading.RLock()
        self._poll_lock = threading.Lock()
        self._rids = itertools.count(1)
        self._ctrl_ids = itertools.count(0)  # fresh channels per process
        self._requests: dict = {}       # rid -> PoolRequest
        self._inflight: dict = {}       # slot -> outstanding count
        self._draining: set = set()
        self._quarantined: set = set()  # engine-dead / failed-over slots
        self._suspect_t0: dict = {}     # slot -> trace ts of suspicion
        # per-link health, measured from this controller's OWN control
        # sends (every submit/drain command is a timed blob put): the
        # routing penalty that keeps traffic off a member behind a
        # degraded link BEFORE its lease ever wobbles
        self._shed = bool(shed)
        self._shed_headroom = float(shed_headroom)
        self._rtt_degraded_x = float(rtt_degraded_x)
        self._rtt: dict = {}            # slot -> EWMA send seconds
        self._degraded_t0: dict = {}    # slot -> trace ts of degrade
        self._xfers: dict = {}          # xfer id -> {"evt", "events"}
        self._out: dict = {}            # slot -> (channel, lock, [seq])
        self._listeners: dict = {}      # slot -> (thread, stop)
        self.procs: list = [None] * self.n_members
        self._stop = threading.Event()
        try:
            self._bb = _mb.create_blackboard(
                "127.0.0.1", self.port, table_id=self._membership_table,
                n_slots=self.n_members)
            self.svc = _mb.MembershipService(
                self._bb, self.n_members, lease_s=lease_s,
                suspect_grace_s=suspect_grace_s)
            for slot in range(self.n_members):
                self._spawn(slot)
            self._wait_joined(range(self.n_members))
        except Exception:
            self.close()
            raise
        self._poll_thread = None
        if start_poll:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, args=(float(poll_s),), daemon=True)
            self._poll_thread.start()

    # ---- spawning ----
    def _spawn(self, slot: int) -> None:
        from hetu_tpu.resilience.shardproc import spawn_module
        cid = next(self._ctrl_ids)
        spec = MemberSpec(
            port=self.port, slot=slot, n_slots=self.n_members,
            submit_ch=CONTROL_CHANNEL_BASE + 2 * cid,
            event_ch=CONTROL_CHANNEL_BASE + 2 * cid + 1,
            membership_table=self._membership_table, hb_ms=self.hb_ms,
            request_timeout_s=self.request_timeout_s, model=self.model,
            shed=self._shed, shed_headroom=self._shed_headroom)
        from pathlib import Path
        cfg = Path(self.workdir) / f"member_{slot}_{cid}.json"
        cfg.write_text(spec.to_json())
        proc = spawn_module(self.workdir, f"member_{slot}_{cid}",
                            "hetu_tpu.serve.crosshost", [str(cfg)],
                            extra_env=self._member_env,
                            timeout_s=self._spawn_timeout_s)
        self.procs[slot] = proc
        ch = self._van.BlobChannel("127.0.0.1", self.port, spec.submit_ch)
        with self._lock:
            old = self._out.get(slot)
            self._out[slot] = (ch, threading.Lock(), [1])
            self._inflight[slot] = 0
        if old is not None:  # a revived slot's previous control channel
            try:
                old[0].close()
            except Exception:
                pass
        self._start_listener(slot, spec.event_ch)

    def _start_listener(self, slot: int, event_ch: int) -> None:
        old = self._listeners.get(slot)
        if old is not None:
            old[1].set()
        stop = threading.Event()
        t = threading.Thread(target=self._event_loop,
                             args=(slot, event_ch, stop), daemon=True)
        self._listeners[slot] = (t, stop)
        t.start()

    def _wait_joined(self, slots, timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self._spawn_timeout_s)
        want = set(int(s) for s in slots)
        while time.monotonic() < deadline:
            self.poll()
            if want <= set(self.svc.present_slots()):
                return
            time.sleep(0.05)
        raise TimeoutError(f"members {sorted(want)} did not join within "
                           f"the spawn window")

    # ---- wire helpers ----
    def _send(self, slot: int, msg: dict, *, timeout_s: float = 2.0,
              attempts: int = 2) -> None:
        """One ordered control send with bounded retry: same-seq blob
        resend is idempotent, so a transport wobble retries safely; a
        member that stays unreadable (suspended/dead) surfaces as the
        TimeoutError the router treats as 'pick someone else'."""
        ent = self._out.get(slot)
        if ent is None:
            raise ConnectionError(f"member {slot} has no control channel")
        ch, lock, seq = ent
        payload = json.dumps(msg).encode()
        t0 = time.monotonic()
        try:
            with lock:
                _mb.control_rpc(
                    lambda: ch.put(payload, seq[0], timeout_s=timeout_s),
                    attempts=attempts, base_s=0.05,
                    op=f"send[{msg.get('cmd')}]", link=f"ctrl->m{slot}",
                    is_transient=lambda e: isinstance(
                        e, (TimeoutError, ConnectionError, RuntimeError)))
                seq[0] += 1
        finally:
            # every control send doubles as a link probe — failures
            # included (a send that burned its whole retry budget is the
            # strongest degradation signal there is)
            self._observe_rtt(slot, time.monotonic() - t0)

    def _observe_rtt(self, slot: int, rtt_s: float) -> None:
        prev = self._rtt.get(slot)
        ewma = rtt_s if prev is None else 0.7 * prev + 0.3 * rtt_s
        self._rtt[slot] = ewma
        base = self._rtt_floor()
        if base is None:
            return
        if ewma > self._rtt_degraded_x * base:
            if slot not in self._degraded_t0:
                # the degrade window opens: recorded retroactively as a
                # serve.link_degraded span when the link recovers — the
                # recovery event RECOVERY_FOR pairs with fault.netem_degrade
                self._degraded_t0[slot] = trace.now_us()
                self.metrics.inc("links_degraded")
        elif ewma < 2.0 * base:
            t0d = self._degraded_t0.pop(slot, None)
            if t0d is not None:
                trace.complete("serve.link_degraded", t0d,
                               {"member": int(slot),
                                "rtt_ms": round(ewma * 1e3, 3)},
                               cat="serve")
                self.metrics.inc("links_recovered")

    def _rtt_floor(self) -> Optional[float]:
        """The healthiest observed link (EWMA floor) — the baseline a
        degraded link is judged against.  None until measured.  Floored
        at 2ms: on loopback the true RTT is microseconds and any GIL
        hiccup would read as a 5x 'degradation' — a link must be
        MILLISECONDS worse than its peers before it is called gray."""
        if not self._rtt:
            return None
        return max(min(self._rtt.values()), 2e-3)

    def _rtt_penalty(self, slot: int) -> float:
        """Routing penalty in 'equivalent in-flight requests': each
        multiple of the baseline RTT costs like one extra outstanding
        request, capped so a wedged link ranks worst but stays finite
        (a suspect lease, not this penalty, takes it out entirely)."""
        rtt = self._rtt.get(slot)
        base = self._rtt_floor()
        if rtt is None or base is None:
            return 0.0
        return min(max(rtt / base - 1.0, 0.0), 16.0)

    def _event_loop(self, slot: int, event_ch: int,
                    stop: threading.Event) -> None:
        ch = self._van.BlobChannel("127.0.0.1", self.port, event_ch)
        seq = 1
        try:
            while not (stop.is_set() or self._stop.is_set()):
                try:
                    raw = ch.get(seq, timeout_s=0.25)
                except (TimeoutError, ConnectionError):
                    continue
                except RuntimeError:
                    if self._stop.is_set():
                        break
                    time.sleep(0.1)
                    continue
                seq += 1
                try:
                    ev = json.loads(raw)
                except (ValueError, TypeError):
                    continue
                try:
                    self._dispatch_event(slot, ev)
                except Exception:
                    traceback.print_exc()
        finally:
            ch.close()

    def _dispatch_event(self, slot: int, ev: dict) -> None:
        kind = ev.get("type")
        if kind == "done":
            self._on_done(slot, ev)
            return
        xfer = self._xfers.get(int(ev.get("xfer", -1)))
        if xfer is not None:
            xfer["events"][kind] = ev
            xfer["evt"].set()

    def _on_done(self, slot: int, ev: dict) -> None:
        req = self._requests.get(int(ev.get("rid", -1)))
        if req is None or req.done.is_set():
            return  # late duplicate from a failed-over member: first wins
        status = ev.get("status", "error")
        if status in ("error", "shutdown"):
            with self._lock:
                stale = req.member != slot
            if stale:
                return  # an old owner's drain echo; the new owner decides
            if req.retries < self.max_retries:
                # the member failed the request without serving it (engine
                # death drain, poisoned admission): fold re-prefill on a
                # peer = resubmit the original record elsewhere
                req.retries += 1
                self.metrics.inc("requests_rerouted")
                self._route(req, exclude={slot})
                return
        self._resolve(req, status, tokens=ev.get("tokens", ()),
                      ttft_s=ev.get("ttft_s"))

    def _resolve(self, req: PoolRequest, status: str, *, tokens=(),
                 ttft_s=None) -> None:
        with self._lock:
            if req.done.is_set():
                return
            if req.member is not None:
                self._inflight[req.member] = max(
                    self._inflight.get(req.member, 1) - 1, 0)
            req.tokens = [int(t) for t in tokens]
            req.status = status
            req.ttft_s = ttft_s
            req.done.set()
            # evict: a long-lived controller must not retain every
            # completed request forever (a late duplicate completion
            # for an evicted rid is simply ignored by _on_done)
            self._requests.pop(req.rid, None)
        self.metrics.inc(f"requests_{status}")

    # ---- routing ----
    def _routable(self, exclude=()) -> list:
        alive = set(self.svc.alive_slots())
        with self._lock:
            return [s for s in alive
                    if s not in exclude and s not in self._draining
                    and s not in self._quarantined
                    and self.svc.state_of(s).healthy]

    def _route(self, req: PoolRequest, *, exclude=None) -> None:
        exclude = set(exclude or ())
        while True:
            with self._lock:
                cands = self._routable(exclude)
                if not cands:
                    break
                # least-loaded, where "load" counts both outstanding
                # requests AND the link penalty: a member behind a
                # degraded link serves fewer requests per unit time, so
                # its slower wire is priced like extra queue depth
                slot = min(cands,
                           key=lambda s: self._inflight.get(s, 0) +
                           self._rtt_penalty(s))
                prev = req.member
                req.member = slot
                self._inflight[slot] = self._inflight.get(slot, 0) + 1
                if prev is not None:
                    self._inflight[prev] = max(
                        self._inflight.get(prev, 1) - 1, 0)
            try:
                self._send(slot, {"cmd": "submit", "rid": req.rid,
                                  **req.msg})
                return
            except Exception:
                with self._lock:
                    self._inflight[slot] = max(
                        self._inflight.get(slot, 1) - 1, 0)
                    req.member = None
                exclude.add(slot)
        self._resolve(req, "error")
        self.metrics.inc("requests_rejected_no_member")

    def submit(self, prompt, *, max_tokens: int = 16, eos_id=None,
               timeout_s: Optional[float] = None) -> PoolRequest:
        rid = next(self._rids)
        msg = {"prompt": [int(t) for t in prompt],
               "max_tokens": int(max_tokens), "eos_id": eos_id,
               "timeout_s": float(timeout_s if timeout_s is not None
                                  else self.request_timeout_s)}
        req = PoolRequest(rid, msg)
        with self._lock:
            self._requests[rid] = req
        self.metrics.inc("pool_requests")
        self._route(req)
        return req

    def generate(self, prompt, *, max_tokens: int = 16, eos_id=None,
                 timeout_s: Optional[float] = None) -> dict:
        req = self.submit(prompt, max_tokens=max_tokens, eos_id=eos_id,
                          timeout_s=timeout_s)
        # generous backstop over the serving deadline: a failover or a
        # suspended-then-resumed member must not strand the waiter
        if not req.done.wait(timeout=req.msg["timeout_s"] + 30.0):
            self._resolve(req, "timeout")
        return {"id": req.rid, "status": req.status or "ok",
                "tokens": list(req.tokens), "ttft_s": req.ttft_s}

    # ---- membership / failover ----
    def _poll_loop(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            try:
                self.poll()
            except Exception:
                traceback.print_exc()  # the poll must survive anything

    def poll(self) -> int:
        """One membership sweep; returns how many members failed over.
        Serialized by ``_poll_lock``: the background poll thread and
        direct callers (``revive_member``'s join wait, tests) share one
        lease state machine."""
        with self._poll_lock:
            return self._poll_locked()

    def _poll_locked(self) -> int:
        events = self.svc.poll()
        n = 0
        for kind, slot in events:
            if kind == "suspect":
                self._suspect_t0[slot] = trace.now_us()
                self.metrics.inc("members_suspected")
            elif kind == "clear":
                t0 = self._suspect_t0.pop(slot, None)
                if t0 is not None:
                    # the retroactive recovery span: the partition HEALED
                    # — no loss, no rejoin, just a measured outage window
                    trace.complete("serve.member_suspect", t0,
                                   {"member": int(slot)}, cat="serve")
                self.metrics.inc("members_suspect_cleared")
            elif kind == "lost":
                self._suspect_t0.pop(slot, None)
                self.failover(slot)
                n += 1
            elif kind in ("join", "rejoin"):
                with self._lock:
                    self._quarantined.discard(slot)
                    self._draining.discard(slot)
                if kind == "rejoin":
                    self.metrics.inc("members_rejoined")
            elif kind == "left":
                with self._lock:
                    self._draining.discard(slot)
        # a live process whose ENGINE died reports healthy=0 in its
        # heartbeat: its queue drains 'error' member-side (each request
        # re-routes via its completion event), but stop routing NEW work
        # at it immediately
        for slot in self.svc.alive_slots():
            if not self.svc.state_of(slot).healthy and \
                    slot not in self._quarantined:
                with self._lock:
                    self._quarantined.add(slot)
                self.metrics.inc("members_engine_dead")
        # active link probe for DEGRADED slots: routing steers traffic
        # away from them, so without a probe no send would ever observe
        # the recovery and the degrade window would never close.  The
        # ping is a no-op command; its put waits on the member's ack of
        # the previous frame, so it measures the member's real read path
        for slot in list(self._degraded_t0):
            if self.svc.state_of(slot).state in ("alive", "suspect"):
                try:
                    self._send(slot, {"cmd": "ping"}, timeout_s=0.5,
                               attempts=1)
                except Exception:
                    pass  # the failure itself updated the RTT EWMA
        return n

    # ---- network-plane chaos (ps/netem.py over the command wire) ----
    def apply_net_fault(self, kind: str, member_idx: int,
                        duration_s: float = 1.0) -> None:
        """Route an injected network fault at a member by index:
        ``netem_partition`` = one-way EGRESS partition (the member's
        beats and completions black-hole; it still hears us — the
        asymmetric case), ``netem_degrade`` = gray link both ways
        (loss + latency + bandwidth cap).  Policies carry
        ``duration_s`` and heal themselves member-side — a heal
        command could not cross a cut link."""
        slot = int(member_idx) % self.n_members
        if kind == "netem_partition":
            msg = {"cmd": "netem", "direction": "egress",
                   "policy": {"partition": True,
                              "duration_s": float(duration_s)}}
        elif kind == "netem_degrade":
            msg = {"cmd": "netem", "direction": "both",
                   "policy": {"latency_s": 0.05, "jitter_s": 0.05,
                              "drop_p": 0.05, "rate_mbps": 50.0,
                              "duration_s": float(duration_s)}}
        else:
            raise ValueError(f"unknown net fault kind {kind!r}")
        self.metrics.inc(f"{kind}s_applied")
        self._send(slot, msg)

    def run_net_events(self, events) -> None:
        """Apply events drained from ``FaultInjector.pop_net_events()``
        — prefer draining with ``kinds=("netem_partition",
        "netem_degrade")`` so a mixed schedule's ``straggler`` events
        stay queued for the training supervisor that owns them; any
        straggler event handed here anyway is left untouched."""
        for kind, idx, duration_s in events:
            if kind == "straggler":
                continue
            self.apply_net_fault(kind, idx, duration_s)

    def failover(self, slot: int) -> int:
        """The member process is gone (lease expired past the suspect
        grace): every outstanding request re-routes to a survivor, which
        re-prefills from the original prompt — the cross-process fold
        (the dead process took the emitted tokens with it, and greedy
        decode regenerates them exactly)."""
        slot = int(slot)
        with self._lock:
            if slot in self._quarantined:
                return 0  # already failed over (engine-dead path)
            self._quarantined.add(slot)
            pending = [r for r in self._requests.values()
                       if r.member == slot and not r.done.is_set()]
        with trace.span("serve.failover", cat="serve") as sp:
            sp.set("member", slot)
            for req in pending:
                self._route(req, exclude={slot})
            sp.set("requests", len(pending))
        p = self.procs[slot]
        if p is not None and p.poll() is None:
            pass  # suspended-past-grace: declared lost but still exists;
            # revive_member replaces it (and reaps) if the operator asks
        self.metrics.inc("pool_failovers")
        self.metrics.inc("requests_failed_over", len(pending))
        return len(pending)

    # ---- planned drain (cross-process live migration) ----
    def drain_member(self, slot: int, *, codec: Optional[str] = None,
                     close: bool = True, target: Optional[int] = None,
                     timeout_s: float = 60.0) -> int:
        """Two-phase planned drain: the source process exports its live
        KV slots + request records over the migrate wire, the target
        adopts, and only the target's confirmation releases the source
        (which then leaves cleanly and, with ``close``, exits).  Any
        failure before the commit aborts back to a still-serving source.
        Returns the number of requests migrated.

        ``codec`` overrides the pool default for THIS drain (a
        preemption-deadline drain picks "int8"; routine drains stay
        lossless)."""
        slot = int(slot)
        codec = self.migrate_codec if codec is None \
            else _migrate.check_codec(codec)
        if codec == "auto":
            codec = self._resolve_auto_codec(slot)
        with self._lock:
            if slot in self._draining or slot in self._quarantined:
                return 0
            self._draining.add(slot)
        xid = next(_xfer_ids)
        xfer = {"evt": threading.Event(), "events": {}}
        self._xfers[xid] = xfer
        try:
            with trace.span("serve.migrate", cat="serve") as sp:
                sp.set("member", slot)
                if target is None:
                    cands = self._routable({slot})
                    if not cands:
                        raise RuntimeError(
                            f"no surviving peer to drain member {slot} "
                            f"into")
                    target = min(cands,
                                 key=lambda s: self._inflight.get(s, 0))
                sp.set("target", int(target))
                ch = CROSSHOST_MIGRATE_BASE + next(_MIG_SEQ)
                self._send(target, {"cmd": "recv_migration", "ch": ch,
                                    "xfer": xid, "timeout_s": timeout_s})
                self._await_xfer(xfer, ("mig_ready",), timeout_s)
                self._send(slot, {"cmd": "drain", "ch": ch, "xfer": xid,
                                  "codec": codec, "timeout_s": timeout_s})
                ev = self._await_xfer(
                    xfer, ("adopted", "adopt_failed", "drain_failed"),
                    timeout_s)
                if ev.get("type") != "adopted":
                    # roll the source back before surfacing the failure
                    try:
                        self._send(slot, {"cmd": "drain_abort",
                                          "xfer": xid})
                    except Exception:
                        traceback.print_exc()
                    raise RuntimeError(
                        f"cross-process drain failed: {ev.get('error', ev)}")
                n = int(ev.get("n", 0))
                # evidence for callers/tests: how many LIVE KV slots the
                # peer adopted (mid-decode continuations, zero re-prefill)
                self.last_drain = {"source": slot, "target": int(target),
                                   "requests": n,
                                   "slots": int(ev.get("slots", 0)),
                                   "codec": codec}
                # the hand-off is real: re-home the outstanding rids so
                # the target's completion events find their requests
                with self._lock:
                    moved = [r for r in self._requests.values()
                             if r.member == slot and not r.done.is_set()]
                    for r in moved:
                        r.member = int(target)
                    self._inflight[int(target)] = \
                        self._inflight.get(int(target), 0) + len(moved)
                    self._inflight[slot] = 0
                self._send(slot, {"cmd": "drain_commit", "xfer": xid,
                                  "exit": bool(close)})
                sp.set("requests", n)
        except Exception:
            with self._lock:
                self._draining.discard(slot)
            raise
        finally:
            self._xfers.pop(xid, None)
        if close:
            p = self.procs[slot]
            if p is not None:
                try:
                    p.wait(timeout=10.0)
                except Exception:
                    p.kill()
        else:
            # the emptied member keeps serving (it never left the
            # blackboard): put it back in the routing set now
            with self._lock:
                self._draining.discard(slot)
        self.metrics.inc("pool_migrations")
        self.metrics.inc("requests_migrated", n)
        return n

    def _resolve_auto_codec(self, slot: int) -> str:
        """Controller-side ``codec="auto"`` resolution (the member's
        live token lengths are across a process boundary, so the
        payload is ESTIMATED from the model spec and the slot's
        outstanding requests — each assumed halfway through
        ``max_len``); the link rate is this process's best evidence
        (:func:`hetu_tpu.serve.migrate.known_link_mbps`: a netem cap,
        else a previously observed BULK transfer — never the tiny
        ack-paced control frames, whose bytes/latency ratio reads
        orders of magnitude below the real wire).  No evidence resolves
        to "none": on an unmeasured link, compression is a bet, not a
        measurement."""
        m = self.model
        head_dim = int(m["hidden_size"]) // int(m["num_heads"])
        per_tok = 2 * int(m["num_heads"]) * head_dim * 4  # f32 K+V
        tokens = max(self._inflight.get(slot, 0), 1) * \
            int(m["max_len"]) // 2
        payload = tokens * int(m["num_layers"]) * per_tok
        return _migrate.pick_codec(_migrate.known_link_mbps(),
                                   payload, "float32")

    @staticmethod
    def _await_xfer(xfer: dict, kinds, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for k in kinds:
                ev = xfer["events"].get(k)
                if ev is not None:
                    return ev
            xfer["evt"].wait(0.05)
            xfer["evt"].clear()
        raise TimeoutError(f"no {kinds} event within {timeout_s}s")

    # ---- membership operations ----
    def revive_member(self, slot: int) -> None:
        """Replace a lost/drained member with a FRESH process on the
        same slot (new incarnation, new control channels); it rejoins
        routing once its first heartbeat lands."""
        slot = int(slot)
        p = self.procs[slot]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self._spawn(slot)
        self._wait_joined([slot])
        with self._lock:
            self._quarantined.discard(slot)
            self._draining.discard(slot)
        self.metrics.inc("members_revived")

    # ---- lifecycle ----
    def close(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = getattr(self, "_poll_thread", None)
        if t is not None:
            t.join(timeout_s)
        for slot in range(self.n_members):
            try:
                self._send(slot, {"cmd": "shutdown"}, timeout_s=0.5,
                           attempts=1)
            except Exception:
                pass
        for _, (th, stop) in list(self._listeners.items()):
            stop.set()
        deadline = time.monotonic() + 5.0
        for p in self.procs:
            if p is None:
                continue
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except Exception:
                p.kill()
                p.wait()
        for slot, ent in list(self._out.items()):
            try:
                ent[0].close()
            except Exception:
                pass
        bb = getattr(self, "_bb", None)
        if bb is not None:
            bb.close()
        if self._own_van:
            self._van.stop()


if __name__ == "__main__":
    import sys
    sys.exit(member_main(sys.argv[1]))
