"""Cross-process serving pool: members are real OS processes.

PR 5's :class:`~hetu_tpu.serve.pool.ServingPool` proved the HA
machinery — health routing, live KV drain, fold re-prefill failover —
but its members share one Python process, so "member death" was a kill
switch, not a kill.  This module promotes the pool across the process
boundary: each member is a SEPARATE process running a listener-less
:class:`~hetu_tpu.serve.server.InferenceServer` (engine loop + requeue
machinery, ``own_van=False``) attached to the controller's van, and the
control plane crosses the wire:

* **membership** — members join and heartbeat through the van
  blackboard (:mod:`hetu_tpu.ps.membership`); the controller's lease
  state machine (alive → suspect → lost) replaces in-process
  ``server.healthy`` polling.  A SIGSTOPped member goes *suspect*
  (unroutable, state presumed intact) and CLEARS when its beats resume
  — never double-counted as a loss plus a rejoin;
* **requests** — the controller routes each accepted request to the
  least-loaded alive member over a per-process submit channel and
  resolves it from the member's completion events; member death
  (SIGKILL → lease expiry) re-routes every outstanding request to a
  survivor, which re-prefills from the original prompt — greedy decode
  makes the re-served tokens exactly the tokens the dead member would
  have produced;
* **drain** — a planned preemption ships the member's live KV slots AND
  its in-flight request records to a peer process over the existing
  chunked-CRC migrate wire (:func:`hetu_tpu.serve.migrate.
  export_payload` / :func:`~hetu_tpu.serve.migrate.adopt_payload`),
  two-phase: the source holds its export until the target confirms
  adoption, so a failed transfer rolls back to a still-serving source.
  The adopting process continues mid-decode sequences token-for-token
  with zero re-prefill.

Channel topology on the ONE shared van: each member process gets a
fresh (submit, event) blob-channel pair allocated by the controller
(never reused across member incarnations — blob seqs are per-channel
and a revived process must start clean), migration transfers draw ids
from their own base (disjoint from the in-process pool's
``MIGRATE_CHANNEL_BASE`` — several pools can share one van), and the
membership blackboard is a small f32 table.  Recovery spans mirror the
in-process pool (``serve.migrate`` / ``serve.failover``) plus the new
retroactive ``serve.member_suspect`` for a partition that healed.

**Controller death is just another fault kind.**  The controller holds
a lease of its own (the blackboard's controller row — incarnation
fence + beat), journals every piece of RAM-only state (rid→member
ownership, retry budgets, half-open drains, per-slot channel bases) to
a :class:`~hetu_tpu.ps.membership.ControllerLedger` on the van, and
keys every command channel by its incarnation.  A SIGKILLed controller
therefore loses nothing durable: a new incarnation
(:meth:`CrossProcessServingPool.takeover`) claims the fence, reads
blackboard + ledger, re-adopts the still-serving member processes via
their lease rows, aborts half-open drains back to a serving source,
and resolves every accepted request (members re-announce their
completion records when they rebind to the new incarnation's
channels — the ``ctrl.takeover`` span measures the whole hand-off).
A SIGSTOPped controller that wakes after the takeover is FENCED:
members ignore its stale-incarnation control rows and commands, and
its own read-before-write checks raise
:class:`~hetu_tpu.ps.membership.ControllerFenced` before it can touch
the fleet.  This requires the van (the durable tier) to outlive the
controller — production deployments and the chaos tests run it as its
own process (``resilience/shardproc.spawn_shard_server``) and build
the pool with ``own_van=False``.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import sys
import signal as _signal
import threading
import time
import traceback
from collections import OrderedDict
from dataclasses import asdict, dataclass, field
from typing import Optional

import numpy as np

from hetu_tpu.ps import membership as _mb
from hetu_tpu.serve import migrate as _migrate
from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.serve.pool import _MIG_SEQ
from hetu_tpu.telemetry import trace

# controller-allocated control channels ('CHCT'); migration transfers get
# their own base ('MIG3'), disjoint from serve/pool.py's in-process base
# so a mixed deployment sharing one van cannot cross streams
CONTROL_CHANNEL_BASE = 0x43484354
CROSSHOST_MIGRATE_BASE = 0x4D494733

_xfer_ids = itertools.count(1)

# control channels are keyed by CONTROLLER incarnation: blob seqs are
# per-channel and a takeover cannot know the dead controller's
# positions, so each incarnation binds fresh channels — and a fenced
# zombie keeps writing to channels nobody reads
CTRL_CHAN_STRIDE = 1 << 20


def _fenced_chan(base: int, ctrl_inc: int) -> int:
    return int(base) + int(ctrl_inc) * CTRL_CHAN_STRIDE


def _fleet_event(name: str, rec: dict) -> None:
    """Structured fleet forensics: one instant on the process span
    stream (``membership.event`` / ``route.park`` / ``route.send_fail``
    — the fleet doctor and ``fleet_report.py`` read these), with the
    old ``HETU_DEBUG_FLEET`` stderr dump kept as a FORMATTER over the
    same record — the env var now picks a sink, it no longer decides
    whether the evidence exists."""
    trace.instant(name, rec, cat="fleet")
    if os.environ.get("HETU_DEBUG_FLEET"):
        kv = " ".join(f"{k}={v}" for k, v in rec.items())
        print(f"[fleet] {time.monotonic():.2f} {name} {kv}",
              file=sys.stderr, flush=True)


def seeded_prompts(n: int, seed: int = 0, *, vocab: int = 89,
                   max_len: int = 6) -> list:
    """Deterministic prompt set shared by the controller harness, the
    chaos tests, and ``bench.py ctrlchaos`` — same (n, seed) → same
    prompts in every process, so token-exactness is checkable across a
    controller death without shipping the prompts anywhere."""
    rng = np.random.default_rng((int(seed), 0xC7A0))
    out = []
    for _ in range(int(n)):
        k = int(rng.integers(2, max(int(max_len), 3)))
        out.append([int(t) for t in rng.integers(1, int(vocab), size=k)])
    return out


@dataclass
class MemberSpec:
    """Everything a member process needs to build its engine and find
    the control plane — JSON-serialized into the spawn config so the
    member re-derives the SAME model weights (deterministic seeded
    init) the controller and its peers hold."""

    port: int
    slot: int
    n_slots: int
    submit_ch: int
    event_ch: int
    membership_table: int = _mb.SERVE_MEMBERSHIP_TABLE
    hb_ms: int = 100
    request_timeout_s: float = 60.0
    max_loop_errors: int = 2
    failover_grace_s: float = 5.0
    model: dict = field(default_factory=dict)
    # overload shedding in the member's scheduler (serve/scheduler.py):
    # deadline-doomed submits resolve 'shed' instantly instead of
    # queueing into collapse
    shed: bool = False
    shed_headroom: float = 1.0
    # per-tenant SLO classes forwarded into the member scheduler
    # (serve/scheduler.py): {name: {"priority": int, "weight": float,
    # "ttft_slo_s": float|None}} — empty keeps pure FIFO admission
    slo_classes: dict = field(default_factory=dict)
    # netem link emulation applied at process start: {"seed": int,
    # "links": [[direction, policy_dict], ...]} — the static half; the
    # dynamic half arrives over the wire as a "netem" command
    netem: dict = field(default_factory=dict)
    # the controller-ledger table id, recorded here so a TAKEOVER can
    # find every durable control-plane id from any member's spawn
    # config on disk; members themselves never read the ledger.  The
    # ROW COUNT is geometry, not just capacity: DeltaLedger derives
    # base/delta region boundaries from it, so a takeover reading with
    # a different rows value would misparse the delta region — it must
    # ride the spawn config like the id does
    ledger_table: int = 0
    ledger_rows: int = 2048
    # fleet observability: non-empty = the member opens a crash-durable
    # span/metric stream (<trace_dir>/member_sN_pPID.trace.jsonl) at
    # startup — the flight recorder a SIGKILL cannot erase.  scrape_s
    # is recorded so a controller TAKEOVER restores the pool's scrape
    # cadence, not the constructor default
    trace_dir: str = ""
    scrape_s: float = 1.0
    # replicated durable tier: a ReplicaSpec dict ({"endpoints":
    # [[h,p],[h,p]], "epoch_table": id, ...}) — non-empty means the
    # member's blackboard/channel wire runs over the primary+backup van
    # pair and re-resolves to the promoted endpoint on primary death.
    # Recorded in the spawn config like every other durable id, so a
    # controller takeover finds the SAME pair.
    van: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "MemberSpec":
        return cls(**json.loads(s))


DEFAULT_MODEL = {
    "vocab_size": 97, "hidden_size": 64, "num_layers": 2, "num_heads": 4,
    "ffn_size": 128, "max_position": 64, "seed": 0,
    "num_slots": 4, "max_len": 48, "min_bucket": 8,
}


def build_engine(model_spec: dict):
    """Deterministic engine construction shared by member processes and
    in-test reference engines: same spec → same weights everywhere, the
    property that makes cross-process failover token-exact.

    ``{"engine": "paged"}`` in the spec builds a
    :class:`~hetu_tpu.serve.engine.PagedServeEngine` (page size via
    ``"page_size"``, pool size via ``"num_pages"``) instead of the slot
    engine — same weights, same wire; migration between the two is the
    cross-allocator path serve/migrate.py already supports."""
    import jax

    from hetu_tpu.models.gpt import GPTConfig, GPTModel
    from hetu_tpu.serve.engine import PagedServeEngine, ServeEngine
    spec = {**DEFAULT_MODEL, **(model_spec or {})}
    cfg = GPTConfig(
        vocab_size=int(spec["vocab_size"]),
        hidden_size=int(spec["hidden_size"]),
        num_layers=int(spec["num_layers"]),
        num_heads=int(spec["num_heads"]),
        ffn_size=int(spec["ffn_size"]),
        max_position=int(spec["max_position"]), dropout_rate=0.0)
    model = GPTModel(cfg)
    variables = model.init(jax.random.PRNGKey(int(spec["seed"])))
    if spec.get("engine") == "paged":
        num_pages = spec.get("num_pages")
        return model, variables, PagedServeEngine(
            model, variables, num_slots=int(spec["num_slots"]),
            max_len=int(spec["max_len"]),
            page_size=int(spec.get("page_size", 8)),
            num_pages=None if num_pages is None else int(num_pages),
            min_bucket=int(spec["min_bucket"]))
    return model, variables, ServeEngine(
        model, variables, num_slots=int(spec["num_slots"]),
        max_len=int(spec["max_len"]), min_bucket=int(spec["min_bucket"]))


# ---------------------------------------------------------------------------
# member process
# ---------------------------------------------------------------------------

class MemberHarness:
    """The member-process half of the control plane.

    Wraps a listener-less :class:`InferenceServer` (its engine loop,
    crash requeue, and failover-grace machinery are reused unchanged)
    with three wire surfaces on the shared van: a command loop on the
    submit channel (submit / drain two-phase / adopt / shutdown — ONE
    reader thread, so a drain command is naturally ordered after every
    submit the controller sent before it), an outbound event queue
    (completions, drain acks) on the event channel, and a membership
    heartbeat carrying load + engine health."""

    def __init__(self, spec: MemberSpec):
        from hetu_tpu.ps import van
        from hetu_tpu.serve.scheduler import ContinuousBatchingScheduler
        from hetu_tpu.serve.server import InferenceServer
        self.spec = spec
        self._van = van
        # the replicated durable tier, when the spawn config names one:
        # every table/channel this member builds re-resolves to the
        # promoted endpoint on primary-van death (a VanFailover is a
        # retried transient at every call site)
        self.replica = None
        if spec.van:
            from hetu_tpu.ps.replica import VanReplica
            self.replica = VanReplica.from_spec(spec.van)
        # the flight recorder FIRST: every span this process ever
        # records (engine prefill/decode, per-request lifecycle, drain
        # legs) streams to disk line-by-line, so a SIGKILL loses at most
        # one torn line (trace.load_jsonl skips it)
        if spec.trace_dir:
            trace.open_process_stream(
                spec.trace_dir, f"member_s{spec.slot}_p{os.getpid()}")
        _, _, engine = build_engine(spec.model)
        self.scheduler = ContinuousBatchingScheduler(
            engine, shed=spec.shed, shed_headroom=spec.shed_headroom,
            slo_classes=spec.slo_classes)
        # the member's half of the gray-failure plane: one emulator per
        # process, installed up front (policies arrive via spec.netem
        # and/or "netem" commands; an empty emulator is a transparent
        # wire)
        from hetu_tpu.ps.netem import LinkPolicy, NetEm
        self.netem = NetEm(local=f"m{spec.slot}", peer="van",
                           seed=int(spec.netem.get("seed", 0)))
        for direction, pol in spec.netem.get("links", ()):
            self.netem.set_link(LinkPolicy.from_dict(pol),
                                direction=direction)
        self.netem.install()
        self.server = InferenceServer(
            self.scheduler, port=spec.port, own_van=False, max_clients=0,
            request_timeout_s=spec.request_timeout_s,
            max_loop_errors=spec.max_loop_errors,
            failover_grace_s=spec.failover_grace_s)
        self.member = _mb.MembershipClient(
            "127.0.0.1", spec.port, table_id=spec.membership_table,
            slot=spec.slot, n_slots=spec.n_slots, replica=self.replica)
        self._stop = threading.Event()
        self._events: queue.Queue = queue.Queue()
        self._migrated: set = set()   # rids handed to a peer (no event)
        # rid dedup: after a van failover the controller RE-SENDS every
        # unresolved submit (it cannot know which landed before the
        # primary died); a rid this member already owns must not be
        # served twice.  Bounded like _done_log.
        self._seen_rids: OrderedDict = OrderedDict()
        self._pending_drain = None    # (xfer_id, pairs) awaiting commit
        # completion RECORDS, kept after emission: when a controller
        # dies, whatever sat unread in the old event channel's single
        # slot died with it — on rebind every record is re-announced
        # and the new controller dedups by rid
        self._done_log: list = []
        self._fenced_cmds = 0
        self._epoch_ack = 0
        # the controller's incarnation keys the command channels: wait
        # for the first control publish (the pool publishes BEFORE
        # spawning members, so this is immediate except under chaos)
        deadline = time.monotonic() + 30.0
        while True:
            try:
                self._epoch_ack = self.member.read_control()[0]
            except Exception:
                pass
            if self.member.ctrl_inc > 0:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "no controller incarnation on the control row")
            time.sleep(0.02)
        self._ctrl_gen = self.member.ctrl_inc
        self._van_gen = self.replica.incarnation if self.replica else 0
        # generations are (controller incarnation, van incarnation)
        # pairs: EITHER bump rebinds the command/event channels — a new
        # controller allocates fresh incarnation-keyed ids, a promoted
        # van has fresh (empty) channel state at the same ids
        self._in_gen = self._out_gen = (self._ctrl_gen, self._van_gen)
        self._in = self._chan(spec.submit_ch, self._ctrl_gen)
        self._out = self._chan(spec.event_ch, self._ctrl_gen)
        self.member.join(epoch_ack=float(self._epoch_ack))
        self._threads = [
            threading.Thread(target=self._beat_loop, daemon=True),
            threading.Thread(target=self._event_loop, daemon=True),
            threading.Thread(target=self._ctrl_watch_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()

    # ---- outbound ----
    def _emit(self, ev: dict) -> None:
        self._events.put(ev)

    def _chan(self, base: int, ctrl_inc: int):
        """A control/event blob channel at the CURRENT durable-tier
        endpoint, keyed by controller incarnation as always."""
        cid = _fenced_chan(base, ctrl_inc)
        if self.replica is not None:
            return self.replica.channel(cid)
        return self._van.BlobChannel("127.0.0.1", self.spec.port, cid)

    def _mig_chan(self, ch_id: int):
        if self.replica is not None:
            return self.replica.channel(ch_id)
        return self._van.BlobChannel("127.0.0.1", self.spec.port, ch_id)

    def _gen(self) -> tuple:
        return (self._ctrl_gen, self._van_gen)

    def _ctrl_watch_loop(self) -> None:
        """Track the controller lease: the read updates the client's
        fence (``ctrl_inc``) and silence clock; an incarnation bump is
        the rebind signal for the command/event loops, and the observed
        control EPOCH is acked through the heartbeat so deaf-member
        detection works on the serving plane too.  With a replicated
        durable tier the same read drives VAN failover: a failed pull
        runs the replica's promotion dance inside its retry loop, and
        the observed van incarnation joins the rebind generation."""
        period = max(self.spec.hb_ms, 10) / 1000.0
        while not self._stop.wait(period):
            try:
                e = self.member.read_control()[0]
            except Exception:
                e = None  # unreadable control row: nothing to react to
            if e is not None:
                self._epoch_ack = max(self._epoch_ack, e)
                if self.member.ctrl_inc > self._ctrl_gen:
                    self._ctrl_gen = self.member.ctrl_inc
            if self.replica is not None and \
                    self.replica.incarnation != self._van_gen:
                self._van_gen = self.replica.incarnation

    def _event_loop(self) -> None:
        seq = 1
        backlog: list = []
        while not self._stop.is_set():
            if self._out_gen != self._gen():
                # a new controller incarnation owns the fleet (or the
                # durable tier failed over to the promoted van): bind
                # the event channel there and RE-ANNOUNCE every
                # completion record — the dead controller may have
                # resolved none/some of them (the new one dedups by
                # rid), whatever sat unread in the old channel's single
                # slot is gone, and a promoted van starts with EMPTY
                # channel state at the same ids
                gen = self._gen()
                try:
                    self._out.close()
                except Exception:
                    pass
                try:
                    self._out = self._chan(self.spec.event_ch, gen[0])
                except ConnectionError:
                    # mid-promotion (see run()): keep the thread alive,
                    # retry once the replica adopts the new primary
                    time.sleep(0.1)
                    continue
                self._out_gen = gen
                seq = 1
                backlog = list(self._done_log)
            from_backlog = bool(backlog)
            if from_backlog:
                ev = backlog[0]
            else:
                try:
                    ev = self._events.get(timeout=0.1)
                except queue.Empty:
                    continue
            payload = json.dumps(ev).encode()
            sent = False
            while not self._stop.is_set() and \
                    self._out_gen == self._gen():
                try:
                    # idempotent same-seq resend: a timeout retries the
                    # SAME slot until the controller drains it.
                    # ConnectionError covers a netem-partitioned egress
                    # (NetemDrop): a one-way-partitioned member must
                    # QUEUE its completions and flush them at heal, not
                    # lose its event thread to the partition
                    self._out.put(payload, seq, timeout_s=2.0)
                    seq += 1
                    sent = True
                    break
                except (TimeoutError, ConnectionError, RuntimeError):
                    time.sleep(0.05)
            if sent:
                if from_backlog:
                    backlog.pop(0)
            elif not from_backlog:
                # a rebind (or stop) interrupted a queue event mid-send:
                # requeue it — done events would ride the replay anyway,
                # but drain acks exist only here
                self._events.put(ev)

    def _beat_loop(self) -> None:
        from hetu_tpu.ps.replica import _dbg
        period = max(self.spec.hb_ms, 10) / 1000.0
        last_err = 0.0
        while not self._stop.wait(period):
            try:
                self.member.heartbeat(
                    load=float(self.scheduler.load),
                    healthy=self.server.healthy,
                    epoch_ack=float(self._epoch_ack))
            except Exception as e:
                now = time.monotonic()
                if now - last_err > 1.0:
                    last_err = now
                    _dbg(f"slot={self.spec.slot} heartbeat failed: "
                         f"{type(e).__name__}: {e}")
                # a transiently unreachable van must not kill the beat
                # thread — silence IS the loss signal, so keep trying
                time.sleep(period)

    def _record_request_span(self, req, tenant) -> None:
        """One retroactive ``serve.request`` span per resolved rid: the
        member-side anchor of the cross-process causal chain (the fleet
        stitcher links controller ``serve.submit`` → this → controller
        ``serve.resolve`` by the shared rid) PLUS the in-process latency
        decomposition — queue wait (submit→slot), prefill (slot→first
        token), decode (first→last token) — measured where the clocks
        are local and exact.  Control-plane ids ride as args (``ci`` =
        controller incarnation, ``slot``) so a trace of a takeover run
        shows which incarnation owned each leg."""
        t = trace.get_tracer()
        if t is None or req.submitted_at is None:
            return
        # request stamps are time.monotonic(); anchor them to the
        # tracer's clock via a (now_monotonic, now_track) pair so no
        # cross-clock epoch assumption is needed
        now_m, now_us = time.monotonic(), t._now_us()

        def at(stamp):
            return max(now_us - max(now_m - stamp, 0.0) * 1e6, 0.0)

        attrs = {"rid": int(req.rid), "status": req.status or "ok",
                 "slot": int(self.spec.slot),
                 "ci": int(self._ctrl_gen), "tokens": len(req.tokens)}
        if tenant:
            attrs["tenant"] = tenant
        if req.admitted_at is not None:
            attrs["queue_s"] = round(req.admitted_at - req.submitted_at, 6)
            if req.first_token_at is not None:
                attrs["prefill_s"] = round(
                    req.first_token_at - req.admitted_at, 6)
        if req.ttft_s is not None:
            attrs["ttft_s"] = round(req.ttft_s, 6)
        end = req.finished_at if req.finished_at is not None else now_m
        if req.first_token_at is not None:
            attrs["decode_s"] = round(end - req.first_token_at, 6)
        t.complete("serve.request", at(req.submitted_at), attrs,
                   cat="serve", end_us=at(end))

    def _watch(self, req, tenant=None) -> None:
        """Report the request's terminal state to the controller once it
        resolves — unless it migrated away (the adopter reports it).
        The record survives in ``_done_log`` so a controller takeover
        can be re-announced to."""
        def run():
            req.done.wait()
            if req.status == "migrated" or req.rid in self._migrated:
                return
            try:
                self._record_request_span(req, tenant)
            except Exception:
                traceback.print_exc()  # telemetry must never block a
                # completion from reaching the controller
            ev = {"type": "done", "rid": int(req.rid),
                  "status": req.status or "ok",
                  "tokens": [int(t) for t in req.tokens],
                  "ttft_s": req.ttft_s}
            self._done_log.append(ev)
            if len(self._done_log) > 1024:
                del self._done_log[0]
            self._emit(ev)
        threading.Thread(target=run, daemon=True).start()

    # ---- command dispatch (single reader: ordering is the protocol) ----
    def run(self) -> None:
        seq = 1
        while not self._stop.is_set():
            if self._in_gen != self._gen():
                # DRAIN the dying incarnation's channel before
                # switching: the slot is one-deep, and the command
                # possibly still sitting in it (e.g. the submit the
                # dead controller journaled right before dying) belongs
                # to a request the NEW controller has adopted and is
                # waiting on — dropping it would strand that rid
                # forever.  These drained commands bypass the staleness
                # fence (they were written by the then-legitimate
                # controller; a zombie can only reach this window by
                # racing the one bounded drain, after which the old
                # channel is never read again).  When the VAN
                # incarnation changed, the old channel lives on a dead
                # (or fenced) van — nothing to drain, and the
                # controller re-sends every unresolved submit after its
                # own rebind, so skip straight to the new endpoint.
                van_changed = self._in_gen[1] != self._van_gen
                drain_deadline = time.monotonic() + 5.0
                while not van_changed and not self._stop.is_set():
                    try:
                        # a generous get timeout: 0.2s would conflate
                        # "slot empty" with "slow wire" and drop a
                        # journaled submit under a netem-degraded link
                        raw = self._in.get(seq, timeout_s=1.0)
                    except TimeoutError:
                        break  # slot empty — the drain is complete
                    except RuntimeError:
                        break  # van gone under us
                    except ConnectionError:
                        # transient wire wobble (netem degrade, van
                        # hiccup): must not truncate the ONE bounded
                        # drain — a journaled submit dropped here
                        # strands the rid its successor adopted
                        if time.monotonic() >= drain_deadline:
                            break
                        time.sleep(0.05)
                        continue
                    seq += 1
                    try:
                        if not self._dispatch(json.loads(raw),
                                              allow_stale=True):
                            self.close()
                            return
                    except Exception:
                        traceback.print_exc()
                gen = self._gen()
                try:
                    self._in.close()
                except Exception:
                    pass
                try:
                    self._in = self._chan(self.spec.submit_ch, gen[0])
                except ConnectionError:
                    # the pair is mid-promotion (a SECOND fault can
                    # land while this rebind is already in flight):
                    # _in_gen stays stale, so the loop re-enters this
                    # block and re-binds once the watch loop adopts
                    # the promoted incarnation — a member must outlive
                    # the window, not crash into a lease expiry
                    time.sleep(0.1)
                    continue
                self._in_gen = gen
                seq = 1
            try:
                raw = self._in.get(seq, timeout_s=0.25)
            except (TimeoutError, ConnectionError):
                continue  # idle poll / netem-partitioned ingress: the
                # command loop outlives a transiently unreachable wire
            except RuntimeError:
                if self.replica is not None:
                    # a dead PRIMARY van surfaces here as rc=-101 at
                    # the get deadline — with a replicated durable
                    # tier that is a survivable outage (the watch
                    # loop promotes/adopts and bumps the van
                    # generation, and this loop rebinds), NOT a
                    # shutdown signal
                    time.sleep(0.05)
                    continue
                break  # van gone under us
            seq += 1
            try:
                msg = json.loads(raw)
                if not self._dispatch(msg):
                    break
            except Exception:
                traceback.print_exc()  # one bad command must not kill
                # the member — the controller's lease would misread a
                # parse error as a death
        self.close()

    def _dispatch(self, msg: dict, *, allow_stale: bool = False) -> bool:
        from hetu_tpu.serve.scheduler import Request
        ci = msg.get("ci")
        if not allow_stale and ci is not None and \
                int(ci) < self.member.ctrl_inc:
            # a fenced (superseded-incarnation) controller's command:
            # refused — the member-side half of the zombie fence
            self._fenced_cmds += 1
            return True
        cmd = msg.get("cmd")
        if cmd == "submit":
            rid = int(msg["rid"])
            if rid in self._seen_rids:
                # duplicate delivery (a controller re-send after a van
                # failover, or an orphan re-route that picked this
                # member again): already owned — serving it twice would
                # waste slots, and the original's completion record
                # answers the controller either way
                return True
            self._seen_rids[rid] = True
            while len(self._seen_rids) > 4096:
                self._seen_rids.popitem(last=False)
            req = Request(prompt=[int(t) for t in msg["prompt"]],
                          max_tokens=int(msg.get("max_tokens", 16)),
                          eos_id=msg.get("eos_id"),
                          timeout_s=float(msg.get(
                              "timeout_s", self.spec.request_timeout_s)))
            req.rid = rid  # controller-global id: completion
            # events and cross-process drains correlate on it
            req.tenant = msg.get("tenant")  # rides the migration record
            # too, so an adopter keeps the attribution
            req.slo = msg.get("slo")  # SLO class name — the scheduler
            # maps it to (priority, weight) via its slo_classes
            self._watch(req, tenant=req.tenant)
            self.scheduler.submit(req)
        elif cmd == "recv_migration":
            self._recv_migration(int(msg["ch"]), int(msg["xfer"]),
                                 float(msg.get("timeout_s", 30.0)))
        elif cmd == "drain":
            self._drain(int(msg["ch"]), int(msg["xfer"]),
                        str(msg.get("codec", "none")),
                        float(msg.get("timeout_s", 30.0)))
        elif cmd == "drain_commit":
            self._drain_commit(int(msg["xfer"]), leave=bool(msg.get("exit")))
            if msg.get("exit"):
                return False
        elif cmd == "drain_abort":
            self._drain_abort(int(msg["xfer"]))
        elif cmd == "netem":
            self._apply_netem(msg)
        elif cmd == "replay":
            # the controller lost track of these rids (an event that
            # died in a dead van's single-slot channel, a listener
            # rebind race): re-emit any COMPLETED record it names —
            # in-progress rids simply have no record yet, and the
            # controller's first-wins dedup absorbs duplicates
            rids = {int(r) for r in msg.get("rids", ())}
            for ev in list(self._done_log):
                if int(ev.get("rid", -1)) in rids:
                    self._emit(ev)
        elif cmd == "metrics":
            self._emit_metrics()
        elif cmd == "shutdown":
            return False
        return True

    _DURABLE_TIER_METRICS = ("membership.", "van.replica.",
                             "van.resilver.", "ledger.", "standby.",
                             "ps.")

    def _emit_metrics(self) -> None:
        """Answer a fleet scrape: ship the FULL registry state (raw
        histogram buckets, not percentiles — the controller's merge is
        bucket-wise) over the event channel, and mirror it into the span
        stream as a black-box record so a later SIGKILL cannot erase
        the last scraped numbers.  Durable-tier health counters
        (stale control reads, replication lag/promotions) live in the
        process-default registry — folded into the same dump so
        ``fleet_metrics()`` and the Prometheus export cover them."""
        from hetu_tpu.telemetry import default_registry
        if self.replica is not None:
            self.replica.export_lag()  # refresh the lag gauge
        dump = {k: v for k, v in default_registry.dump().items()
                if k.startswith(self._DURABLE_TIER_METRICS)}
        dump.update(self.scheduler.metrics.registry.dump())
        t = trace.get_tracer()
        if t is not None:
            t.metric_dump(dump)
        self._emit({"type": "metrics", "slot": int(self.spec.slot),
                    "dump": dump})

    def _apply_netem(self, msg: dict) -> None:
        """Install (or clear) a link policy on this member's van wire.
        The policy usually carries ``duration_s`` so a PARTITION heals
        itself — a heal command could never cross the very link it is
        supposed to heal."""
        from hetu_tpu.ps.netem import LinkPolicy
        direction = str(msg.get("direction", "both"))
        pol = msg.get("policy")
        if pol is None:
            self.netem.clear_link(direction=direction)
        else:
            self.netem.set_link(LinkPolicy.from_dict(pol),
                                direction=direction)

    # ---- migration (two-phase, source side holds until commit) ----
    def _drain(self, ch_id: int, xfer: int, codec: str,
               timeout_s: float) -> None:
        # the MEMBER-side half of the drain recovery, recorded in THIS
        # process's stream: a preemption fault injected controller-side
        # pairs with this span on the merged fleet trace (the xfer id is
        # the drain's control-plane correlation key).  A failed export
        # carries args.error, so the timeline never claims it as a
        # recovery that repaired anything.
        with trace.span("serve.migrate",
                        {"xfer": int(xfer), "member": int(self.spec.slot),
                         "ci": int(self._ctrl_gen)}, cat="serve") as sp:
            pairs = None
            try:
                payload, pairs = _migrate.export_payload(self.scheduler,
                                                         codec=codec)
                tx = self._mig_chan(ch_id)
                try:
                    _migrate.send_payload(tx, payload, timeout_s=timeout_s)
                finally:
                    tx.close()
            except Exception as e:
                traceback.print_exc()
                sp.set("error", type(e).__name__)
                if pairs is not None:
                    try:
                        self.scheduler.adopt_inflight(pairs)  # resume
                    except Exception:
                        traceback.print_exc()
                self._emit({"type": "drain_failed", "xfer": xfer,
                            "error": repr(e)})
                return
            sp.set("requests", len(pairs))
        self._pending_drain = (xfer, pairs)
        self._emit({"type": "drained", "xfer": xfer, "n": len(pairs)})

    def _drain_commit(self, xfer: int, *, leave: bool = True) -> None:
        from hetu_tpu.serve.scheduler import finish_request
        if self._pending_drain is None or self._pending_drain[0] != xfer:
            return
        _, pairs = self._pending_drain
        self._pending_drain = None
        for req, _slot in pairs:
            # resolve locally as 'migrated' so the watcher stays silent —
            # the ADOPTER owns the client-visible completion now
            self._migrated.add(req.rid)
            finish_request(req, "migrated", None)
        _migrate.release_exported(self.scheduler, pairs)
        if leave:
            try:
                self.member.leave()  # planned exit: never grieved
            except Exception:
                pass

    def _drain_abort(self, xfer: int) -> None:
        if self._pending_drain is None or self._pending_drain[0] != xfer:
            return
        _, pairs = self._pending_drain
        self._pending_drain = None
        try:
            self.scheduler.adopt_inflight(pairs)  # back in service
        except Exception:
            traceback.print_exc()

    def _recv_migration(self, ch_id: int, xfer: int,
                        timeout_s: float) -> None:
        # ack FIRST: the controller must not start the source's send
        # before this member is committed to receiving
        self._emit({"type": "mig_ready", "xfer": xfer})
        with trace.span("serve.adopt",
                        {"xfer": int(xfer), "member": int(self.spec.slot),
                         "ci": int(self._ctrl_gen)}, cat="serve") as sp:
            try:
                rx = self._mig_chan(ch_id)
                try:
                    got = _migrate.recv_payload(rx, timeout_s=timeout_s)
                finally:
                    rx.close()
                reqs, slot_map = _migrate.adopt_payload(self.scheduler, got)
            except Exception as e:
                traceback.print_exc()
                sp.set("error", type(e).__name__)
                self._emit({"type": "adopt_failed", "xfer": xfer,
                            "error": repr(e)})
                return
            sp.set("requests", len(reqs))
        for req in reqs:
            self._seen_rids[req.rid] = True  # adopted = owned: a later
            # duplicate submit for the rid must not double-serve it
            self._watch(req, tenant=getattr(req, "tenant", None))
        self._emit({"type": "adopted", "xfer": xfer, "n": len(reqs),
                    "slots": len(slot_map)})

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        t = trace.get_tracer()
        if t is not None:
            try:  # final black-box record + flush (clean exits; kills
                # rely on the per-line flush)
                t.metric_dump(self.scheduler.metrics.registry.dump())
                t.flush()
            except Exception:
                pass
        try:
            self.member.leave()
        except Exception:
            pass
        try:
            self.server.close(5.0)
        except Exception:
            traceback.print_exc()
        for ch in (self._in, self._out):
            try:
                ch.close()
            except Exception:
                pass
        self.member.close()
        self.netem.uninstall()


def member_main(config_path: str) -> int:
    """Entry point for a spawned member process: build the harness,
    announce READY (the spawner's handshake), serve until told to stop."""
    import faulthandler
    import signal as _signal
    faulthandler.register(_signal.SIGUSR1)  # live-stack dump to stderr
    spec = MemberSpec.from_json(open(config_path).read())
    harness = MemberHarness(spec)
    print("READY", spec.slot, flush=True)
    harness.run()
    return 0


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class PoolRequest:
    """Controller-side request record: the original message (the
    failover resubmission source), current route, and the waiter's
    completion event.  Response dict shape matches the in-process
    pool's ``generate``."""

    __slots__ = ("rid", "msg", "member", "retries", "tokens", "status",
                 "ttft_s", "done", "sent", "routed_at")

    def __init__(self, rid: int, msg: dict):
        self.rid = rid
        self.msg = msg
        self.member: Optional[int] = None
        self.retries = 0
        self.routed_at: Optional[float] = None  # monotonic; the
        # replay-nudge ages unresolved requests from here
        self.tokens: list = []
        self.status: Optional[str] = None
        self.ttft_s = None
        self.done = threading.Event()
        # True once the submit command LANDED on the member's channel:
        # the ledger journals an ownership only when it is real — a
        # concurrent journal snapshotting the optimistic assignment
        # mid-send would otherwise record a member that never heard of
        # the rid, and a takeover would wait on it forever
        self.sent = False


class CrossProcessServingPool:
    """Controller over N serving-member PROCESSES on one van.

    Construction starts the van, creates the membership blackboard,
    spawns ``n_members`` member processes (each builds the same seeded
    model), and waits for them to join.  ``generate``/``submit`` route
    over the wire; the poll thread runs the lease state machine and the
    failover/suspect handling; ``drain_member`` runs the two-phase
    cross-process KV migration.  ``procs`` holds the live ``Popen``
    handles — exactly what the chaos harness's ``member_kill`` /
    ``member_suspend`` faults target.
    """

    def __init__(self, n_members: int = 2, *, workdir, model: dict = None,
                 port: int = 0, own_van: bool = True,
                 hb_ms: int = 80, lease_s: float = 0.6,
                 suspect_grace_s: float = 0.5,
                 poll_s: float = 0.05,
                 request_timeout_s: float = 60.0,
                 max_retries: int = 3,
                 migrate_codec: str = "none",
                 membership_table: Optional[int] = None,
                 ledger_table: Optional[int] = None,
                 # DeltaLedger geometry: half the rows hold the base
                 # snapshot (state capacity ~= the old snapshot
                 # ledger's), half the append-only delta region
                 ledger_rows: int = 2048,
                 deaf_ack_s: Optional[float] = None,
                 metrics: Optional[ServeMetrics] = None,
                 member_env: Optional[dict] = None,
                 spawn_timeout_s: float = 120.0,
                 shed: bool = False, shed_headroom: float = 1.0,
                 slo_classes: Optional[dict] = None,
                 rtt_degraded_x: float = 5.0,
                 start_poll: bool = True,
                 telemetry_streams: bool = True,
                 scrape_s: float = 1.0,
                 van_spec: Optional[dict] = None,
                 van_backup_factory=None,
                 _takeover: bool = False):
        from hetu_tpu.ps import van
        if n_members < 1:
            raise ValueError("a serving pool needs at least one member")
        migrate_codec = _migrate.check_codec(migrate_codec)
        self._van = van
        self._own_van = own_van
        # replicated durable tier: `van_spec` (a ReplicaSpec dict)
        # names a primary+backup van pair — the blackboard and ledger
        # dual-write synchronously, channels re-resolve to the promoted
        # endpoint, and a primary-van SIGKILL costs a rebind, not the
        # fleet
        self._replica = None
        self._van_spec = dict(van_spec) if van_spec else {}
        self._van_gen = 0
        self._mb_van_seen = 0
        self._van_rebind_pending = False
        if self._van_spec:
            if own_van:
                raise ValueError(
                    "a replicated durable tier is external by "
                    "definition: pass own_van=False with van_spec")
            from hetu_tpu.ps.replica import VanReplica
            # pair-membership rendezvous on the shared workdir: members
            # whose cached endpoint view goes fully dead (both slots
            # replaced while they were busy) re-read the pair from here
            # instead of livelocking against two dead ports
            self._van_spec.setdefault(
                "rendezvous", os.path.join(workdir, "van_pair.json"))
            self._replica = VanReplica.from_spec(
                self._van_spec, bootstrap=not _takeover)
            if _takeover:
                self._replica.refresh()  # unconditional: a stale
                # cached view must not adopt the dead primary
            port = self._replica.primary[1]
            self._van_gen = self._replica.incarnation
            self._mb_van_seen = self._replica.incarnation
            self._replica.register(self._on_van_failover)
            if van_backup_factory is not None:
                # continuous redundancy: a promotion auto-resilvers
                # onto a fresh van from this factory (() -> (host,
                # port)), restoring the pair without an operator
                self._replica.spawn_backup = van_backup_factory
                self._replica.write_rendezvous()  # seed the snapshot
        if own_van:
            self.port = van.serve(port)
        else:
            if not port:
                raise ValueError("own_van=False needs the running van's port")
            self.port = port
        self.workdir = workdir
        self.model = {**DEFAULT_MODEL, **(model or {})}
        self.n_members = int(n_members)
        self.hb_ms = int(hb_ms)
        self.request_timeout_s = float(request_timeout_s)
        self.max_retries = int(max_retries)
        self.migrate_codec = migrate_codec
        # fresh by default: the native table registry outlives van.stop(),
        # and two pools in one process must not share a blackboard
        self._membership_table = int(membership_table) \
            if membership_table is not None else _mb.fresh_table_id()
        self._ledger_table = int(ledger_table) \
            if ledger_table is not None else _mb.fresh_table_id()
        self._ledger_rows = int(ledger_rows)
        self._spawn_timeout_s = float(spawn_timeout_s)
        # e.g. {"JAX_PLATFORMS": "cpu"} — a bench on an accelerator box
        # keeps member processes off the chip the controller holds
        self._member_env = dict(member_env) if member_env else None
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self._lock = threading.RLock()
        self._poll_lock = threading.Lock()
        self._journal_lock = threading.Lock()
        self._journal_dirty = False
        self._pending_deltas: list = []  # coalesced route/resolve
        # records, flushed by the poll loop in ONE append frame
        self._unrouted: dict = {}  # rid -> routing deadline (parked
        # while no member is routable — e.g. mid van-failover blind
        # window; journaled, so they must resolve, not error out)
        self._rid_seq = 0               # journaled: rid space survives
        self._ctrl_seq = 0              # a takeover (no reuse)
        self._requests: dict = {}       # rid -> PoolRequest
        # rid -> terminal status, bounded: the ledger's dedup record —
        # a member re-announcing an already-resolved completion after a
        # takeover must be recognized, not re-served
        self._resolved: OrderedDict = OrderedDict()
        self._ch_bases: dict = {}       # slot -> (submit_base, event_base)
        self._drain_journal: dict = {}  # xfer -> two-phase drain record
        self._member_pids: dict = {}    # takeover-adopted pids (no Popen)
        self._fenced = False
        self._inflight: dict = {}       # slot -> outstanding count
        self._draining: set = set()
        self._quarantined: set = set()  # engine-dead / failed-over slots
        self._suspect_t0: dict = {}     # slot -> trace ts of suspicion
        # per-link health, measured from this controller's OWN control
        # sends (every submit/drain command is a timed blob put): the
        # routing penalty that keeps traffic off a member behind a
        # degraded link BEFORE its lease ever wobbles
        self._shed = bool(shed)
        self._shed_headroom = float(shed_headroom)
        # per-tenant SLO classes, forwarded verbatim into every member's
        # spawn config (and so into each member scheduler) — the pool
        # itself only needs them to stamp submits with a class name
        self._slo_classes = dict(slo_classes) if slo_classes else {}
        self._rtt_degraded_x = float(rtt_degraded_x)
        self._rtt: dict = {}            # slot -> EWMA send seconds
        self._degraded_t0: dict = {}    # slot -> trace ts of degrade
        self._xfers: dict = {}          # xfer id -> {"evt", "events"}
        self._out: dict = {}            # slot -> (channel, lock, [seq])
        self._listeners: dict = {}      # slot -> (thread, stop)
        # fleet observability: members stream spans to workdir when
        # telemetry_streams, and the poll loop scrapes their registry
        # dumps every scrape_s (0 disables the cadence; scrape() still
        # works on demand).  The scrape round runs in a ONE-SHOT side
        # thread so a wedged member's control channel can never stall
        # the membership sweep that would declare it lost.
        self._telemetry_streams = bool(telemetry_streams)
        self._scrape_s = float(scrape_s)
        self._member_metrics: dict = {}  # slot -> last registry dump
        self._metrics_replies: dict = {}  # slot -> reply count
        self._scrape_pending: dict = {}  # slot -> unanswered ask time
        # counters/histograms of DEAD member incarnations, folded in at
        # revive time: without this, a replacement's first scrape reply
        # would overwrite the victim's last dump and the fleet's
        # request counters would go BACKWARD (a broken Prometheus
        # counter) while silently dropping the dead incarnation's work
        self._retired_metrics: dict = {}
        self._last_scrape = 0.0
        self._scrape_busy = threading.Event()
        # completion-replay nudge: a done event can die in a dead van's
        # single-slot channel (or a listener rebind race) — the member
        # keeps the record in its _done_log, so the controller
        # periodically asks owners to re-emit records for rids it still
        # sees unresolved.  First-wins dedup makes duplicates free.
        self._nudge_after_s = 3.0
        self._last_nudge = 0.0
        self._nudge_busy = threading.Event()
        self.procs: list = [None] * self.n_members
        self.adopted: dict = {}         # takeover: rid -> PoolRequest
        self.takeover_report: dict = {}
        # warm autoscaler takeover: the control loop's streaks/cooldown
        # deadlines/active set journal here (and into the ledger) so a
        # takeover resumes the loop from measured history, not cold
        self._autoscaler_state: Optional[dict] = None
        # live health plane (started on demand by start_health_monitor)
        self.health_monitor = None
        self._stop = threading.Event()
        try:
            if _takeover:
                # adopt, don't create: the blackboard, ledger, and the
                # member PROCESSES all outlived the dead controller
                self._bb = _mb.attach_blackboard(
                    "127.0.0.1", self.port,
                    table_id=self._membership_table,
                    n_slots=self.n_members, replica=self._replica)
                self.svc = _mb.MembershipService(
                    self._bb, self.n_members, lease_s=lease_s,
                    suspect_grace_s=suspect_grace_s,
                    deaf_ack_s=deaf_ack_s)
                self._ledger = _mb.DeltaLedger(
                    "127.0.0.1", self.port, table_id=self._ledger_table,
                    rows=self._ledger_rows, create=False,
                    replica=self._replica)
                self._adopt()
            else:
                self._bb = _mb.create_blackboard(
                    "127.0.0.1", self.port,
                    table_id=self._membership_table,
                    n_slots=self.n_members, replica=self._replica)
                self.svc = _mb.MembershipService(
                    self._bb, self.n_members, lease_s=lease_s,
                    suspect_grace_s=suspect_grace_s,
                    deaf_ack_s=deaf_ack_s)
                self._ledger = _mb.DeltaLedger(
                    "127.0.0.1", self.port, table_id=self._ledger_table,
                    rows=self._ledger_rows, create=True,
                    replica=self._replica)
                # publish the control row BEFORE spawning: members key
                # their command channels on the incarnation it carries
                self.svc.publish_control(epoch=1, width=self.n_members,
                                         alive_mask=0)
                for slot in range(self.n_members):
                    self._spawn(slot)
                self._wait_joined(range(self.n_members))
                self._journal()
        except Exception:
            self.close()
            raise
        self._poll_thread = None
        if start_poll:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, args=(float(poll_s),), daemon=True)
            self._poll_thread.start()

    @classmethod
    def takeover(cls, *, workdir, port, lease_s: float = 0.6,
                 suspect_grace_s: float = 0.5, poll_s: float = 0.05,
                 request_timeout_s: float = 60.0, max_retries: int = 3,
                 deaf_ack_s: Optional[float] = None,
                 metrics: Optional[ServeMetrics] = None,
                 spawn_timeout_s: float = 120.0,
                 start_poll: bool = True) -> "CrossProcessServingPool":
        """Become the fleet's NEW controller after the old one died.

        Reads the dead controller's member spawn configs from
        ``workdir`` (the durable record of every control-plane id:
        blackboard, ledger, channel bases, model), attaches to the
        still-running van at ``port``, claims the controller row with a
        strictly higher incarnation, and adopts: members rebind their
        command channels to the new incarnation and re-announce their
        completion records, unresolved requests are restored from the
        ledger (orphans re-routed), and half-open drains are aborted
        back to a serving source — the whole hand-off under one
        ``ctrl.takeover`` span.  Adopted in-flight requests land in
        ``self.adopted``; :meth:`wait_adopted` blocks on them."""
        from pathlib import Path
        cfgs = sorted(Path(workdir).glob("member_*.json"),
                      key=lambda p: p.stat().st_mtime)
        if not cfgs:
            raise FileNotFoundError(
                f"no member spawn configs under {workdir} — nothing to "
                f"take over")
        spec = MemberSpec.from_json(cfgs[-1].read_text())
        return cls(spec.n_slots, workdir=workdir, model=spec.model,
                   port=port, own_van=False, hb_ms=spec.hb_ms,
                   lease_s=lease_s, suspect_grace_s=suspect_grace_s,
                   poll_s=poll_s, request_timeout_s=request_timeout_s,
                   max_retries=max_retries,
                   membership_table=spec.membership_table,
                   ledger_table=spec.ledger_table,
                   ledger_rows=spec.ledger_rows,
                   deaf_ack_s=deaf_ack_s, metrics=metrics,
                   spawn_timeout_s=spawn_timeout_s,
                   shed=spec.shed, shed_headroom=spec.shed_headroom,
                   telemetry_streams=bool(spec.trace_dir),
                   scrape_s=spec.scrape_s, van_spec=spec.van or None,
                   start_poll=start_poll, _takeover=True)

    def _adopt(self) -> None:
        got = self._ledger.read()
        state = self._replay_ledger(got) if got else {}
        with trace.span("ctrl.takeover", cat="ctrl") as sp:
            sp.set("plane", "serving")
            sp.set("incarnation", self.svc.ctrl_incarnation)
            ctrl = self.svc.read_control_row()
            # carry any injected slow-link fields forward (the serving
            # plane publishes rarely, but the rule is uniform: a
            # takeover must not silently heal an injection)
            self.svc.adopt_slow(ctrl["slow_slot"], ctrl["slow_ms"])
            # republish under the NEW incarnation: this is the rebind
            # signal every member's control watch is waiting for
            self.svc.publish_control(
                epoch=max(int(ctrl["epoch"]), 1), width=self.n_members,
                alive_mask=int(ctrl["alive_mask"]))
            with self._lock:
                self._rid_seq = int(state.get("rid", 0))
                self._ctrl_seq = int(state.get("cid", 0))
                for s, bases in (state.get("channels") or {}).items():
                    self._ch_bases[int(s)] = (int(bases[0]),
                                              int(bases[1]))
                for rid_s, rec in (state.get("requests") or {}).items():
                    req = PoolRequest(int(rid_s), dict(rec["msg"]))
                    req.member = rec.get("member")
                    req.sent = req.member is not None
                    if req.sent:  # nudge-eligible: the member's
                        # re-announce usually beats the nudge, but a
                        # lost event must not strand the adoption
                        req.routed_at = time.monotonic()
                    req.retries = int(rec.get("retries", 0))
                    self._requests[req.rid] = req
                    self.adopted[req.rid] = req
                for rid_s, st in (state.get("resolved") or {}).items():
                    self._resolved[int(rid_s)] = st
                self._drain_journal = {
                    str(k): dict(v)
                    for k, v in (state.get("drains") or {}).items()}
                self._autoscaler_state = \
                    dict(state["autoscaler"]) \
                    if state.get("autoscaler") else None
            # wire up every recorded member under the new incarnation
            inc = self.svc.ctrl_incarnation
            for slot, (sub, evb) in sorted(self._ch_bases.items()):
                ch = self._ctrl_chan(_fenced_chan(sub, inc))
                with self._lock:
                    old = self._out.get(slot)
                    self._out[slot] = (ch, threading.Lock(), [1])
                    self._inflight.setdefault(slot, 0)
                if old is not None:
                    try:
                        old[0].close()
                    except Exception:
                        pass
                self._start_listener(slot, evb)
            # learn who is still beating (members that died WITH the
            # controller surface as ordinary lease expiries below)
            self.svc.wait_present(self._spawn_timeout_s, poll=self.poll)
            # member pids off the blackboard: these processes are the
            # DEAD controller's children — the pid is the only handle
            # close()/revive have on them
            self._member_pids.update(self.svc.member_pids())
            # half-open drains: abort back to a still-serving source
            # (the PR 5/8 abort path — the source re-adopts its export;
            # a target that also adopted serves duplicates the rid
            # dedup absorbs, token-identically).  The abort must LAND
            # before the record may be dropped: the source parks its
            # exported requests in _pending_drain until told, and a
            # swallowed send failure would strand them forever.  The
            # send can fail transiently right after takeover (the
            # source rebinds its incarnation-keyed channel one watch
            # period after the bump), so failed aborts retry until the
            # source either hears us or loses its lease (dead source ⇒
            # _pending_drain died with it; its rids re-route as
            # orphans below).  Records that outlive the budget stay
            # journaled for the next incarnation rather than vanish.
            aborted = 0
            orphaned = 0  # source died WITH the drain: no abort to
            # deliver — the record drops and its rids re-route below
            pending = dict(self._drain_journal)
            abort_deadline = time.monotonic() + self._spawn_timeout_s
            while pending:
                for xid_s, d in list(pending.items()):
                    src = int(d.get("source", -1))
                    src_alive = 0 <= src < self.n_members and \
                        self.svc.state_of(src).state in ("alive",
                                                         "suspect")
                    sent = False
                    try:
                        self._send(src, {"cmd": "drain_abort",
                                         "xfer": int(xid_s)})
                        sent = True
                    except Exception:
                        traceback.print_exc()
                    if sent or not src_alive:
                        with self._lock:
                            self._draining.discard(src)
                            self._drain_journal.pop(xid_s, None)
                        del pending[xid_s]
                        if sent:
                            aborted += 1
                        else:
                            orphaned += 1
                if pending:
                    if time.monotonic() >= abort_deadline:
                        break
                    self.poll()
                    time.sleep(0.05)
            # rebuild routing state and re-home orphans: a request whose
            # member is gone re-prefills on a survivor (the ordinary
            # failover fold — greedy decode keeps it token-exact)
            with self._lock:
                for r in self._requests.values():
                    if r.member is not None:
                        self._inflight[r.member] = \
                            self._inflight.get(r.member, 0) + 1
                alive = set(self.svc.present_slots())
                orphans = [r for r in self._requests.values()
                           if r.member is None or r.member not in alive]
            for r in orphans:
                self._route(r, exclude=(
                    {r.member} if r.member is not None else set()))
            self.takeover_report = {
                "incarnation": self.svc.ctrl_incarnation,
                "adopted_requests": len(self.adopted),
                "resolved_known": len(self._resolved),
                # the ledger's pre-kill resolutions, by rid: the
                # supported loss-accounting surface (a rid is safe iff
                # adopted-and-resolved OR already here)
                "resolved": dict(self._resolved),
                "drains_aborted": aborted,
                "drains_orphaned": orphaned,
                "orphans_rerouted": len(orphans),
                "members_present": sorted(self.svc.present_slots()),
                "autoscaler_state": dict(self._autoscaler_state)
                if self._autoscaler_state else None,
            }
            sp.set("adopted_requests", len(self.adopted))
            sp.set("drains_aborted", aborted)
            sp.set("drains_orphaned", orphaned)
            sp.set("orphans_rerouted", len(orphans))
        self.metrics.inc("controller_takeovers")
        # the new incarnation opens on a FRESH base: one compaction
        # subsumes the predecessor's base + deltas (and proves the
        # mid-compaction takeover safe — a reader only ever sees one
        # atomic frame or the other)
        self._compact_ledger()

    def wait_adopted(self, timeout_s: float = 120.0) -> dict:
        """Block until every request adopted at takeover resolves;
        returns ``{rid: {"status", "tokens", "ttft_s"}}``.  A request
        that never resolves within the budget reads status
        'timeout'."""
        deadline = time.monotonic() + float(timeout_s)
        out = {}
        for rid, req in sorted(self.adopted.items()):
            if not req.done.wait(max(deadline - time.monotonic(), 0.01)):
                self._resolve(req, "timeout")
            out[rid] = {"status": req.status or "ok",
                        "tokens": list(req.tokens),
                        "ttft_s": req.ttft_s}
        return out

    @property
    def fenced(self) -> bool:
        """True once a newer controller incarnation superseded this one
        (every further control write is refused)."""
        return self._fenced

    # ---- spawning ----
    def _next_rid(self) -> int:
        with self._lock:
            self._rid_seq += 1
            return self._rid_seq

    def _spawn(self, slot: int) -> None:
        from hetu_tpu.resilience.shardproc import spawn_module
        if self._replica is not None:
            # spawn configs must carry the CURRENT pair membership: a
            # member spawned after failovers + re-silvers would find
            # the original endpoints both dead and have no rendezvous
            self._van_spec = self._replica.current_spec()
        with self._lock:
            cid = self._ctrl_seq
            self._ctrl_seq += 1
        spec = MemberSpec(
            port=self.port, slot=slot, n_slots=self.n_members,
            submit_ch=CONTROL_CHANNEL_BASE + 2 * cid,
            event_ch=CONTROL_CHANNEL_BASE + 2 * cid + 1,
            membership_table=self._membership_table, hb_ms=self.hb_ms,
            request_timeout_s=self.request_timeout_s, model=self.model,
            shed=self._shed, shed_headroom=self._shed_headroom,
            slo_classes=self._slo_classes,
            ledger_table=self._ledger_table,
            ledger_rows=self._ledger_rows,
            trace_dir=str(self.workdir) if self._telemetry_streams
            else "", scrape_s=self._scrape_s, van=self._van_spec)
        from pathlib import Path
        cfg = Path(self.workdir) / f"member_{slot}_{cid}.json"
        cfg.write_text(spec.to_json())
        proc = spawn_module(self.workdir, f"member_{slot}_{cid}",
                            "hetu_tpu.serve.crosshost", [str(cfg)],
                            extra_env=self._member_env,
                            timeout_s=self._spawn_timeout_s)
        self.procs[slot] = proc
        ch = self._ctrl_chan(
            _fenced_chan(spec.submit_ch, self.svc.ctrl_incarnation))
        with self._lock:
            old = self._out.get(slot)
            self._out[slot] = (ch, threading.Lock(), [1])
            self._inflight[slot] = 0
            self._ch_bases[slot] = (spec.submit_ch, spec.event_ch)
            self._member_pids.pop(slot, None)
            self._scrape_pending.pop(slot, None)  # fresh incarnation:
            # the old unanswered ask died with the old process
            self._retire_member_metrics_locked(slot)
        if old is not None:  # a revived slot's previous control channel
            try:
                old[0].close()
            except Exception:
                pass
        self._start_listener(slot, spec.event_ch)
        # the fresh channel bases are JOURNALED state: a controller
        # death right after a revive must hand the successor the new
        # bases, not the dead slot's old ones (a takeover would
        # otherwise wire this member to channels nobody serves)
        try:
            self._append_ledger([
                {"c": [slot, spec.submit_ch, spec.event_ch]},
                {"q": [self._rid_seq, self._ctrl_seq]}])
        except Exception:
            traceback.print_exc()

    def _ctrl_chan(self, channel_id: int):
        """A control/event blob channel at the CURRENT durable-tier
        endpoint (the replica's primary when replicated)."""
        if self._replica is not None:
            return self._replica.channel(channel_id)
        return self._van.BlobChannel("127.0.0.1", self.port, channel_id)

    def _on_van_failover(self, replica) -> None:
        """Replica callback (runs on whichever thread hit the
        failover): flag only — the poll loop owns the rebind, so
        channel surgery never runs concurrently with itself."""
        self._van_rebind_pending = True

    def _van_rebind(self) -> None:
        """The durable tier failed over: rebind every member control/
        event channel to the promoted endpoint (same incarnation-keyed
        ids — the new van has fresh channel state, both sides reset to
        seq 1) and RE-SEND every unresolved submit (whatever sat in the
        dead van's single-slot channels died with it; members dedup by
        rid, so a duplicate is absorbed and a lost one re-delivered).
        The blackboard and ledger need no rebinding — their tables
        re-target inside :class:`~hetu_tpu.ps.replica.
        ReplicatedPSTable`."""
        if self._replica is None:
            return
        self._van_rebind_pending = False
        self._van_gen = self._replica.incarnation
        self.port = self._replica.primary[1]
        with trace.span("ctrl.van_rebind",
                        {"incarnation": int(self._van_gen)},
                        cat="ctrl"):
            inc = self.svc.ctrl_incarnation
            with self._lock:
                bases = dict(self._ch_bases)
            rebind_failed = False
            for slot, (sub, evb) in sorted(bases.items()):
                try:
                    ch = self._ctrl_chan(_fenced_chan(sub, inc))
                except Exception:
                    traceback.print_exc()
                    # this slot is still bound to the dead van: the
                    # pending flag was cleared at entry, so re-arm it
                    # below or the slot never rebinds (a SECOND fault
                    # mid-rebind would strand it forever)
                    rebind_failed = True
                    continue
                with self._lock:
                    old = self._out.get(slot)
                    self._out[slot] = (ch, threading.Lock(), [1])
                if old is not None:
                    # deferred close: a _send may be inside the old
                    # channel — closing now frees its fd for kernel
                    # reassignment mid-op
                    from hetu_tpu.ps.replica import retire_handle
                    retire_handle(old[0])
                self._start_listener(slot, evb)
            if rebind_failed:
                self._van_rebind_pending = True
            with self._lock:
                pending = [r for r in self._requests.values()
                           if not r.done.is_set()]
            for r in pending:
                if r.member is not None and r.sent:
                    try:
                        self._send(r.member, {"cmd": "submit",
                                              "rid": r.rid, **r.msg})
                        r.routed_at = time.monotonic()
                    except Exception:
                        # the member did not hear the re-send (its own
                        # rebind may be lagging): PARK the rid so the
                        # unrouted sweep re-routes it — a sent+owned
                        # request is otherwise in nobody's recovery
                        # scope (the lease never expires for a beating
                        # member, and the replay nudge only re-emits
                        # COMPLETED records)
                        with self._lock:
                            r.sent = False
                            self._unrouted.setdefault(
                                r.rid, time.monotonic() + float(
                                    r.msg.get("timeout_s",
                                              self.request_timeout_s)))
                else:
                    self._route(r)
        self.metrics.inc("van_rebinds")

    def _start_listener(self, slot: int, event_ch: int) -> None:
        old = self._listeners.get(slot)
        if old is not None:
            old[1].set()
        stop = threading.Event()
        t = threading.Thread(
            target=self._event_loop,
            args=(slot, _fenced_chan(event_ch,
                                     self.svc.ctrl_incarnation), stop),
            daemon=True)
        self._listeners[slot] = (t, stop)
        t.start()

    # ---- the controller ledger (durable RAM, O(delta) per change) ----
    def _snapshot(self) -> dict:
        """The full recoverable state — everything a takeover cannot
        re-derive from lease rows or member-side records: rid→member
        ownership, retry budgets, original request messages, half-open
        drains, per-slot channel bases, id high-waters.  Written only
        at COMPACTION (amortized); the per-change path appends O(delta)
        records instead."""
        with self._lock:
            return {
                "rid": self._rid_seq, "cid": self._ctrl_seq,
                "channels": {str(s): list(b)
                             for s, b in self._ch_bases.items()},
                "requests": {str(r.rid): {
                    # an ownership mid-send is NOT journaled (member
                    # None = orphan = the takeover re-routes; if the
                    # send actually landed, the duplicate submit is
                    # absorbed by the rid dedup, token-identically)
                    "msg": r.msg,
                    "member": r.member if r.sent else None,
                    "retries": r.retries}
                    for r in self._requests.values()
                    if not r.done.is_set()},
                "resolved": {str(k): v
                             for k, v in self._resolved.items()},
                "drains": {str(k): dict(v)
                           for k, v in self._drain_journal.items()},
                "autoscaler": dict(self._autoscaler_state)
                if self._autoscaler_state else None,
            }

    # ---- warm autoscaler takeover (the control loop's durable RAM) ----
    def journal_autoscaler(self, state: dict, *,
                           sync: bool = True) -> None:
        """Journal the autoscaler's exported state (streaks, cooldown
        elapsed times, active set) into the ledger alongside accepts.
        ``sync=True`` for ACTION ticks (a lost scale action must not be
        repeated by a cold successor); hold ticks may coalesce — each
        record is a full upsert, so losing one costs staleness, never
        corruption."""
        with self._lock:
            self._autoscaler_state = dict(state)
        rec = {"s": dict(state)}
        if sync:
            self._append_ledger([rec])
        else:
            self._queue_delta(rec)

    def autoscaler_state(self) -> Optional[dict]:
        """The journaled autoscaler state (after a takeover: replayed
        from the ledger) — what a resumed control loop warms up from."""
        with self._lock:
            return dict(self._autoscaler_state) \
                if self._autoscaler_state else None

    def _append_ledger(self, records) -> None:
        """Synchronously journal delta records (accept / drain / spawn
        transitions — the load-bearing writes).  A full delta region
        triggers compaction: the CURRENT state (which already contains
        everything the records describe — state mutates before it
        journals) becomes the new base in one atomic frame, and the
        records are therefore covered without re-append.  The old
        snapshot ledger's refuse-accepts cliff is gone: sustained
        accepts cost O(record) bytes each, plus an amortized O(state)
        compaction."""
        with self._journal_lock:
            self._append_records_locked(list(records))

    def _append_records_locked(self, records) -> None:
        ci = self.svc.ctrl_incarnation
        try:
            try:
                self._ledger.append(records, ctrl_inc=ci)
            except _mb.LedgerCompactionNeeded:
                self._ledger.compact(self._snapshot(), ctrl_inc=ci)
        except _mb.ControllerFenced:
            self._fenced = True
            raise

    def _queue_delta(self, rec: dict) -> None:
        """Coalesced (route/resolve) records: flushed by the poll loop
        in one append frame.  Losing them with the controller is safe
        by the replay's own invariants — an unjournaled owner re-routes
        and the rid dedup absorbs the duplicate; a lost resolution is
        recovered from re-announced ``_done_log`` records — only the
        ACCEPT record is load-bearing for zero loss and stays
        synchronous."""
        with self._lock:
            self._pending_deltas.append(rec)
            self._journal_dirty = True

    def _journal(self) -> None:
        """Flush the coalesced delta queue (poll loop / close).  On
        failure the batch is re-queued AT THE FRONT so per-rid record
        order survives the retry."""
        with self._journal_lock:
            with self._lock:
                batch = self._pending_deltas
                self._pending_deltas = []
                self._journal_dirty = False
            if not batch:
                return
            try:
                self._append_records_locked(batch)
            except Exception:
                with self._lock:
                    self._pending_deltas = batch + self._pending_deltas
                    self._journal_dirty = True
                raise

    def _compact_ledger(self) -> None:
        """One amortized full-state write: at takeover (a fresh base
        under the new incarnation) and proactively from the poll loop
        before the delta region forces it mid-accept."""
        with self._journal_lock:
            with self._lock:
                batch = self._pending_deltas
                self._pending_deltas = []
                self._journal_dirty = False
            # the snapshot subsumes any queued deltas (state mutates
            # before journaling), so the batch just drops
            del batch
            try:
                self._ledger.compact(self._snapshot(),
                                     ctrl_inc=self.svc.ctrl_incarnation)
            except _mb.ControllerFenced:
                self._fenced = True
                raise

    @staticmethod
    def _replay_ledger(got: dict) -> dict:
        """Base snapshot + delta records → the snapshot-shaped state a
        takeover adopts.  Every record application is an idempotent
        upsert, so replay converges whatever the interleaving of
        coalesced flushes and compactions was."""
        state = got.get("state") or {}
        requests = dict(state.get("requests") or {})
        resolved = OrderedDict(state.get("resolved") or {})
        drains = dict(state.get("drains") or {})
        channels = dict(state.get("channels") or {})
        autoscaler = state.get("autoscaler") or None
        rid_seq = int(state.get("rid", 0))
        cid_seq = int(state.get("cid", 0))
        for d in got.get("deltas") or ():
            if "a" in d:
                rid, msg = d["a"]
                rid_seq = max(rid_seq, int(rid))
                requests[str(int(rid))] = {"msg": msg, "member": None,
                                           "retries": 0}
            elif "o" in d:
                rid, member, retries = d["o"]
                rec = requests.get(str(int(rid)))
                if rec is not None:
                    rec["member"] = member
                    rec["retries"] = int(retries)
            elif "r" in d:
                rid, status = d["r"]
                requests.pop(str(int(rid)), None)
                resolved[str(int(rid))] = status
            elif "d" in d:
                xid, rec = d["d"]
                if rec is None:
                    drains.pop(str(xid), None)
                else:
                    drains[str(xid)] = dict(rec)
            elif "c" in d:
                slot, sub, evb = d["c"]
                channels[str(int(slot))] = [int(sub), int(evb)]
            elif "q" in d:
                rid_seq = max(rid_seq, int(d["q"][0]))
                cid_seq = max(cid_seq, int(d["q"][1]))
            elif "s" in d:
                # autoscaler state: each record is a full upsert —
                # the LAST one wins, whatever compaction interleaving
                autoscaler = dict(d["s"])
        while len(resolved) > 1024:
            resolved.popitem(last=False)
        return {"rid": rid_seq, "cid": cid_seq, "channels": channels,
                "requests": requests, "resolved": resolved,
                "drains": drains, "autoscaler": autoscaler}

    def _wait_joined(self, slots, timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self._spawn_timeout_s)
        want = set(int(s) for s in slots)
        while time.monotonic() < deadline:
            self.poll()
            if want <= set(self.svc.present_slots()):
                return
            time.sleep(0.05)
        raise TimeoutError(f"members {sorted(want)} did not join within "
                           f"the spawn window")

    # ---- wire helpers ----
    def _send(self, slot: int, msg: dict, *, timeout_s: float = 2.0,
              attempts: int = 2, observe_rtt: bool = True) -> None:
        """One ordered control send with bounded retry: same-seq blob
        resend is idempotent, so a transport wobble retries safely; a
        member that stays unreadable (suspended/dead) surfaces as the
        TimeoutError the router treats as 'pick someone else'.

        ``observe_rtt=False`` keeps a send out of the link-health EWMA:
        the fleet scrape uses a deliberately tiny timeout, and letting
        its routine timeout against a momentarily busy member read as
        evidence of a GRAY LINK would open the degrade window — whose
        active probe pings then stall every poll sweep for members
        that were never degraded at all."""
        if self._fenced:
            raise ConnectionError(
                "controller fenced: a newer incarnation owns the fleet")
        ent = self._out.get(slot)
        if ent is None:
            raise ConnectionError(f"member {slot} has no control channel")
        ch, lock, seq = ent
        # every command carries the incarnation: the member-side fence
        # rejects a stale controller's writes wherever they land
        payload = json.dumps(
            {**msg, "ci": self.svc.ctrl_incarnation}).encode()
        t0 = time.monotonic()
        try:
            with lock:
                _mb.control_rpc(
                    lambda: ch.put(payload, seq[0], timeout_s=timeout_s),
                    attempts=attempts, base_s=0.05,
                    op=f"send[{msg.get('cmd')}]", link=f"ctrl->m{slot}",
                    is_transient=lambda e: isinstance(
                        e, (TimeoutError, ConnectionError, RuntimeError)))
                seq[0] += 1
        finally:
            # every control send doubles as a link probe — failures
            # included (a send that burned its whole retry budget is the
            # strongest degradation signal there is)
            if observe_rtt:
                self._observe_rtt(slot, time.monotonic() - t0)

    def _observe_rtt(self, slot: int, rtt_s: float) -> None:
        prev = self._rtt.get(slot)
        ewma = rtt_s if prev is None else 0.7 * prev + 0.3 * rtt_s
        self._rtt[slot] = ewma
        base = self._rtt_floor()
        if base is None:
            return
        if ewma > self._rtt_degraded_x * base:
            if slot not in self._degraded_t0:
                # the degrade window opens: recorded retroactively as a
                # serve.link_degraded span when the link recovers — the
                # recovery event RECOVERY_FOR pairs with fault.netem_degrade
                self._degraded_t0[slot] = trace.now_us()
                self.metrics.inc("links_degraded")
        elif ewma < 2.0 * base:
            t0d = self._degraded_t0.pop(slot, None)
            if t0d is not None:
                trace.complete("serve.link_degraded", t0d,
                               {"member": int(slot),
                                "rtt_ms": round(ewma * 1e3, 3)},
                               cat="serve")
                self.metrics.inc("links_recovered")

    def _rtt_floor(self) -> Optional[float]:
        """The healthiest observed link (EWMA floor) — the baseline a
        degraded link is judged against.  None until measured.  Floored
        at 2ms: on loopback the true RTT is microseconds and any GIL
        hiccup would read as a 5x 'degradation' — a link must be
        MILLISECONDS worse than its peers before it is called gray."""
        if not self._rtt:
            return None
        return max(min(self._rtt.values()), 2e-3)

    def _rtt_penalty(self, slot: int) -> float:
        """Routing penalty in 'equivalent in-flight requests': each
        multiple of the baseline RTT costs like one extra outstanding
        request, capped so a wedged link ranks worst but stays finite
        (a suspect lease, not this penalty, takes it out entirely)."""
        rtt = self._rtt.get(slot)
        base = self._rtt_floor()
        if rtt is None or base is None:
            return 0.0
        return min(max(rtt / base - 1.0, 0.0), 16.0)

    def _event_loop(self, slot: int, event_ch: int,
                    stop: threading.Event) -> None:
        ch = None
        seq = 1
        try:
            while not (stop.is_set() or self._stop.is_set()):
                if ch is None:
                    # bound in-loop, retried: this listener is usually
                    # (re)started by a van-failover rebind, i.e. MID
                    # promotion — a bind that raises once must not kill
                    # the thread, or the member's completions strand in
                    # its event channel until the NEXT failover (which
                    # may never come) while its emitter spins on an
                    # undrained single-slot mailbox
                    try:
                        ch = self._ctrl_chan(event_ch)
                    except Exception:
                        if stop.wait(0.2):
                            break
                        continue
                try:
                    raw = ch.get(seq, timeout_s=0.25)
                except TimeoutError:
                    continue
                except ConnectionError:
                    # a failover raises instantly (VanFailover) until
                    # the rebind replaces this listener: pace the loop
                    time.sleep(0.05)
                    continue
                except RuntimeError:
                    if self._stop.is_set():
                        break
                    time.sleep(0.1)
                    continue
                seq += 1
                try:
                    ev = json.loads(raw)
                except (ValueError, TypeError):
                    continue
                try:
                    self._dispatch_event(slot, ev)
                except Exception:
                    traceback.print_exc()
        finally:
            if ch is not None:
                ch.close()

    def _dispatch_event(self, slot: int, ev: dict) -> None:
        kind = ev.get("type")
        if kind == "done":
            self._on_done(slot, ev)
            return
        if kind == "metrics":
            with self._lock:
                self._member_metrics[slot] = ev.get("dump") or {}
                self._metrics_replies[slot] = \
                    self._metrics_replies.get(slot, 0) + 1
                self._scrape_pending.pop(slot, None)
            return
        xfer = self._xfers.get(int(ev.get("xfer", -1)))
        if xfer is not None:
            xfer["events"][kind] = ev
            xfer["evt"].set()

    # ---- fleet metric aggregation ----
    def _retire_member_metrics_locked(self, slot: int) -> None:
        """Caller holds ``self._lock``.  Fold the slot's last dump into
        the retired accumulator before a replacement incarnation's
        first reply overwrites it — counters and histograms only (sums
        stay monotone); a dead process's GAUGE is a stale level with
        nothing to aggregate into."""
        dump = self._member_metrics.pop(slot, None)
        if not dump:
            return
        from hetu_tpu.telemetry.registry import MetricsRegistry
        reg = MetricsRegistry.from_dump(self._retired_metrics)
        reg.merge({k: v for k, v in dump.items()
                   if v.get("type") != "gauge"})
        self._retired_metrics = reg.dump()

    def _drain_busy_slots(self) -> set:
        """Both ends of every active two-phase drain: off-limits to the
        scrape — a scrape frame queued ahead of (or holding the channel
        lock against) recv_migration/drain commands would stretch the
        preemption-critical hand-off for a routine metrics ask."""
        with self._lock:
            busy = set(self._draining)
            for d in self._drain_journal.values():
                busy.add(int(d.get("source", -1)))
                busy.add(int(d.get("target", -1)))
        return busy

    def _scrape_once(self, timeout_s: float = 0.1) -> list:
        """Ask every routable member for a registry dump (replies land
        asynchronously via the event loop).  A scrape is advisory, so
        the wire discipline is strict: VERY short timeout, one attempt,
        failures swallowed, and a member with an UNANSWERED ask is
        skipped until it replies (or a 3 s re-ask window lapses) — a
        put to a frozen member parks the van connection until the
        member reads it, and the single-threaded van would stall every
        other caller (including the lease sweep that is about to
        notice that very freeze) for the whole timeout.  The LAST dump
        stays current for a member that misses rounds."""
        now = time.monotonic()
        busy = self._drain_busy_slots()
        targets = []
        for s in self.svc.alive_slots():
            if not self.svc.state_of(s).healthy or s in busy:
                continue
            pending = self._scrape_pending.get(s)
            if pending is not None and now - pending < 3.0:
                continue  # don't pile blocking puts on a silent member
            targets.append(s)
        for slot in targets:
            self._scrape_pending[slot] = now
            try:
                self._send(slot, {"cmd": "metrics"}, timeout_s=timeout_s,
                           attempts=1, observe_rtt=False)
            except Exception:
                # the ask (very likely) never landed: re-ask after a
                # SHORT window, not the full reply window — a member
                # mid-jit-compile at its first ask would otherwise be
                # excluded from a whole synchronous scrape() budget
                self._scrape_pending[slot] = now - 2.5
        return targets

    def _scrape_guarded(self) -> None:
        try:
            self._scrape_once()
        except Exception:
            traceback.print_exc()
        finally:
            self._scrape_busy.clear()

    def _nudge_stale_guarded(self) -> None:
        """One replay-nudge round (one-shot side thread, like the
        scrape: a wedged member's channel must never stall the lease
        sweep).  For every member owning requests unresolved past
        ``_nudge_after_s``, ask it to re-emit their completion records
        — a no-op for rids still decoding, a recovery for any done
        event lost in transit."""
        try:
            now = time.monotonic()
            busy = self._drain_busy_slots()
            by_slot: dict = {}
            with self._lock:
                for r in self._requests.values():
                    if r.done.is_set() or r.member is None or \
                            not r.sent or r.routed_at is None or \
                            now - r.routed_at < self._nudge_after_s:
                        continue
                    by_slot.setdefault(r.member, []).append(r.rid)
            for slot, rids in by_slot.items():
                if slot in busy or \
                        self.svc.state_of(slot).state != "alive":
                    continue
                try:
                    self._send(slot, {"cmd": "replay", "rids": rids},
                               timeout_s=0.5, attempts=1,
                               observe_rtt=False)
                    self.metrics.inc("completion_replays_asked")
                except Exception:
                    pass  # the lease machinery owns unreachable members
        except Exception:
            traceback.print_exc()
        finally:
            self._nudge_busy.clear()

    def scrape(self, timeout_s: float = 3.0) -> dict:
        """One SYNCHRONOUS scrape: keep asking (under the same
        pending-window discipline as the cadence — a cadence ask
        already in flight counts, it is not re-sent) until every
        routable member has replied SINCE THIS CALL or the budget
        lapses.  Returns ``{slot: dump}`` of everything known —
        including the last dump of members that no longer answer."""
        if self._fenced:
            # fail FAST like every other fenced operation: spinning the
            # full budget on sends a newer incarnation rejects would
            # return pre-fence dumps dressed up as a fresh scrape
            raise ConnectionError(
                "controller fenced: a newer incarnation owns the fleet")
        with self._lock:
            before = dict(self._metrics_replies)
        deadline = time.monotonic() + float(timeout_s)
        while True:
            self._scrape_once()
            # recomputed every sweep: a member that dies (or enters a
            # drain window) mid-scrape drops out instead of pinning the
            # wait on a slot that will not be asked
            busy = self._drain_busy_slots()
            want = [s for s in self.svc.alive_slots()
                    if self.svc.state_of(s).healthy and s not in busy]
            with self._lock:
                done = all(self._metrics_replies.get(s, 0) >
                           before.get(s, 0) for s in want)
            if done or time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        return self.member_metric_dumps

    @property
    def member_metric_dumps(self) -> dict:
        """Last known registry dump per member slot (what the fleet
        export sums).  A SIGKILLed member keeps its final pre-kill
        dump here — and the same record sits in its span stream as the
        ``hetu_metrics`` black box."""
        with self._lock:
            return {s: dict(d) for s, d in self._member_metrics.items()}

    def fleet_metrics(self, *, scrape: bool = True,
                      timeout_s: float = 3.0):
        """ONE fleet-level registry over the whole pool: member
        counters and histograms merged under their own names (a
        counter here is the SUM across members; a histogram percentile
        is computed from summed buckets), member GAUGES under
        ``m<slot>.`` (a level like queue_depth has no fleet-wide sum —
        last-write-wins across members would silently report whichever
        slot merged last), and the controller's own metrics under
        ``ctrl.`` (its ``requests_ok`` and a member's are different
        events — summing them would double-count).  Export with
        ``.write_prometheus(path)`` / ``.prometheus_text()``."""
        from hetu_tpu.telemetry.registry import MetricsRegistry
        if scrape:
            self.scrape(timeout_s=timeout_s)
        reg = MetricsRegistry()
        with self._lock:
            retired = dict(self._retired_metrics)
        reg.merge(retired)  # dead incarnations' counters stay counted
        dumps = self.member_metric_dumps
        for slot in sorted(dumps):
            dump = dumps[slot]
            gauges = {k: v for k, v in dump.items()
                      if v.get("type") == "gauge"}
            reg.merge({k: v for k, v in dump.items()
                       if v.get("type") != "gauge"})
            reg.merge(gauges, prefix=f"m{slot}.")
        reg.merge(self.metrics.registry.dump(), prefix="ctrl.")
        # the controller's own durable-tier health (ledger append/
        # compaction bytes, replication lag, promotions observed) lives
        # in the process-default registry — exported under ctrl. like
        # the rest of its metrics
        from hetu_tpu.telemetry import default_registry
        if self._replica is not None:
            self._replica.export_lag()
        reg.merge({k: v for k, v in default_registry.dump().items()
                   if k.startswith(MemberHarness._DURABLE_TIER_METRICS)},
                  prefix="ctrl.")
        reg.gauge("fleet.members_reporting",
                  help="member slots with a scraped registry dump"
                  ).set(len(dumps))
        reg.gauge("fleet.members_alive").set(len(self.svc.alive_slots()))
        return reg

    def start_health_monitor(self, rules=None, *, interval_s: float = 0.5,
                             history_s: float = 120.0, **rule_kw):
        """Host the live health plane on this controller: a
        :class:`~hetu_tpu.telemetry.health.HealthMonitor` loop over the
        cadence-scraped ``fleet_metrics()`` view plus (when telemetry
        streams are on) a streaming tail of the workdir for the fleet
        doctor's evidence.  ``rules`` defaults to
        :func:`~hetu_tpu.telemetry.health.default_fleet_rules` compiled
        from this pool's ``slo_classes``; ``rule_kw`` (``burn_windows``,
        ``burn_budget``, ``burn_factor``, ``window_s``, ...) tunes that
        compilation — tests and benches shrink the burn windows to
        match runs shorter than five minutes.

        Alert state rides ``fleet_metrics()`` as ``ctrl.health.*``
        (active gauge, fired/resolved counters, doctor verdict count),
        and every transition is a ``health.alert`` instant on this
        process's span stream — alerts are themselves telemetry.
        """
        from hetu_tpu.telemetry.health import (
            HealthMonitor, default_fleet_rules,
        )
        if self.health_monitor is not None:
            raise RuntimeError("health monitor already running")
        if rules is None:
            rules = default_fleet_rules(self._slo_classes, **rule_kw)
        mon = HealthMonitor(
            rules,
            # scrape=False: the poll loop's cadence scrape feeds the
            # dumps — the monitor must never block on a wedged member
            source=lambda: self.fleet_metrics(scrape=False).dump(),
            tail=(self.workdir if self._telemetry_streams else None),
            interval_s=interval_s, history_s=history_s,
            registry=self.metrics.registry)
        self.health_monitor = mon
        mon.start()
        return mon

    def _on_done(self, slot: int, ev: dict) -> None:
        req = self._requests.get(int(ev.get("rid", -1)))
        if req is None or req.done.is_set():
            return  # late duplicate from a failed-over member: first wins
        status = ev.get("status", "error")
        if status in ("error", "shutdown"):
            with self._lock:
                stale = req.member != slot
            if stale:
                return  # an old owner's drain echo; the new owner decides
            if req.retries < self.max_retries:
                # the member failed the request without serving it (engine
                # death drain, poisoned admission): fold re-prefill on a
                # peer = resubmit the original record elsewhere
                req.retries += 1
                self.metrics.inc("requests_rerouted")
                self._route(req, exclude={slot})
                return
        self._resolve(req, status, tokens=ev.get("tokens", ()),
                      ttft_s=ev.get("ttft_s"))

    def _resolve(self, req: PoolRequest, status: str, *, tokens=(),
                 ttft_s=None) -> None:
        t0 = trace.now_us()
        with self._lock:
            if req.done.is_set():
                return
            if req.member is not None:
                self._inflight[req.member] = max(
                    self._inflight.get(req.member, 1) - 1, 0)
            req.tokens = [int(t) for t in tokens]
            req.status = status
            req.ttft_s = ttft_s
            req.done.set()
            # evict: a long-lived controller must not retain every
            # completed request forever (a late duplicate completion
            # for an evicted rid is simply ignored by _on_done)
            self._requests.pop(req.rid, None)
            self._resolved[req.rid] = status
            while len(self._resolved) > 1024:
                self._resolved.popitem(last=False)
        self.metrics.inc(f"requests_{status}")
        tenant = req.msg.get("tenant")
        if tenant:
            self.metrics.note_tenant(tenant, f"requests_{status}")
            if status == "shed":
                self.metrics.note_tenant(tenant, "shed")
        if ttft_s is not None:
            self.metrics.observe_ttft(float(ttft_s), tenant=tenant)
        # the terminal leg of the rid's causal chain (a SPAN, not an
        # instant: the fleet stitcher binds flow arrows to slices)
        trace.complete("serve.resolve",
                       t0, {"rid": req.rid, "status": status},
                       cat="serve")
        # resolution journaling is COALESCED (flushed by the poll loop
        # as one multi-record append): losing it with the controller is
        # safe — a resolution is recovered from the members'
        # re-announced ``_done_log`` records, token-identically — while
        # the accept record (the zero-loss contract) stays synchronous.
        self._queue_delta({"r": [req.rid, status]})

    # ---- routing ----
    def _routable(self, exclude=()) -> list:
        alive = set(self.svc.alive_slots())
        with self._lock:
            return [s for s in alive
                    if s not in exclude and s not in self._draining
                    and s not in self._quarantined
                    and self.svc.state_of(s).healthy]

    def _route(self, req: PoolRequest, *, exclude=None) -> None:
        exclude = set(exclude or ())
        while True:
            with self._lock:
                cands = self._routable(exclude)
                if not cands:
                    break
                # least-loaded, where "load" counts both outstanding
                # requests AND the link penalty: a member behind a
                # degraded link serves fewer requests per unit time, so
                # its slower wire is priced like extra queue depth
                slot = min(cands,
                           key=lambda s: self._inflight.get(s, 0) +
                           self._rtt_penalty(s))
                prev = req.member
                req.member = slot
                req.sent = False
                self._inflight[slot] = self._inflight.get(slot, 0) + 1
                if prev is not None:
                    self._inflight[prev] = max(
                        self._inflight.get(prev, 1) - 1, 0)
            try:
                self._send(slot, {"cmd": "submit", "rid": req.rid,
                                  **req.msg})
                req.sent = True
                req.routed_at = time.monotonic()
                trace.instant("serve.route",
                              {"rid": req.rid, "member": int(slot)},
                              cat="serve")
                # ownership journaling is coalesced like resolutions:
                # by the replay's own invariant, losing it is safe —
                # an unjournaled owner reads member=None, the takeover
                # re-routes, and the duplicate submit is absorbed by
                # the rid dedup token-identically.  Only the ACCEPT
                # record is load-bearing for zero loss.
                self._queue_delta({"o": [req.rid, int(slot),
                                         req.retries]})
                with self._lock:
                    self._unrouted.pop(req.rid, None)
                return
            except Exception as e:
                _fleet_event("route.send_fail",
                             {"rid": req.rid, "member": int(slot),
                              "error": f"{type(e).__name__}: {e}"})
                with self._lock:
                    self._inflight[slot] = max(
                        self._inflight.get(slot, 1) - 1, 0)
                    req.member = None
                exclude.add(slot)
        _fleet_event("route.park",
                     {"rid": req.rid,
                      "exclude": sorted(int(s) for s in exclude),
                      "states": [[int(m.slot), m.state,
                                  m.suspect_reason]
                                 for m in self.svc.members]})
        # no routable member RIGHT NOW (every member suspect during a
        # durable-tier failover's blind window, a mid-rebind wire, the
        # whole fleet draining): the request is JOURNALED, so it must
        # resolve, not error out — park it and let the poll loop
        # re-route once somebody is routable again.  Only outliving
        # its own deadline turns the outage into an error.
        with self._lock:
            if req.rid not in self._unrouted:
                self._unrouted[req.rid] = time.monotonic() + float(
                    req.msg.get("timeout_s", self.request_timeout_s))
        self.metrics.inc("requests_routing_deferred")

    def submit(self, prompt, *, max_tokens: int = 16, eos_id=None,
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None,
               slo: Optional[str] = None) -> PoolRequest:
        rid = self._next_rid()
        msg = {"prompt": [int(t) for t in prompt],
               "max_tokens": int(max_tokens), "eos_id": eos_id,
               "timeout_s": float(timeout_s if timeout_s is not None
                                  else self.request_timeout_s)}
        if tenant is not None:
            # the tenant tag rides the wire into the member (span args)
            # and the journal (a takeover keeps the attribution)
            msg["tenant"] = str(tenant)
        if slo is not None:
            # the SLO class name rides the same way — the member
            # scheduler maps it to (priority, weight) from its spawn
            # config's slo_classes; an unknown name is best-effort
            msg["slo"] = str(slo)
        req = PoolRequest(rid, msg)
        # the controller-side head of the rid's causal chain: the fleet
        # stitcher links this span to the member-side serve.request and
        # the terminal serve.resolve by the shared rid arg
        attrs = {"rid": rid}
        if tenant is not None:
            attrs["tenant"] = str(tenant)
        with trace.span("serve.submit", attrs, cat="serve"):
            with self._lock:
                self._requests[rid] = req
            # accepted ⇒ durable, BEFORE routing: once this ONE delta
            # record lands, a controller death at ANY later point still
            # resolves the request (the zero-lost-accepted-requests
            # contract).  O(record) bytes — not a full snapshot — so
            # sustained accepts never hit a capacity cliff (a full
            # delta region compacts and continues).  A journal failure
            # still REFUSES the accept.
            try:
                self._append_ledger([{"a": [rid, msg]}])
            except Exception:
                with self._lock:
                    self._requests.pop(rid, None)
                raise
            self.metrics.inc("pool_requests")
            self.metrics.note_tenant(tenant, "requests")
            self._route(req)
        return req

    def generate(self, prompt, *, max_tokens: int = 16, eos_id=None,
                 timeout_s: Optional[float] = None,
                 tenant: Optional[str] = None,
                 slo: Optional[str] = None) -> dict:
        req = self.submit(prompt, max_tokens=max_tokens, eos_id=eos_id,
                          timeout_s=timeout_s, tenant=tenant, slo=slo)
        # generous backstop over the serving deadline: a failover or a
        # suspended-then-resumed member must not strand the waiter
        if not req.done.wait(timeout=req.msg["timeout_s"] + 30.0):
            self._resolve(req, "timeout")
        return {"id": req.rid, "status": req.status or "ok",
                "tokens": list(req.tokens), "ttft_s": req.ttft_s}

    # ---- membership / failover ----
    def _sweep_unrouted(self) -> None:
        """Re-route parked requests once somebody is routable again;
        only a request that outlived its own deadline errors out."""
        with self._lock:
            items = list(self._unrouted.items())
        if not items:
            return
        now = time.monotonic()
        for rid, deadline in items:
            with self._lock:
                req = self._requests.get(rid)
            if req is None or req.done.is_set():
                with self._lock:
                    self._unrouted.pop(rid, None)
                continue
            if now > deadline:
                with self._lock:
                    self._unrouted.pop(rid, None)
                self._resolve(req, "error")
                self.metrics.inc("requests_rejected_no_member")
            elif self._routable():
                # the entry is NOT popped first: a failed route re-park
                # (setdefault) must keep the ORIGINAL deadline, or a
                # request could outlive its own budget forever while
                # members are alive but unreachable
                self._route(req)

    def _poll_loop(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            try:
                self.poll()
            except Exception:
                traceback.print_exc()  # the poll must survive anything
            # the durable tier failed over: rebind channels + re-send
            # unresolved submits (the poll loop owns channel surgery)
            if self._van_rebind_pending and not self._fenced:
                try:
                    self._van_rebind()
                except Exception:
                    traceback.print_exc()
                    self._van_rebind_pending = True  # retry next sweep
            try:
                self._sweep_unrouted()
            except Exception:
                traceback.print_exc()
            # fleet scrape on its cadence: triggered here (the poll loop
            # is the controller's one clock) but RUN in a one-shot side
            # thread — a member whose control channel is wedged must
            # stall the scrape, never the lease state machine
            if self._scrape_s > 0 and not self._fenced and \
                    time.monotonic() - self._last_scrape >= \
                    self._scrape_s and not self._scrape_busy.is_set():
                self._last_scrape = time.monotonic()
                self._scrape_busy.set()
                threading.Thread(target=self._scrape_guarded,
                                 daemon=True).start()
            if not self._fenced and \
                    time.monotonic() - self._last_nudge >= \
                    self._nudge_after_s and \
                    not self._nudge_busy.is_set():
                self._last_nudge = time.monotonic()
                self._nudge_busy.set()
                threading.Thread(target=self._nudge_stale_guarded,
                                 daemon=True).start()
            if self._journal_dirty and not self._fenced:
                try:
                    self._journal()
                except Exception:
                    traceback.print_exc()  # stays dirty; retried next
                    # sweep
            # proactive compaction: one amortized O(state) frame on the
            # poll thread beats paying it inside an accept
            if not self._fenced and self._ledger.needs_compaction(
                    margin_rows=max(
                        self._ledger.delta_capacity_rows() // 4, 16)):
                try:
                    self._compact_ledger()
                except Exception:
                    traceback.print_exc()

    def poll(self) -> int:
        """One membership sweep; returns how many members failed over.
        Serialized by ``_poll_lock``: the background poll thread and
        direct callers (``revive_member``'s join wait, tests) share one
        lease state machine."""
        with self._poll_lock:
            return self._poll_locked()

    def _poll_locked(self) -> int:
        # a durable-tier failover stalls every member's beats while the
        # pair promotes: grant the lease grace BEFORE this sweep so the
        # window never reads as member silence (and a loss that still
        # slips through is forgiven once the member's beats resume)
        if self._replica is not None and \
                self._replica.incarnation != self._mb_van_seen:
            self._mb_van_seen = self._replica.incarnation
            self.svc.note_van_failover()
        try:
            events = self.svc.poll()
        except _mb.ControllerFenced:
            # a newer incarnation owns the fleet: this controller is a
            # zombie — stop acting, refuse every further write, and let
            # the operator loop (controller_main) exit cleanly WITHOUT
            # touching the members it no longer owns
            self._fenced = True
            self.metrics.inc("controller_fenced")
            return 0
        n = 0
        if events:
            states = [[int(m.slot), m.state] for m in self.svc.members]
            for kind, slot in events:
                _fleet_event("membership.event",
                             {"kind": str(kind), "member": int(slot),
                              "states": states})
        for kind, slot in events:
            if kind == "suspect":
                self._suspect_t0[slot] = trace.now_us()
                self.metrics.inc("members_suspected")
            elif kind == "clear":
                t0 = self._suspect_t0.pop(slot, None)
                if t0 is not None:
                    # the retroactive recovery span: the partition HEALED
                    # — no loss, no rejoin, just a measured outage window
                    trace.complete("serve.member_suspect", t0,
                                   {"member": int(slot)}, cat="serve")
                self.metrics.inc("members_suspect_cleared")
            elif kind == "lost":
                self._suspect_t0.pop(slot, None)
                self.failover(slot)
                n += 1
            elif kind in ("join", "rejoin"):
                with self._lock:
                    self._quarantined.discard(slot)
                    self._draining.discard(slot)
                if kind == "rejoin":
                    self.metrics.inc("members_rejoined")
            elif kind == "left":
                with self._lock:
                    self._draining.discard(slot)
        # a live process whose ENGINE died reports healthy=0 in its
        # heartbeat: its queue drains 'error' member-side (each request
        # re-routes via its completion event), but stop routing NEW work
        # at it immediately
        for slot in self.svc.alive_slots():
            if not self.svc.state_of(slot).healthy and \
                    slot not in self._quarantined:
                with self._lock:
                    self._quarantined.add(slot)
                self.metrics.inc("members_engine_dead")
        # active link probe for DEGRADED slots: routing steers traffic
        # away from them, so without a probe no send would ever observe
        # the recovery and the degrade window would never close.  The
        # ping is a no-op command; its put waits on the member's ack of
        # the previous frame, so it measures the member's real read path
        for slot in list(self._degraded_t0):
            if self.svc.state_of(slot).state in ("alive", "suspect"):
                try:
                    self._send(slot, {"cmd": "ping"}, timeout_s=0.5,
                               attempts=1)
                except Exception:
                    pass  # the failure itself updated the RTT EWMA
        return n

    # ---- network-plane chaos (ps/netem.py over the command wire) ----
    def apply_net_fault(self, kind: str, member_idx: int,
                        duration_s: float = 1.0) -> None:
        """Route an injected network fault at a member by index:
        ``netem_partition`` = one-way EGRESS partition (the member's
        beats and completions black-hole; it still hears us — the
        asymmetric case), ``netem_degrade`` = gray link both ways
        (loss + latency + bandwidth cap).  Policies carry
        ``duration_s`` and heal themselves member-side — a heal
        command could not cross a cut link."""
        slot = int(member_idx) % self.n_members
        if kind == "netem_partition":
            msg = {"cmd": "netem", "direction": "egress",
                   "policy": {"partition": True,
                              "duration_s": float(duration_s)}}
        elif kind == "netem_degrade":
            msg = {"cmd": "netem", "direction": "both",
                   "policy": {"latency_s": 0.05, "jitter_s": 0.05,
                              "drop_p": 0.05, "rate_mbps": 50.0,
                              "duration_s": float(duration_s)}}
        else:
            raise ValueError(f"unknown net fault kind {kind!r}")
        self.metrics.inc(f"{kind}s_applied")
        self._send(slot, msg)

    def run_net_events(self, events) -> None:
        """Apply events drained from ``FaultInjector.pop_net_events()``
        — prefer draining with ``kinds=("netem_partition",
        "netem_degrade")`` so a mixed schedule's ``straggler`` events
        stay queued for the training supervisor that owns them; any
        straggler event handed here anyway is left untouched."""
        for kind, idx, duration_s in events:
            if kind == "straggler":
                continue
            self.apply_net_fault(kind, idx, duration_s)

    def failover(self, slot: int) -> int:
        """The member process is gone (lease expired past the suspect
        grace): every outstanding request re-routes to a survivor, which
        re-prefills from the original prompt — the cross-process fold
        (the dead process took the emitted tokens with it, and greedy
        decode regenerates them exactly)."""
        slot = int(slot)
        with self._lock:
            if slot in self._quarantined:
                return 0  # already failed over (engine-dead path)
            self._quarantined.add(slot)
            pending = [r for r in self._requests.values()
                       if r.member == slot and not r.done.is_set()]
        with trace.span("serve.failover", cat="serve") as sp:
            sp.set("member", slot)
            for req in pending:
                self._route(req, exclude={slot})
            sp.set("requests", len(pending))
        p = self.procs[slot]
        if p is not None and p.poll() is None:
            pass  # suspended-past-grace: declared lost but still exists;
            # revive_member replaces it (and reaps) if the operator asks
        self.metrics.inc("pool_failovers")
        self.metrics.inc("requests_failed_over", len(pending))
        return len(pending)

    # ---- planned drain (cross-process live migration) ----
    def _drain_begin(self, slot: int, target: int, *, codec: str,
                     close: bool, timeout_s: float) -> tuple:
        """The BEGIN phase of a two-phase drain, shared by
        :meth:`drain_member` and the chaos harness (which dies with the
        drain half-open on purpose): allocate the migrate channel,
        journal, recv_migration → mig_ready → drain.  Returns
        ``(xid, xfer)``; a failure inside rolls back its own journal
        record and xfer registration before re-raising.

        Migrate channels are incarnation-keyed like the command
        channels: the van outlives controllers, and a takeover's
        process-local ``_MIG_SEQ`` restarts — an un-keyed id could
        rebind a dead drain's channel, whose slot still holds an
        unconsumed frame at foreign seqs.  The half-open record is
        journaled BEFORE the first command: a controller death anywhere
        inside the two-phase window leaves a record its successor
        ABORTS back to a serving source (zero request loss)."""
        xid = next(_xfer_ids)
        xfer = {"evt": threading.Event(), "events": {}}
        self._xfers[xid] = xfer
        ch = _fenced_chan(CROSSHOST_MIGRATE_BASE + next(_MIG_SEQ),
                          self.svc.ctrl_incarnation)
        try:
            rec = {"source": int(slot), "target": int(target), "ch": ch,
                   "codec": codec, "state": "begin", "close": bool(close)}
            with self._lock:
                self._drain_journal[str(xid)] = rec
            self._append_ledger([{"d": [xid, rec]}])
            self._send(target, {"cmd": "recv_migration", "ch": ch,
                                "xfer": xid, "timeout_s": timeout_s})
            self._await_xfer(xfer, ("mig_ready",), timeout_s)
            self._send(slot, {"cmd": "drain", "ch": ch, "xfer": xid,
                              "codec": codec, "timeout_s": timeout_s})
        except Exception:
            self._xfers.pop(xid, None)
            with self._lock:
                dropped = self._drain_journal.pop(str(xid),
                                                  None) is not None
            if dropped:
                try:  # journal the rollback too (best effort — a
                    # takeover aborting a long-dropped record is benign)
                    self._append_ledger([{"d": [xid, None]}])
                except Exception:
                    traceback.print_exc()
            raise
        return xid, xfer

    def drain_member(self, slot: int, *, codec: Optional[str] = None,
                     close: bool = True, target: Optional[int] = None,
                     timeout_s: float = 60.0) -> int:
        """Two-phase planned drain: the source process exports its live
        KV slots + request records over the migrate wire, the target
        adopts, and only the target's confirmation releases the source
        (which then leaves cleanly and, with ``close``, exits).  Any
        failure before the commit aborts back to a still-serving source.
        Returns the number of requests migrated.

        ``codec`` overrides the pool default for THIS drain (a
        preemption-deadline drain picks "int8"; routine drains stay
        lossless)."""
        slot = int(slot)
        codec = self.migrate_codec if codec is None \
            else _migrate.check_codec(codec)
        if codec == "auto":
            codec = self._resolve_auto_codec(slot)
        with self._lock:
            if slot in self._draining or slot in self._quarantined:
                return 0
            self._draining.add(slot)
        xid = None
        try:
            with trace.span("serve.migrate", cat="serve") as sp:
                sp.set("member", slot)
                if target is None:
                    cands = self._routable({slot})
                    if not cands:
                        raise RuntimeError(
                            f"no surviving peer to drain member {slot} "
                            f"into")
                    target = min(cands,
                                 key=lambda s: self._inflight.get(s, 0))
                sp.set("target", int(target))
                xid, xfer = self._drain_begin(
                    slot, int(target), codec=codec, close=close,
                    timeout_s=timeout_s)
                ev = self._await_xfer(
                    xfer, ("adopted", "adopt_failed", "drain_failed"),
                    timeout_s)
                if ev.get("type") != "adopted":
                    # roll the source back before surfacing the failure
                    try:
                        self._send(slot, {"cmd": "drain_abort",
                                          "xfer": xid})
                    except Exception:
                        traceback.print_exc()
                    raise RuntimeError(
                        f"cross-process drain failed: {ev.get('error', ev)}")
                n = int(ev.get("n", 0))
                # evidence for callers/tests: how many LIVE KV slots the
                # peer adopted (mid-decode continuations, zero re-prefill)
                self.last_drain = {"source": slot, "target": int(target),
                                   "requests": n,
                                   "slots": int(ev.get("slots", 0)),
                                   "codec": codec}
                # the hand-off is real: re-home the outstanding rids so
                # the target's completion events find their requests
                with self._lock:
                    moved = [r for r in self._requests.values()
                             if r.member == slot and not r.done.is_set()]
                    for r in moved:
                        r.member = int(target)
                    self._inflight[int(target)] = \
                        self._inflight.get(int(target), 0) + len(moved)
                    self._inflight[slot] = 0
                self._send(slot, {"cmd": "drain_commit", "xfer": xid,
                                  "exit": bool(close)})
                with self._lock:
                    self._drain_journal.pop(str(xid), None)
                self._append_ledger([{"d": [xid, None]}])
                sp.set("requests", n)
        except Exception:
            with self._lock:
                self._draining.discard(slot)
                if xid is not None:
                    self._drain_journal.pop(str(xid), None)
            try:
                if xid is not None:
                    self._append_ledger([{"d": [xid, None]}])
            except Exception:
                traceback.print_exc()
            raise
        finally:
            if xid is not None:
                self._xfers.pop(xid, None)
        if close:
            p = self.procs[slot]
            if p is not None:
                try:
                    p.wait(timeout=10.0)
                except Exception:
                    p.kill()
        else:
            # the emptied member keeps serving (it never left the
            # blackboard): put it back in the routing set now
            with self._lock:
                self._draining.discard(slot)
        self.metrics.inc("pool_migrations")
        self.metrics.inc("requests_migrated", n)
        return n

    def _resolve_auto_codec(self, slot: int) -> str:
        """Controller-side ``codec="auto"`` resolution (the member's
        live token lengths are across a process boundary, so the
        payload is ESTIMATED from the model spec and the slot's
        outstanding requests — each assumed halfway through
        ``max_len``); the link rate is this process's best evidence
        (:func:`hetu_tpu.serve.migrate.known_link_mbps`: a netem cap,
        else a previously observed BULK transfer — never the tiny
        ack-paced control frames, whose bytes/latency ratio reads
        orders of magnitude below the real wire).  No evidence resolves
        to "none": on an unmeasured link, compression is a bet, not a
        measurement."""
        m = self.model
        head_dim = int(m["hidden_size"]) // int(m["num_heads"])
        per_tok = 2 * int(m["num_heads"]) * head_dim * 4  # f32 K+V
        tokens = max(self._inflight.get(slot, 0), 1) * \
            int(m["max_len"]) // 2
        payload = tokens * int(m["num_layers"]) * per_tok
        return _migrate.pick_codec(_migrate.known_link_mbps(),
                                   payload, "float32")

    @staticmethod
    def _await_xfer(xfer: dict, kinds, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for k in kinds:
                ev = xfer["events"].get(k)
                if ev is not None:
                    return ev
            xfer["evt"].wait(0.05)
            xfer["evt"].clear()
        raise TimeoutError(f"no {kinds} event within {timeout_s}s")

    # ---- membership operations ----
    def revive_member(self, slot: int) -> None:
        """Replace a lost/drained member with a FRESH process on the
        same slot (new incarnation, new control channels); it rejoins
        routing once its first heartbeat lands."""
        slot = int(slot)
        p = self.procs[slot]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        elif slot in self._member_pids:
            # a takeover-adopted member (the dead controller's child):
            # the pid is the only handle
            try:
                os.kill(self._member_pids[slot], _signal.SIGKILL)
            except OSError:
                pass
        self._spawn(slot)
        self._wait_joined([slot])
        with self._lock:
            self._quarantined.discard(slot)
            self._draining.discard(slot)
        self.metrics.inc("members_revived")

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(int(pid), 0)
            return True
        except OSError:
            return False

    # ---- lifecycle ----
    def close(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self.health_monitor is not None:
            try:
                self.health_monitor.stop()
            except Exception:
                pass
            self.health_monitor = None
        if self._replica is not None:
            self._replica.unregister(self._on_van_failover)
        t = getattr(self, "_poll_thread", None)
        if t is not None:
            t.join(timeout_s)
        if self._journal_dirty and not self._fenced:
            try:
                self._journal()  # flush coalesced resolutions
            except Exception:
                traceback.print_exc()
        if not self._fenced:
            # a FENCED zombie does not own these members anymore: no
            # shutdown commands, no kills — the new incarnation does
            for slot in range(self.n_members):
                try:
                    self._send(slot, {"cmd": "shutdown"}, timeout_s=0.5,
                               attempts=1)
                except Exception:
                    pass
        for _, (th, stop) in list(self._listeners.items()):
            stop.set()
        deadline = time.monotonic() + 5.0
        if not self._fenced:
            for p in self.procs:
                if p is None:
                    continue
                try:
                    p.wait(timeout=max(deadline - time.monotonic(), 0.1))
                except Exception:
                    p.kill()
                    p.wait()
            # takeover-adopted members have no Popen handle — wait for
            # their pids to honor the shutdown command, then SIGKILL
            # stragglers (they were reparented when their spawner died,
            # so there is no zombie-reap concern here)
            for slot, pid in list(self._member_pids.items()):
                while self._pid_alive(pid) and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                if self._pid_alive(pid):
                    try:
                        os.kill(pid, _signal.SIGKILL)
                    except OSError:
                        pass
        for slot, ent in list(self._out.items()):
            try:
                ent[0].close()
            except Exception:
                pass
        for obj in (getattr(self, "_bb", None),
                    getattr(self, "_ledger", None)):
            if obj is not None:
                try:
                    obj.close()
                except Exception:
                    pass
        if self._own_van:
            self._van.stop()


# ---------------------------------------------------------------------------
# controller process harness (the chaos kill target)
# ---------------------------------------------------------------------------

def _begin_drain_and_hang(pool: CrossProcessServingPool, *,
                          timeout_s: float = 30.0) -> None:
    """Chaos-harness helper: START a two-phase drain (recv_migration +
    drain sent, journaled half-open) and then hang forever — the
    controller 'dies' with the drain half-exported; only a SIGKILL ends
    this process.  The takeover must abort the drain back to a
    still-serving source with zero request loss."""
    with pool._lock:
        src = max(range(pool.n_members),
                  key=lambda s: pool._inflight.get(s, 0))
        tgt = min((s for s in range(pool.n_members) if s != src),
                  key=lambda s: pool._inflight.get(s, 0))
        pool._draining.add(src)
    pool._drain_begin(src, tgt, codec="none", close=True,
                      timeout_s=timeout_s)
    print("DRAIN_SENT", flush=True)
    while True:
        time.sleep(3600)


def controller_main(config_path: str) -> int:
    """Entry point for a spawned CONTROLLER process: build the pool
    against an EXTERNAL van (the durable tier must outlive this
    process — that is the whole point), submit a seeded request stream,
    and hold.  The chaos harness SIGKILLs/SIGSTOPs this process; its
    log carries the progress markers (``ACCEPTED k`` per accept,
    ``ALLDONE``, ``DRAIN_SENT``, ``FENCED``) the harness keys on.  A
    fenced wake-up (SIGSTOP → takeover → SIGCONT) exits WITHOUT
    touching the members the new incarnation owns."""
    cfg = json.loads(open(config_path).read())
    # the controller's own flight recorder, next to its members' (the
    # chaos harness SIGKILLs this process too — its accepted-request
    # spans must survive for the merged post-mortem)
    trace.open_process_stream(cfg["workdir"],
                              f"controller_p{os.getpid()}")
    pool = CrossProcessServingPool(
        int(cfg.get("n_members", 2)), workdir=cfg["workdir"],
        model=cfg.get("model"), port=int(cfg["port"]), own_van=False,
        hb_ms=int(cfg.get("hb_ms", 80)),
        lease_s=float(cfg.get("lease_s", 0.6)),
        suspect_grace_s=float(cfg.get("suspect_grace_s", 0.5)),
        request_timeout_s=float(cfg.get("request_timeout_s", 120.0)),
        deaf_ack_s=cfg.get("deaf_ack_s"),
        van_spec=cfg.get("van"),
        member_env={"JAX_PLATFORMS": "cpu"})
    print("READY", flush=True)
    try:
        ac = cfg.get("autoscale")
        if ac:
            # the soak's controller-kill target: make >= 1 JOURNALED
            # scale decision before the chaos harness SIGKILLs this
            # process, so the takeover can prove the successor resumes
            # the loop's RAM warm (no duplicate action)
            from hetu_tpu.traffic.autoscale import (AutoscalePolicy,
                                                    Autoscaler)
            for s in ac.get("park", []):
                pool.drain_member(int(s), close=True)
            scaler = Autoscaler(
                pool, AutoscalePolicy(**ac["policy"]),
                active={int(s) for s in ac.get("active", [0])})
            for _ in range(int(ac.get("ticks", 1))):
                rec = scaler.tick()
                print(f"SCALED {rec['action']} {rec.get('slot', -1)}",
                      flush=True)
                time.sleep(float(ac.get("tick_gap_s", 0.1)))
        prompts = seeded_prompts(
            int(cfg.get("n_requests", 8)),
            int(cfg.get("prompt_seed", 0)),
            vocab=int(pool.model["vocab_size"]))
        gap = float(cfg.get("submit_gap_s", 0.05))
        drain_at = cfg.get("drain_at")
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(pool.submit(
                p, max_tokens=int(cfg.get("max_tokens", 24))))
            print(f"ACCEPTED {len(reqs)}", flush=True)
            if drain_at is not None and i + 1 == int(drain_at):
                _begin_drain_and_hang(pool)  # never returns
            time.sleep(gap)
        deadline = time.monotonic() + float(cfg.get("deadline_s",
                                                    300.0))
        while any(not r.done.is_set() for r in reqs) and \
                not pool.fenced and time.monotonic() < deadline:
            time.sleep(0.05)
        if not pool.fenced:
            print("ALLDONE", flush=True)
        hold_until = time.monotonic() + float(cfg.get("hold_s", 0.0))
        while time.monotonic() < hold_until and not pool.fenced:
            time.sleep(0.05)
    except _mb.ControllerFenced:
        pool._fenced = True  # fence mid-submit/mid-drain: exit below
    if pool.fenced:
        print("FENCED", flush=True)
        pool.close()  # fenced close: channels only, members untouched
        return 3
    pool.close()
    return 0


if __name__ == "__main__":
    import sys
    if sys.argv[1] == "--controller":
        sys.exit(controller_main(sys.argv[2]))
    sys.exit(member_main(sys.argv[1]))
