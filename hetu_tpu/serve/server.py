"""Inference front-end over the van's blob-channel transport.

Reuses the thread-per-connection C++ van server (csrc/hetu_ps_van.cpp —
the same single-slot acked blob channels the MPMD mailbox and its 16-pair
concurrency soak already exercise) as the wire: client ``i`` talks on a
dedicated request/response channel pair derived from its ``client_id``
(ids are caller-assigned, the same convention as van table ids), with
monotonically increasing seqs per channel, so every wire op inherits the
blob channel's idempotent-retry reliability.

Threads:
  * one listener per client id — blocks in a server-side blob GET (no
    polling frames while idle beyond the shutdown-check interval),
    submits to the scheduler, waits on the request's completion event
    with the per-request timeout, sends the response;
  * one engine loop — runs ``scheduler.step()`` whenever there is work
    (continuous batching: admissions interleave with decode steps).

Graceful shutdown: ``close()`` stops the loop, drains the scheduler (so
waiting listeners get 'shutdown' responses instead of hanging), joins
every thread, then stops the van if this server started it.
"""

from __future__ import annotations

import json
import threading
import time

from hetu_tpu.serve.scheduler import (
    ContinuousBatchingScheduler, Request, cancel_detached,
)

# channel namespace: far above the table/mailbox ids the tests use
SERVE_CHANNEL_BASE = 0x53525645  # 'SRVE'


def request_channel(client_id: int) -> int:
    return SERVE_CHANNEL_BASE + 2 * int(client_id)


def response_channel(client_id: int) -> int:
    return SERVE_CHANNEL_BASE + 2 * int(client_id) + 1


class InferenceServer:
    """Engine loop + wire listeners over one scheduler.

    ``max_clients=0`` is the LISTENER-LESS mode: only the engine loop,
    crash-requeue, and failover-grace machinery run — the deployment
    unit both pool flavors build on (serve/pool.py routes to it
    in-process; serve/crosshost.py wraps it in a member PROCESS whose
    submit/event channels and membership heartbeat replace the
    per-client listeners)."""

    def __init__(self, scheduler: ContinuousBatchingScheduler, *,
                 port: int = 0, max_clients: int = 4,
                 request_timeout_s: float = 60.0,
                 poll_s: float = 0.25, own_van: bool = True,
                 max_loop_errors: int = 3,
                 failover_grace_s: float = 10.0):
        """port=0 picks a free port; ``own_van=False`` attaches to a van
        already serving in this process (the server then must be handed
        that van's port).  ``max_loop_errors`` consecutive engine-loop
        exceptions (no successful step in between) declare the engine dead:
        the loop exits and ``healthy`` turns False.

        Request failover: every engine-loop exception requeues the
        in-flight requests (re-prefill from prompt + tokens emitted so
        far, bounded by the scheduler's ``max_requeues``) instead of
        failing them, so an engine crash followed by
        :meth:`restart_engine` within ``failover_grace_s`` loses ZERO
        accepted requests.  If no restart arrives inside the grace window
        (or ``failover_grace_s <= 0``), the queue drains with status
        'error' and new submits fail fast — the pre-failover behavior."""
        from hetu_tpu.ps import van
        self._van = van
        self.scheduler = scheduler
        self.metrics = scheduler.metrics
        self.request_timeout_s = float(request_timeout_s)
        self._poll_s = float(poll_s)
        self._own_van = own_van
        self._max_loop_errors = int(max_loop_errors)
        self._failover_grace_s = float(failover_grace_s)
        if own_van:
            self.port = van.serve(port)
        else:
            if not port:
                raise ValueError("own_van=False needs the running van's port")
            self.port = port
        self._stop = threading.Event()
        self.last_loop_error = None
        self._loop_dead = False
        self._restart_evt = threading.Event()
        self._grace_thread = None
        self._loop = threading.Thread(target=self._engine_loop, daemon=True)
        self._listeners = [
            threading.Thread(target=self._listen, args=(cid,), daemon=True)
            for cid in range(max_clients)]
        self._loop.start()
        for t in self._listeners:
            t.start()

    @property
    def healthy(self) -> bool:
        """True while the engine loop is alive and serving.  False once the
        loop gave up after ``max_loop_errors`` consecutive failures, died
        some other way, or the server was closed — callers should stop
        sending and restart/replace the server.  ``last_loop_error`` holds
        the final traceback when the engine failed."""
        return self._loop.is_alive() and not self._loop_dead

    # ---- engine loop ----
    def _engine_loop(self) -> None:
        consecutive = 0
        while not self._stop.is_set():
            try:
                if self.scheduler.has_work():
                    self.scheduler.step()
                    consecutive = 0
                else:
                    time.sleep(0.002)
            except Exception:
                # a step blowing up must not wedge the in-flight requests
                # (the listeners are waiting on their events) OR lose them:
                # requeue them for a retry / a restarted engine, keep the
                # evidence (traceback to stderr, repr for the operator, a
                # counter for dashboards)
                import traceback
                self.last_loop_error = traceback.format_exc()
                traceback.print_exc()
                self.metrics.inc("engine_loop_errors")
                consecutive += 1
                try:
                    self.scheduler.requeue_inflight()
                except Exception:
                    traceback.print_exc()  # never let cleanup kill the loop
                if consecutive >= self._max_loop_errors:
                    self._loop_dead = True
                    self.metrics.inc("engine_loop_dead")
                    self._arm_failover_grace()
                    return

    def _arm_failover_grace(self) -> None:
        """The engine is dead; the queue (incl. requeued in-flight work) is
        intact.  Hold it for ``failover_grace_s`` awaiting restart_engine;
        expire into the fail-fast drain so clients are never wedged on a
        restart that will not come."""
        if self._stop.is_set():
            return  # closing: close() drains with 'shutdown' itself
        if self._failover_grace_s <= 0:
            self._expire_failover()
            return

        restart_evt = self._restart_evt

        def grace():
            if not restart_evt.wait(self._failover_grace_s):
                self._expire_failover()

        self._grace_thread = threading.Thread(target=grace, daemon=True)
        self._grace_thread.start()

    def _expire_failover(self) -> None:
        import traceback
        if self._stop.is_set():
            # a close() raced the grace window: the scheduler already
            # drained 'shutdown' — an expiry drain here would flip the
            # reject status under the closed server (regression-tested
            # in tests/test_serve_server.py)
            return
        try:
            self.scheduler.drain("error", stop_accepting=True)
            self.metrics.inc("failover_expired")
        except Exception:
            traceback.print_exc()

    def cancel_failover_grace(self, timeout_s: float = 5.0) -> None:
        """Disarm a pending failover-grace timer without restarting.

        The pool's unplanned-failover path calls this after it has taken
        the dead member's queue — a later expiry drain would otherwise
        finish already-migrated bookkeeping with 'error' and flip the
        reject status under the new owner.  ``close()`` uses the same
        path so a closed server can never have the grace thread fire
        afterwards."""
        self._restart_evt.set()
        t = self._grace_thread
        if t is not None:
            try:
                t.join(timeout_s)
            except RuntimeError:
                # armed-but-not-yet-started: _arm_failover_grace assigns
                # the thread before start(), and a pool failover can land
                # in that window.  The event above is the one the thread
                # waits on, so it exits immediately once started — the
                # disarm already happened; there is nothing to wait for
                pass

    # ---- engine restart (request failover) ----
    def restart_engine(self, engine) -> None:
        """Swap in a fresh/recovered engine and resume serving: the
        scheduler re-adopts its queue (requeued in-flight requests
        re-prefill from prompt + tokens emitted so far), intake reopens,
        a new engine loop starts, and ``healthy`` recovers.  Call within
        ``failover_grace_s`` of the crash for the zero-loss guarantee."""
        if self._stop.is_set():
            raise RuntimeError("server is closed")
        if self._loop_dead:
            # the dying loop thread flips _loop_dead BEFORE it arms the
            # grace timer and exits; a caller polling `healthy` can land
            # in that window.  Join it first so the grace timer is armed
            # with the CURRENT event (cancellable below) and is_alive()
            # below reads the settled state.
            self._loop.join(timeout=10.0)
        self.cancel_failover_grace()      # cancel the pending grace timer
        self._restart_evt = threading.Event()
        self.scheduler.replace_engine(engine)
        self.last_loop_error = None
        self._loop_dead = False
        if not self._loop.is_alive():
            self._loop = threading.Thread(target=self._engine_loop,
                                          daemon=True)
            self._loop.start()
        self.metrics.inc("engine_restarts")

    # ---- one listener per client channel pair ----
    def _listen(self, cid: int) -> None:
        req_ch = self._van.BlobChannel("127.0.0.1", self.port,
                                       request_channel(cid))
        resp_ch = self._van.BlobChannel("127.0.0.1", self.port,
                                        response_channel(cid))
        seq = 1
        sent_seq = 0  # last response seq that reached the slot
        # idempotent-resubmission dedup: the client protocol is one
        # request in flight per channel pair, so remembering the LAST
        # request id per listener is sufficient — a timed-out client
        # that re-puts the same id gets the original request's result,
        # never a second generation (or a second token-budget charge)
        dedup: dict = {}
        try:
            while not self._stop.is_set():
                try:
                    raw = req_ch.get(seq, timeout_s=self._poll_s)
                except TimeoutError:
                    # reconnect probe: a client that RESTARTED with this
                    # id begins again at seq 1 while we wait at seq N+1 —
                    # without this it could never be served again.  An
                    # EMPTY read is the already-consumed seq-1 slot (ack
                    # frees the payload but keeps its seq), not a request.
                    if seq > 1:
                        try:
                            raw = req_ch.get(1, timeout_s=0.05)
                        except (TimeoutError, RuntimeError):
                            continue
                        if not raw:
                            continue
                        seq = 1
                    else:
                        continue
                except RuntimeError:
                    break  # van stopped under us
                resp = self._handle(raw, dedup)
                payload = json.dumps(resp).encode()
                for attempt in range(2):
                    try:
                        resp_ch.put(payload, seq,
                                    timeout_s=min(self.request_timeout_s,
                                                  10.0))
                        sent_seq = seq
                        break
                    except (TimeoutError, RuntimeError):
                        # unread slot: a client-side wire timeout left our
                        # previous response stored unacked, which would
                        # wedge this channel FOREVER (puts only overwrite
                        # acked slots).  Consume our own stale response
                        # (get acks it) and retry once; failing that, drop
                        # this response but keep the listener alive.
                        if attempt == 0 and sent_seq:
                            try:
                                resp_ch.get(sent_seq, timeout_s=0.2)
                                continue
                            except (TimeoutError, RuntimeError):
                                pass
                        self.metrics.inc("responses_dropped")
                        break
                seq += 1
        finally:
            req_ch.close()
            resp_ch.close()

    # ---- wire-format hooks (overridden by e.g. recsys.RecsysServer) ----
    def _build_request(self, msg: dict) -> Request:
        """Parse one request message into a scheduler Request.  Raise
        KeyError/TypeError/ValueError for a malformed message — the
        listener answers 'bad_request' without touching the scheduler.
        Subclasses serving a different workload (the CTR front-end)
        override this and :meth:`_build_response`; the listener/dedup/
        engine-loop machinery is shared."""
        if not msg["prompt"]:
            raise ValueError("empty prompt")
        return Request(
            prompt=[int(t) for t in msg["prompt"]],
            max_tokens=int(msg.get("max_tokens", 16)),
            eos_id=msg.get("eos_id"),
            timeout_s=min(float(msg.get("timeout_s",
                                        self.request_timeout_s)),
                          self.request_timeout_s))

    def _build_response(self, msg: dict, req: Request) -> dict:
        return {"id": msg.get("id"), "status": req.status or "ok",
                "tokens": list(req.tokens),
                "ttft_s": req.ttft_s}

    def _bad_request(self, err: Exception) -> dict:
        return {"id": None, "status": "bad_request", "error": str(err),
                "tokens": []}

    def _handle(self, raw: bytes, dedup: dict | None = None) -> dict:
        try:
            msg = json.loads(raw)
            req = self._build_request(msg)
        except (KeyError, TypeError, ValueError) as e:
            return self._bad_request(e)
        # dedup key includes the client's per-incarnation nonce: a
        # RESTARTED client reusing id 1 with a new prompt must not be
        # served the previous incarnation's answer.  A message WITHOUT a
        # nonce is undedupable for the same reason — (None, 1) would
        # collide across incarnations of a raw-JSON client.
        rid = None if msg.get("id") is None or msg.get("cn") is None \
            else (msg["cn"], msg["id"])
        if dedup is not None and rid is not None \
                and dedup.get("id") == rid:
            # a retried submit of the in-flight (or just-finished)
            # request: attach to the original instead of generating twice
            req = dedup["req"]
            self.metrics.inc("requests_deduped")
        else:
            self.scheduler.submit(req)
            if dedup is not None:
                dedup["id"], dedup["req"] = rid, req
        # event wait (not scheduler polling): the engine loop completes the
        # request and sets the event; the deadline here backstops a wedged
        # loop so the client always gets a response frame
        if not req.done.wait(timeout=req.timeout_s + self._poll_s + 5.0):
            # resolve 'timeout', not 'cancelled' — unless the request
            # finished in the race, in which case the finish guard keeps
            # its real terminal status.  Detached: this deadline exists
            # to backstop a WEDGED engine loop, which holds the
            # scheduler lock across the stuck step — a plain
            # scheduler.cancel would hang this handler on that lock and
            # the client would never get its response frame
            cancel_detached(self.scheduler, req, "timeout")
        return self._build_response(msg, req)

    # ---- lifecycle ----
    def close(self, timeout_s: float = 10.0) -> None:
        self._stop.set()  # set BEFORE the cancel: _expire_failover checks it
        self.cancel_failover_grace(timeout_s)  # a grace timer must not
        # outlive us; bounded by the CALLER's close budget
        self.scheduler.drain("shutdown", stop_accepting=True)
        self._loop.join(timeout_s)
        for t in self._listeners:
            t.join(timeout_s)
        if self._own_van:
            self._van.stop()


class InferenceClient:
    """Blocking client for one channel pair.  ``client_id`` must be unique
    per concurrently-connected client and < the server's ``max_clients``
    (the van-table-id convention: caller-assigned, concurrent collision =
    crossed wires).  A RESTARTED client may reuse its id: the listener
    detects the seq reset and resyncs."""

    def __init__(self, host: str, port: int, client_id: int, *,
                 connect_timeout_s: float = 20.0):
        from hetu_tpu.ps import van
        self._req = van.BlobChannel(host, port, request_channel(client_id),
                                    connect_timeout_s=connect_timeout_s)
        self._resp = van.BlobChannel(host, port, response_channel(client_id),
                                     connect_timeout_s=connect_timeout_s)
        self._seq = 0
        self._rid = 0  # request id: stable across retries of one generate
        import os as _os
        self._nonce = _os.urandom(4).hex()  # distinguishes incarnations

    def generate(self, prompt, *, max_tokens: int = 16, eos_id=None,
                 timeout_s: float = 120.0, deadline_s=None,
                 wire_retries: int = 1) -> dict:
        """prompt: token ids in → {'tokens': [...], 'status': ...} out.

        ``timeout_s`` bounds the WIRE wait (put + blocking get) of each
        attempt; ``deadline_s`` is the per-request serving deadline
        enforced by the scheduler (queue wait + decode), defaulting to
        ``timeout_s``.

        Idempotent resubmission: a timed-out attempt retries (up to
        ``wire_retries`` times) with the SAME request id — the server
        dedups on id, so a retry after a slow ack attaches to the
        original request instead of generating (and billing the token
        budget) twice.  A timed-out put reuses its seq (the frame never
        landed); a timed-out response re-puts at the next seq.
        """
        self._rid += 1
        msg = {"id": self._rid, "cn": self._nonce,
               "prompt": [int(t) for t in prompt],
               "max_tokens": int(max_tokens),
               "timeout_s": timeout_s if deadline_s is None
               else float(deadline_s)}
        if eos_id is not None:
            msg["eos_id"] = int(eos_id)
        return self._roundtrip(msg, timeout_s, wire_retries)

    def _roundtrip(self, msg: dict, timeout_s: float,
                   wire_retries: int = 1) -> dict:
        """One idempotent request/response exchange for an already-built,
        already-id-stamped message (the retry/dedup dance shared with the
        CTR client in serve/recsys.py)."""
        payload = json.dumps(msg).encode()
        last_exc: Exception = TimeoutError("generate: no attempts ran")
        for _attempt in range(max(int(wire_retries), 0) + 1):
            self._seq += 1
            try:
                self._req.put(payload, self._seq, timeout_s=timeout_s)
            except TimeoutError as e:
                # the frame never reached the slot (previous one unread):
                # this seq is still ours — reuse it on the next attempt
                self._seq -= 1
                last_exc = e
                continue
            try:
                return self._get_response(self._seq, timeout_s)
            except TimeoutError as e:
                last_exc = e
                # grace drain before resubmitting: the response may land
                # moments late — if so it IS our answer (ids are unique
                # per client incarnation); otherwise the drain attempt
                # leaves the slot for the listener's dedup response
                try:
                    resp = self._get_response(self._seq, 0.2)
                    if resp.get("id") == msg["id"]:
                        return resp
                except (TimeoutError, RuntimeError):
                    pass
                # else: resubmit the same id at the next seq; the server
                # dedups and answers there
        raise last_exc

    def _get_response(self, seq: int, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                return json.loads(self._resp.get(
                    seq, timeout_s=max(deadline - time.monotonic(), 0.05)))
            except RuntimeError as e:
                # rc=-5: the slot still holds a PREVIOUS incarnation's
                # response (this client restarted with a reused id); the
                # server overwrites it with our seq once it resyncs —
                # retry until the deadline
                if "rc=-5" not in str(e) or time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    def close(self) -> None:
        self._req.close()
        self._resp.close()
