"""Live KV-cache slot migration between serving engines.

PR 3 taught one `InferenceServer` to survive engine death by requeueing
in-flight requests and RE-PREFILLING them — correct, but the recovery
cost grows with context length (a 10k-token conversation re-forwards 10k
tokens).  This module is the other half the ROADMAP left open: hand the
LIVE KV slots to a peer engine so decoding continues token-for-token
with zero prefill — the difference between "recovers eventually" and
"users never notice" on preemptible capacity.  The same pattern Hetu's
PS tier already proves (state handed between processes over the van with
deterministic replay) applied at the serve tier.

Three layers, separable on purpose:

* **slot payloads** — :func:`pack` / :func:`unpack` serialize
  :class:`~hetu_tpu.serve.kv_cache.KVSlotSnapshot` lists (plus optional
  request records) into one self-describing byte string: magic + JSON
  header (cache geometry, per-slot metadata, body CRC) + raw K/V bytes.
  ``unpack`` re-validates everything — magic, version, geometry, body
  CRC — before any array is materialized, so a corrupt transfer fails
  clean with nothing adopted;
* **chunked wire** — :func:`send_payload` / :func:`recv_payload` move a
  payload over an existing van :class:`~hetu_tpu.ps.van.BlobChannel` as
  CRC-framed chunks at consecutive seqs.  Every frame is a single-slot
  acked blob put, idempotent under same-seq resend, so a transport drop
  mid-transfer reconnects and resumes at the unacked chunk instead of
  restarting the payload (tests/test_van_blob.py kills the connection
  between chunks);
* **orchestration** — :func:`migrate_inflight` moves every in-flight
  request from one scheduler to another: mid-decode requests carry
  their live slots, queued ones re-queue, and ANY failure re-adopts
  everything at the source and re-raises — migration either completes
  or leaves the source serving.

Request records (:func:`request_record` / :func:`request_from_record`)
are the wire form of a mid-decode ``Request``: prompt, emitted tokens,
fold watermark, deadline (as elapsed time — monotonic clocks do not
compare across processes), requeue count.  Decoding is greedy argmax
today, so there is no sampler/RNG state to carry; a sampling engine
extends the record here.

Paged engines (serve/kv_cache.py:PagedKVCache) speak this wire
unchanged: a paged export assembles each slot's LIVE pages into the
same contiguous truncated-rows snapshot (page ids are process-local
and meaningless on the wire — the adopter rebuilds page tables as it
imports), so payload size scales with live tokens either way, every
codec applies, and slot↔paged CROSS-ALLOCATOR drains work — the
rolling-upgrade path from a slot-engine fleet to a paged one.  A paged
adopter also RE-DEDUPS each imported slot back into its prefix index
(scheduler ``adopt_inflight`` → engine ``reindex_prefix``: page-boundary
hashes of the request's token stream registered against the imported
pages), so post-drain traffic sharing the migrated requests' prompts
keeps its prefix hit rate instead of re-prefilling until the pages age
out.
"""

from __future__ import annotations

import json
import struct
import threading
import time
import zlib

import numpy as np

from hetu_tpu.serve.kv_cache import KVSlotSnapshot

MAGIC = b"HTMG"
VERSION = 1
DEFAULT_CHUNK_BYTES = 1 << 20

# per-chunk frame header: magic, version, chunk index, total chunks,
# crc32 of this chunk's payload
_CHUNK_HDR = struct.Struct("<4sIIII")
# payload prefix: magic, version, JSON header length
_PAYLOAD_HDR = struct.Struct("<4sII")


class MigrationError(RuntimeError):
    """A slot transfer failed validation (geometry, CRC, framing).  The
    receiving side adopts NOTHING when this raises — partial adoption is
    the one outcome the wire format must make impossible."""


class MigrationTargetError(MigrationError):
    """The DESTINATION refused or failed the adoption (drained, killed,
    incompatible geometry).  A pool catches this specifically to retry
    the migration against a different peer — source-side and wire-layer
    failures raise plain exceptions, where retrying with another target
    would be futile."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bf16 etc. live in ml_dtypes, registered via jax
        import jax.numpy as jnp
        return np.dtype(jnp.dtype(name))


# ---------------------------------------------------------------------------
# request records
# ---------------------------------------------------------------------------

def request_record(req, *, now: float | None = None) -> dict:
    """The wire form of a mid-decode ``Request`` — everything a peer
    scheduler needs to continue it.  Deadlines travel as elapsed seconds
    since submission (``time.monotonic`` values are process-local)."""
    now = time.monotonic() if now is None else now
    return {
        "rid": int(req.rid),
        "prompt": [int(t) for t in req.prompt],
        "tokens": [int(t) for t in req.tokens],
        "folded": int(req.folded),
        "max_tokens": int(req.max_tokens),
        "eos_id": None if req.eos_id is None else int(req.eos_id),
        "timeout_s": req.timeout_s,
        "elapsed_s": 0.0 if req.submitted_at is None
        else max(now - req.submitted_at, 0.0),
        "requeues": int(req.requeues),
        "had_first_token": req.first_token_at is not None,
        # per-tenant attribution must survive the hand-off: the
        # adopter's serve.request span and accounting carry it forward
        "tenant": getattr(req, "tenant", None),
        # SLO class rides too — a migrated high-priority request must
        # keep its admission tier on the adopter's scheduler
        "slo": getattr(req, "slo", None),
    }


def request_from_record(rec: dict, *, now: float | None = None):
    """Rebuild a ``Request`` from :func:`request_record` output on the
    adopting side (cross-process migration; the in-process pool hands
    the live objects over instead so waiters keep their events)."""
    from hetu_tpu.serve.scheduler import Request
    now = time.monotonic() if now is None else now
    req = Request(
        prompt=list(rec["prompt"]), max_tokens=int(rec["max_tokens"]),
        eos_id=rec.get("eos_id"), timeout_s=rec.get("timeout_s"))
    req.rid = int(rec["rid"])
    req.tenant = rec.get("tenant")
    req.slo = rec.get("slo")
    req.tokens = list(rec["tokens"])
    req.folded = int(rec.get("folded", 0))
    req.requeues = int(rec.get("requeues", 0))
    req.submitted_at = now - float(rec.get("elapsed_s", 0.0))
    if rec.get("had_first_token"):
        # the migrated request already observed TTFT at the source; the
        # adopter must not re-observe it (exact value is source-local)
        req.first_token_at = req.submitted_at
    return req


# ---------------------------------------------------------------------------
# payload pack/unpack
# ---------------------------------------------------------------------------

CODECS = ("none", "bf16", "int8")


def check_codec(codec: str, *, allow_auto: bool = True) -> str:
    """Validate a KV wire codec name up front (pool construction, the
    per-drain override) so a typo fails where it was written, not at
    the first drain under a preemption deadline.  ONE home for the
    check — both pool flavors and both override points use it.
    ``"auto"`` (policy, not a wire format — :func:`pick_codec` resolves
    it per drain from the measured link rate) is accepted everywhere
    except the pack/unpack layer itself (``allow_auto=False``)."""
    if codec == "auto" and allow_auto:
        return codec
    if codec not in CODECS:
        raise ValueError(f"unknown migrate codec {codec!r}; expected "
                         f"one of {CODECS}" +
                         (" or 'auto'" if allow_auto else ""))
    return codec


# a link-rate sample must come from a transfer big enough that the
# payload, not per-frame ack pacing, dominated the wall time
MIN_RATE_SAMPLE_BYTES = 1 << 16


def measured_link_mbps(registry=None) -> float | None:
    """Observed bulk-transfer rate in Mbit/s — the op-span-derived half
    of the "netem-visible or op-span-derived" link-rate signal the auto
    drain codec uses.  The ONLY samples consulted are completed
    migration payload sends of at least ``MIN_RATE_SAMPLE_BYTES``
    (``migrate.wire.mbps``, recorded by :func:`send_payload`): the
    generic ``van.blob_put`` aggregate is dominated by tiny ack-paced
    control frames whose bytes/latency ratio reads orders of magnitude
    below the real wire — a "measurement" that would always escalate
    the codec on loopback.  Returns None until a real bulk transfer has
    been observed (no evidence = no compression)."""
    if registry is None:
        from hetu_tpu.telemetry import default_registry as registry
    g = registry.metrics().get("migrate.wire.mbps_last")
    if g is None:
        return None
    rate = float(g.value)
    return rate if rate > 0 else None


def known_link_mbps() -> float | None:
    """The best link-rate signal available in THIS process: an
    installed netem bandwidth cap (the emulated truth) wins, else the
    last observed bulk-transfer rate, else None."""
    from hetu_tpu.ps import van as _van
    em = getattr(getattr(_van, "_netem_hook", None), "__self__", None)
    if em is not None and hasattr(em, "current_rate_mbps"):
        rate = em.current_rate_mbps()
        if rate is not None:
            return rate
    return measured_link_mbps()


def estimate_payload_bytes(engine) -> int:
    """Uncompressed (codec="none") drain payload size for the engine's
    LIVE slots: what :func:`pack` would ship, from the cache's lengths
    and geometry — no export needed to decide a codec."""
    cache = engine.cache
    spec = cache.spec
    itemsize = _np_dtype(str(np.dtype(spec.dtype))).itemsize
    per_tok = 2 * spec.num_kv_heads * spec.head_dim * itemsize
    live_tokens = int(np.sum(cache.lengths))
    return live_tokens * spec.num_layers * per_tok


def pick_codec(rate_mbps: float | None, payload_bytes: int,
               cache_dtype: str, *,
               fast_s: float = 0.05, slow_s: float = 0.5) -> str:
    """Resolve ``codec="auto"`` to a concrete wire codec from the
    crossover model ``bench.py migrate --quant`` measures: compression
    only wins when the LINK, not the CPU, is the bottleneck — loopback
    moves bytes for free and the codec would just burn encode time.

    * rate unknown or projected transfer under ``fast_s`` → ``none``
      (nothing to save);
    * bf16 cache → ``bf16`` (bit-lossless, 2x) once transfer costs
      real time; escalate to ``int8`` (4x vs f32, 2x vs bf16,
      near-lossless block scales) when even the bf16 payload would
      exceed ``slow_s`` — the preemption-deadline regime where the
      bench's crossover shows int8 winning outright;
    * f32 cache → ``int8`` directly (bf16 would be lossy anyway at
      only 2x; int8's block-scaled 4x is the measured winner).
    """
    if rate_mbps is None or rate_mbps <= 0 or payload_bytes <= 0:
        return "none"
    transfer_s = payload_bytes / (rate_mbps * 125_000.0)
    if transfer_s <= fast_s:
        return "none"
    if "bfloat16" in str(cache_dtype) or "bf16" in str(cache_dtype):
        return "int8" if transfer_s / 2.0 > slow_s else "bf16"
    return "int8"


def resolve_codec(codec: str, engine, *,
                  rate_mbps: float | None = None) -> str:
    """The per-drain "auto" resolution both pool flavors share: prefer
    an explicitly known link rate (a netem cap, a configured DCN
    share), fall back to the op-span-derived measurement, and feed the
    engine's live payload estimate through :func:`pick_codec`.
    Concrete codecs pass through untouched."""
    if codec != "auto":
        return check_codec(codec, allow_auto=False)
    if rate_mbps is None:
        rate_mbps = known_link_mbps()
    return pick_codec(rate_mbps, estimate_payload_bytes(engine),
                      str(np.dtype(engine.cache.spec.dtype)))


def _encode_kv(arr: np.ndarray, codec: str, dt: np.dtype) -> bytes:
    """One K or V array ``[layers, tokens, heads, head_dim]`` → body bytes.

    ``bf16``: elementwise round (lossless when the model already runs
    bf16 — the token-parity tier); ``int8``: block-scaled with one f32
    scale per (layer, head) — scales prefix the codes, ~4x smaller than
    f32 K/V at negligible per-token cost."""
    if codec == "none":
        return arr.tobytes()
    if codec == "bf16":
        return np.ascontiguousarray(
            arr.astype(_np_dtype("bfloat16"))).tobytes()
    if codec == "int8":
        from hetu_tpu.quantwire import q8_encode_axes
        q, scales = q8_encode_axes(arr, (1, 3))  # block = (layer, head)
        return (np.ascontiguousarray(scales, np.float32).tobytes()
                + np.ascontiguousarray(q).tobytes())
    raise ValueError(f"unknown KV codec {codec!r}; expected one of {CODECS}")


def _decode_kv(buf: memoryview, codec: str, dt: np.dtype,
               shape_tail: tuple, slot: int, name: str) -> np.ndarray:
    """Inverse of :func:`_encode_kv` back to the spec dtype; raises
    :class:`MigrationError` naming the slot on any size mismatch."""
    L, _, H, D = shape_tail
    if codec == "none":
        return np.frombuffer(buf, dt).reshape(shape_tail)
    if codec == "bf16":
        bf = _np_dtype("bfloat16")
        return np.frombuffer(buf, bf).reshape(shape_tail).astype(dt)
    if codec == "int8":
        from hetu_tpu.quantwire import q8_decode_axes
        scale_bytes = L * H * 4
        if len(buf) < scale_bytes:
            raise MigrationError(
                f"slot {slot}: {name} compressed body shorter than its "
                f"{L}x{H} block-scale table")
        scales = np.frombuffer(buf[:scale_bytes],
                               np.float32).reshape(L, 1, H, 1)
        q = np.frombuffer(buf[scale_bytes:], np.int8).reshape(shape_tail)
        return q8_decode_axes(q, scales).astype(dt)
    raise MigrationError(f"payload names unknown KV codec {codec!r}; "
                         f"this build speaks {CODECS}")


def _encoded_tokens(nbytes: int, codec: str, dt: np.dtype, L: int, H: int,
                    D: int) -> int:
    """Token count implied by an encoded K/V byte length (-1: not a whole
    number of tokens — corrupt meta)."""
    if codec == "bf16":
        per_tok = L * H * D * 2
    elif codec == "int8":
        nbytes -= L * H * 4  # block-scale prefix
        per_tok = L * H * D
    else:
        per_tok = L * H * D * dt.itemsize
    if nbytes < 0 or per_tok <= 0 or nbytes % per_tok:
        return -1
    return nbytes // per_tok


def pack(spec, snapshots, records=(), *, codec: str = "none") -> bytes:
    """Serialize slot snapshots (+ optional request records) into one
    migration payload.  ``spec`` is the source cache's ``KVCacheSpec`` —
    the receiver validates it against its own before touching a slot.

    ``codec`` compresses the K/V body ("bf16": 2 B/elt, lossless for
    bf16-model caches; "int8": ~1 B/elt, block-scaled per (layer, head)).
    The payload is self-describing — the header names the codec and the
    body CRC covers the COMPRESSED bytes — so ``unpack`` needs no side
    channel and an old payload (no codec field) still decodes as raw.
    Logical-vs-wire bytes land on the shared ``serve.migrate.bytes_*``
    telemetry counters."""
    if codec not in CODECS:
        raise ValueError(f"unknown KV codec {codec!r}; expected one of "
                         f"{CODECS}")
    dt = np.dtype(spec.dtype)
    slots_meta = []
    blobs = []
    logical = 0
    for s in snapshots:
        k = np.ascontiguousarray(s.k)
        v = np.ascontiguousarray(s.v)
        logical += k.nbytes + v.nbytes
        kb = _encode_kv(k, codec, dt)
        vb = _encode_kv(v, codec, dt)
        slots_meta.append({"slot": int(s.slot), "length": int(s.length),
                           "meta": dict(s.meta),
                           "k_bytes": len(kb), "v_bytes": len(vb)})
        blobs.append(kb)
        blobs.append(vb)
    body = b"".join(blobs)
    header = {
        "version": VERSION,
        "codec": codec,
        "spec": {"num_layers": int(spec.num_layers),
                 "num_kv_heads": int(spec.num_kv_heads),
                 "head_dim": int(spec.head_dim),
                 "dtype": dt.name},
        "slots": slots_meta,
        "records": list(records),
        "body_bytes": len(body),
        "body_crc": zlib.crc32(body),
    }
    from hetu_tpu.quantwire import record_wire_bytes
    record_wire_bytes("serve.migrate", logical, len(body))
    hb = json.dumps(header, separators=(",", ":")).encode()
    return _PAYLOAD_HDR.pack(MAGIC, VERSION, len(hb)) + hb + body


def unpack(payload: bytes):
    """Parse a :func:`pack` payload back into ``(spec_dict, snapshots,
    records)``.  Raises :class:`MigrationError` on any framing/CRC
    problem — before any snapshot is built."""
    if len(payload) < _PAYLOAD_HDR.size:
        raise MigrationError("migration payload shorter than its header")
    magic, ver, hlen = _PAYLOAD_HDR.unpack_from(payload)
    if magic != MAGIC:
        raise MigrationError(f"bad migration magic {magic!r}")
    if ver != VERSION:
        raise MigrationError(f"migration payload version {ver}; this "
                             f"build speaks {VERSION}")
    off = _PAYLOAD_HDR.size
    if len(payload) < off + hlen:
        raise MigrationError("truncated migration header")
    try:
        header = json.loads(payload[off:off + hlen])
    except json.JSONDecodeError as e:
        raise MigrationError(f"corrupt migration header: {e}") from None
    body = payload[off + hlen:]
    if len(body) != int(header["body_bytes"]):
        raise MigrationError(
            f"migration body is {len(body)} bytes; header promised "
            f"{header['body_bytes']}")
    if zlib.crc32(body) != int(header["body_crc"]):
        raise MigrationError("migration body CRC mismatch — refusing to "
                             "adopt any slot from a corrupt transfer")
    spec_d = header["spec"]
    codec = header.get("codec", "none")  # pre-codec payloads: raw body
    if codec not in CODECS:
        raise MigrationError(f"payload names unknown KV codec {codec!r}; "
                             f"this build speaks {CODECS}")
    dt = _np_dtype(spec_d["dtype"])
    L = int(spec_d["num_layers"])
    H = int(spec_d["num_kv_heads"])
    D = int(spec_d["head_dim"])
    snaps = []
    pos = 0
    bodyv = memoryview(body)
    for m in header["slots"]:
        kb, vb = int(m["k_bytes"]), int(m["v_bytes"])
        if pos + kb + vb > len(body):
            raise MigrationError("slot byte ranges overrun the body")
        # token counts are derived from the ENCODED byte lengths before
        # any frombuffer touches the body — a corrupt meta fails loudly,
        # never reshapes garbage
        nk = _encoded_tokens(kb, codec, dt, L, H, D)
        nv = _encoded_tokens(vb, codec, dt, L, H, D)
        if nk < 0 or nv < 0:
            raise MigrationError(
                f"slot {m['slot']}: K/V bytes do not factor into the "
                f"declared geometry under codec {codec!r}")
        try:
            k = _decode_kv(bodyv[pos:pos + kb], codec, dt, (L, nk, H, D),
                           int(m["slot"]), "k")
            v = _decode_kv(bodyv[pos + kb:pos + kb + vb], codec, dt,
                           (L, nv, H, D), int(m["slot"]), "v")
        except ValueError as e:
            raise MigrationError(
                f"slot {m['slot']}: K/V bytes do not factor into the "
                f"declared geometry ({e})") from None
        pos += kb + vb
        if nk != int(m["length"]) or nv != int(m["length"]):
            raise MigrationError(
                f"slot {m['slot']}: {nk} rows of K/V for a "
                f"declared length of {m['length']}")
        snaps.append(KVSlotSnapshot(slot=int(m["slot"]),
                                    length=int(m["length"]),
                                    k=k, v=v, meta=dict(m.get("meta", {}))))
    return spec_d, snaps, list(header.get("records", []))


def check_spec(spec, spec_dict: dict) -> None:
    """Receiver-side geometry gate: the adopting cache's spec must match
    the payload's exactly (layers/kv-heads/head-dim/dtype) — erroring
    loudly beats adopting garbage rows."""
    mine = {"num_layers": int(spec.num_layers),
            "num_kv_heads": int(spec.num_kv_heads),
            "head_dim": int(spec.head_dim),
            "dtype": np.dtype(spec.dtype).name}
    theirs = {k: spec_dict.get(k) for k in mine}
    if mine != theirs:
        raise MigrationError(
            f"KV cache geometry mismatch: payload {theirs} vs local "
            f"{mine} — slots can only migrate between engines serving "
            f"the same model geometry")


# ---------------------------------------------------------------------------
# whole-scheduler payloads (the cross-process drain)
# ---------------------------------------------------------------------------

def export_payload(scheduler, *, codec: str = "none"):
    """Export EVERY in-flight request from ``scheduler`` into one
    self-describing migration payload: mid-decode requests ride with
    their live KV snapshots (zero re-prefill on the adopter), queued
    ones as bare records.  The scheduler half of a PROCESS-BOUNDARY
    drain (serve/crosshost.py): unlike :func:`migrate_inflight`, source
    and destination here share no objects — everything a peer process
    needs crosses inside the payload.

    Returns ``(payload, pairs)``; ``pairs`` is the live export the
    caller must hold for rollback (``scheduler.adopt_inflight(pairs)``)
    until the peer confirms adoption, then release via
    :func:`release_exported`.  Each request record carries its SOURCE
    slot id (``rec["slot"]``, None for queued) so :func:`adopt_payload`
    can rebind it to the imported snapshot."""
    pairs, snaps = scheduler.export_inflight_with_slots()
    try:
        records = []
        now = time.monotonic()
        for req, slot in pairs:
            rec = request_record(req, now=now)
            rec["slot"] = None if slot is None else int(slot)
            records.append(rec)
        payload = pack(scheduler.engine.cache.spec, snaps, records,
                       codec=codec)
    except Exception:
        # the export succeeded but the payload build did not: the
        # requests are off the scheduler and the CALLER never received
        # `pairs` to roll back — re-adopt here or they strand forever
        scheduler.adopt_inflight(pairs)
        raise
    return payload, pairs


def adopt_payload(scheduler, payload: bytes):
    """Adopt an :func:`export_payload` payload into ``scheduler`` —
    geometry-gated, all-or-nothing (KV import + request attachment under
    the adopter's scheduler lock).  Requests are REBUILT from their wire
    records (:func:`request_from_record`): the adopting process owns
    fresh ``Request`` objects whose completion the caller must report
    back over its own control plane.  Returns ``(requests,
    slot_map)`` in the payload's admission order."""
    spec_d, snaps, records = unpack(payload)
    check_spec(scheduler.engine.cache.spec, spec_d)
    now = time.monotonic()
    by_slot = {int(s.slot): s for s in snaps}
    pairs = []
    for rec in records:
        req = request_from_record(rec, now=now)
        slot = rec.get("slot")
        if slot is not None and int(slot) not in by_slot:
            raise MigrationError(
                f"record {rec.get('rid')} names source slot {slot} but "
                f"the payload carries no snapshot for it")
        pairs.append((req, None if slot is None else int(slot)))
    carried = {s for _, s in pairs if s is not None}
    orphans = sorted(set(by_slot) - carried)
    if orphans:
        raise MigrationError(
            f"payload carries snapshots for slots {orphans} that no "
            f"request record references — refusing a partial adoption")
    try:
        slot_map = scheduler.adopt_inflight(pairs,
                                            snapshots=snaps or None)
    except Exception as e:
        raise MigrationTargetError(
            f"destination failed the adoption: {e}") from e
    return [req for req, _ in pairs], slot_map


def release_exported(scheduler, pairs) -> None:
    """Commit half of a cross-process drain: the peer confirmed
    adoption, so the source's exported slots are dead weight — release
    them (best-effort; the source may be about to exit anyway) and
    charge ``requests_exported`` with the committed hand-off."""
    from hetu_tpu.serve.scheduler import release_slot_best_effort
    for _req, slot in pairs:
        if slot is not None:
            release_slot_best_effort(scheduler.engine, slot)
    scheduler.metrics.inc("requests_exported", len(pairs))


# ---------------------------------------------------------------------------
# chunked wire over a van blob channel
# ---------------------------------------------------------------------------

def send_payload(channel, payload: bytes, *, seq0: int = 1,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 timeout_s: float = 60.0, stop=None) -> int:
    """Send ``payload`` over a van blob channel as CRC-framed chunks at
    seqs ``[seq0, seq0+n)``; returns the next free seq.  Each frame is a
    single-slot acked put, idempotent under same-seq resend — a
    connection drop mid-transfer reconnects and resends the in-flight
    chunk, never restarting the payload.

    ``stop`` (a ``threading.Event``): cooperative abort, checked between
    SHORT put slices instead of one ``timeout_s``-long ack wait.  A
    receiver that died mid-stream never acks, and the caller cannot
    safely close the channel under a blocked native put — without the
    slicing, an aborted transfer wedges the sender (and whoever joins
    it) for the whole ack window.  Raises :class:`MigrationError` when
    set."""
    chunk_bytes = max(int(chunk_bytes), 1)
    n = max((len(payload) + chunk_bytes - 1) // chunk_bytes, 1)
    slice_s = 0.5 if stop is not None else timeout_s
    t0 = time.perf_counter()
    for i in range(n):
        part = payload[i * chunk_bytes:(i + 1) * chunk_bytes]
        frame = _CHUNK_HDR.pack(MAGIC, VERSION, i, n,
                                zlib.crc32(part)) + part
        deadline = time.monotonic() + timeout_s
        while True:
            if stop is not None and stop.is_set():
                raise MigrationError(
                    f"send aborted at chunk {i}/{n}: receiver gone")
            remaining = deadline - time.monotonic()
            try:
                channel.put(frame, seq0 + i,
                            timeout_s=max(min(slice_s, remaining), 0.001))
                break
            except TimeoutError:
                # ack window still blocked: same-seq resend is idempotent
                if time.monotonic() >= deadline:
                    raise
    dt = time.perf_counter() - t0
    if len(payload) >= MIN_RATE_SAMPLE_BYTES and dt > 0:
        # a completed BULK transfer is the one honest link-rate sample
        # this process gets (control frames are tiny and ack-paced —
        # their byte/latency aggregate reads orders of magnitude slow):
        # feed the auto-codec model (measured_link_mbps)
        from hetu_tpu.telemetry import default_registry as _reg
        _reg.gauge("migrate.wire.mbps_last").set(
            len(payload) * 8.0 / (dt * 1e6))
        _reg.counter("migrate.wire.rate_samples").inc()
    return seq0 + n


def recv_payload(channel, *, seq0: int = 1,
                 timeout_s: float = 60.0) -> bytes:
    """Receive a :func:`send_payload` stream.  Validates each chunk's
    framing and CRC as it lands and raises :class:`MigrationError` on
    the first mismatch — the caller adopts nothing from a bad stream."""
    parts = []
    i, n = 0, 1
    while i < n:
        frame = channel.get(seq0 + i, timeout_s=timeout_s)
        if len(frame) < _CHUNK_HDR.size:
            raise MigrationError(f"chunk {i}: frame shorter than header")
        magic, ver, idx, total, crc = _CHUNK_HDR.unpack_from(frame)
        if magic != MAGIC or ver != VERSION:
            raise MigrationError(f"chunk {i}: bad magic/version")
        if idx != i or total < 1 or (i > 0 and total != n):
            raise MigrationError(
                f"chunk sequence corrupt: got idx {idx}/{total} at "
                f"position {i}/{n}")
        part = frame[_CHUNK_HDR.size:]
        if zlib.crc32(part) != crc:
            raise MigrationError(f"chunk {i} CRC mismatch")
        n = total
        parts.append(part)
        i += 1
    return b"".join(parts)


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def migrate_inflight(src, dst, *, wire=None, codec: str = "none",
                     chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                     timeout_s: float = 60.0) -> dict:
    """Move EVERY in-flight request from scheduler ``src`` to scheduler
    ``dst``: mid-decode requests carry their live KV slots (the peer
    continues with zero prefill); queued ones re-queue on the peer with
    their deadlines intact.  Returns ``{source_slot: dest_slot}``.

    ``wire``: a ``(tx, rx)`` pair of van blob channels the K/V payload
    crosses as CRC-checked chunks (the sender runs in a helper thread —
    blob puts block on the single-slot ack window); ``None`` hands the
    host arrays over directly (same-process fast path, identical
    validation via the engines).

    ``codec`` ("bf16"/"int8", wire transfers only): compress the K/V
    body — see :func:`pack`.  "bf16" keeps token parity for bf16-model
    caches at half the bytes; "int8" is ~4x smaller (2x for bf16 caches)
    with per-(layer, head) block scales, a near-lossless approximation
    whose drain payloads move the migrate-vs-re-prefill crossover to
    shorter contexts (``bench.py migrate --quant``).

    Failure atomicity: any error re-adopts the requests AND their slots
    at the source (the slots were never released) and re-raises —
    migration either completes or leaves the source serving.  On the
    destination, KV import and request attachment happen atomically
    under its scheduler lock (``adopt_inflight``), so a live peer keeps
    serving its own traffic safely throughout.
    """
    # export + KV snapshot atomically under the source scheduler lock: a
    # decode step sneaking in between would advance the exported slots
    # past the requests' recorded tokens (a silently dropped token on
    # the adopter)
    pairs, snaps = src.export_inflight_with_slots()
    slots = [slot for _, slot in pairs if slot is not None]
    try:
        if wire is not None and snaps:
            spec = src.engine.cache.spec
            payload = pack(spec, snaps, codec=codec)
            tx, rx = wire
            send_exc: list = []
            send_stop = threading.Event()

            def _send():
                try:
                    send_payload(tx, payload, chunk_bytes=chunk_bytes,
                                 timeout_s=timeout_s, stop=send_stop)
                except Exception as e:  # surfaced after the join
                    send_exc.append(e)

            t = threading.Thread(target=_send, daemon=True)
            t.start()
            try:
                got = recv_payload(rx, timeout_s=timeout_s)
            except BaseException:
                # the receive failed mid-stream (corrupt chunk/timeout):
                # the sender would sit out its WHOLE ack window against
                # a peer that will never ack — signal it down instead.
                # The rollback below must run promptly: the exported
                # requests are off both schedulers, burning their
                # serving deadlines while we wait
                send_stop.set()
                t.join(timeout_s)
                raise
            t.join(timeout_s)
            if send_exc:
                raise send_exc[0]
            spec_d, snaps, _ = unpack(got)
            check_spec(dst.engine.cache.spec, spec_d)
        try:
            slot_map, n_adopted = dst.adopt_inflight(
                pairs, snapshots=snaps or None, return_count=True)
        except Exception as e:
            raise MigrationTargetError(
                f"destination failed the adoption: {e}") from e
    except Exception:
        try:
            src.adopt_inflight(pairs)  # source resumes serving, slots
        except Exception:              # intact
            # the source is gone too (closed/drained mid-transfer): the
            # requests must still RESOLVE — nothing will ever serve them,
            # and a waiter blocked on done would sit out its whole
            # backstop timeout undiagnosed
            from hetu_tpu.serve.scheduler import (
                finish_request, release_slot_best_effort,
            )
            for req, _ in pairs:
                if not req.done.is_set():
                    finish_request(req, req.status or "error",
                                   getattr(src, "metrics", None))
            for slot in slots:
                release_slot_best_effort(src.engine, slot)
        raise  # the ORIGINAL failure, not the rollback's
    # the migration has COMMITTED: the hand-off is now real, so charge
    # the source's requests_exported (deferred from the export — a
    # rolled-back export must not count) with what the destination
    # ACTUALLY attached (requests that finished in transit were skipped
    # there and never handed off).  Releasing the source's now-dead
    # slots is best-effort (a source engine dying right here must not
    # turn a successful hand-off into a raised error)
    src.metrics.inc("requests_exported", n_adopted)
    from hetu_tpu.serve.scheduler import release_slot_best_effort
    for slot in slots:
        release_slot_best_effort(src.engine, slot)
    return slot_map
