"""Online CTR recommendation serving with a staleness-bounded
hot-embedding cache tier.

The reference's signature result (HET, VLDB'22) is a worker-side
embedding cache over the PS with BOUNDED staleness; PR 1-5 built that
for training (``ps/client.CacheSparseTable``, ``ps/van.RemoteCacheTable``)
and a transformer-decoding serving stack.  This module opens the second
serving workload — online CTR inference over ``models/wdl.py`` /
``models/ctr_zoo.py`` — whose profile INVERTS the LLM one (the TPU
serving-efficiency frame of PAPERS.md arXiv 2605.25645 applied to
recommendation): tiny dense compute, huge sparse state, and cache
hit-rate — not FLOPs — as the latency lever.

Pieces (each reuses a layer PRs 1-5 built):

* :class:`ServingEmbeddingCache` — read-through host cache over the
  versioned ``sync_pull`` wire op (HET kSyncEmbedding), the read-mostly
  sibling of the training tier's ``CacheSparseTable``: a configurable
  staleness bound (``pull_bound`` versions), thread-safe
  hit/miss/staleness accounting into a metrics registry, a
  negative/cold-row policy, an optional COMPRESSED eviction tier
  (``embedding_compress.ServingRowCodec``), and a degraded-stale mode —
  when the PS stops answering (shard killed), lookups serve the cached
  rows regardless of staleness and the outage is recorded as a
  ``serve.recsys_degrade`` recovery span that
  ``telemetry.timeline`` pairs with the injected ``fault.kill_shard``.
* :class:`RecsysEngine` — bucketed-batch jitted CTR forward (bounded
  executable count, the same compilation discipline as
  ``serve/engine.py``) whose host-side lookup path goes through the
  cache; ``gather_launch``/``finish`` split the step so the NEXT batch's
  embedding gather overlaps the previous batch's device execution.
* :class:`RecsysBatcher` — micro-batching scheduler: coalesces tiny
  single-request lookups into batched forwards under a latency budget
  (``max_delay_s``), with the full pool-compatible scheduler surface
  (submit/export/adopt/requeue), so CTR members ride the SAME
  health-routed routing + failover machinery as LLM members.
* :class:`RecsysServer` / :class:`RecsysClient` — the van blob-channel
  front-end (``serve/server.py`` listeners, idempotent resubmission,
  dedup) speaking ``{dense, sparse} -> {score}`` instead of tokens.
* :class:`RecsysPool` — :class:`~hetu_tpu.serve.pool.ServingPool` with
  CTR members (``member_factory``): least-loaded healthy routing,
  ``serve_engine_kill`` failover, planned drain, revive.

Freshness contract (asserted in tests/test_recsys.py): with
``pull_bound=0`` cached serving is bitwise identical to cache-less PS
pulls, and under a concurrent trainer every served row is at most
``pull_bound`` versions stale.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.serve.scheduler import finish_request
from hetu_tpu.serve.server import InferenceClient, InferenceServer
from hetu_tpu.telemetry import trace
from hetu_tpu.telemetry.registry import DEFAULT_LATENCY_BUCKETS

NOT_CACHED = np.uint64(0xFFFFFFFFFFFFFFFF)

# version-lag buckets for the staleness histogram (powers of two: a lag
# of 0 means the refresh raced a push; big lags mean a cold/returning row)
STALENESS_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 1024, 1 << 20)

_req_ids = itertools.count(1)
_cache_ids = itertools.count(0)


# ---------------------------------------------------------------------------
# the serving cache
# ---------------------------------------------------------------------------

class ServingEmbeddingCache:
    """Read-through bounded-staleness host cache for ONLINE SERVING.

    The training tiers (``CacheSparseTable`` / ``RemoteCacheTable``)
    are read-write: lookups pull, updates accumulate locally.  Serving
    is read-mostly — the trainer pushes through ITS tier while many
    serving threads only read — so this cache keeps a host-side hot set
    ``{key: (row, version)}`` and revalidates each batch with ONE
    versioned ``sync_pull`` (HET kSyncEmbedding: the cached versions go
    out, only rows newer than ``pull_bound`` versions come back).  Rows
    the server does not re-send are hits served from host memory — on
    the remote tier those bytes never cross the wire
    (``ps_bytes_saved``).

    ``table``: a ``ps.PSTable`` or ``ps.van.PartitionedPSTable`` —
    anything exposing ``sync_pull``/``rows``/``dim`` — or a training
    cache (``CacheSparseTable``/``RemoteCacheTable``), whose underlying
    ``.table`` is shared (read-through wrapper: the serving side observes
    the trainer's pushes within the bound).

    ``capacity=0`` disables caching (every row re-pulled — the
    cache-less baseline ``bench.py ctr_serve`` measures against).

    ``policy``: ``"lru"`` (default) or ``"lfu"``.

    ``codec`` (e.g. ``embedding_compress.ServingRowCodec(dim)``): rows
    evicted from the hot f32 tier are kept compressed WITH their PS
    version in an L2 of ``l2_capacity`` entries (default 4x capacity); a
    re-access still within the staleness bound decompresses locally
    instead of re-pulling the full row.  Lossy — leave ``codec=None``
    for bitwise parity.

    ``negative``: policy for ids outside ``[0, rows)`` (the classic
    out-of-vocab / unseen-entity case): ``"zeros"`` (serve a zero row,
    count it, never touch the PS) or ``"error"`` (raise KeyError).

    Degraded-stale mode: when ``sync_pull`` RAISES (PS shard dead), the
    lookup serves what it has — hot rows regardless of staleness, L2
    rows decompressed, zeros for unknown keys — and keeps answering.
    While degraded the PS is re-probed at most once per
    ``probe_interval_s`` (in-line, by simply attempting the sync);
    between probes lookups serve from host memory WITHOUT touching the
    PS, so a dead shard's connect/retry latency is paid ~2x/second, not
    per request.  The first failing lookup opens a
    ``serve.recsys_degrade`` window; the first succeeding one closes it
    as a retroactive recovery span, which the chaos timeline pairs with
    the ``fault.kill_shard`` instant.  ``close()`` records a still-open
    window with ``error="unrecovered"`` so a never-recovered outage is
    not mis-paired as a recovery.

    Thread safety: every lookup (and the stats) holds ``_lock``.
    """

    def __init__(self, table, capacity: int, *, pull_bound: int = 0,
                 policy: str = "lru", codec=None,
                 l2_capacity: Optional[int] = None,
                 negative: str = "zeros", probe_interval_s: float = 0.5,
                 registry=None, name: Optional[str] = None):
        # unwrap a training cache: share its underlying table
        if hasattr(table, "embedding_lookup") and hasattr(table, "table"):
            table = table.table
        if not hasattr(table, "sync_pull"):
            raise TypeError(
                "table must expose sync_pull (PSTable / "
                "PartitionedPSTable, or a cache tier wrapping one)")
        if policy not in ("lru", "lfu"):
            raise ValueError(f"unknown policy {policy!r}; use lru|lfu")
        if negative not in ("zeros", "error"):
            raise ValueError(
                f"unknown negative policy {negative!r}; use zeros|error")
        self.table = table
        self.rows = int(table.rows)
        self.dim = int(table.dim)
        self.capacity = int(capacity)
        self.pull_bound = int(pull_bound)
        self.policy = policy
        self.codec = codec
        self.l2_capacity = int(l2_capacity if l2_capacity is not None
                               else 4 * max(self.capacity, 1))
        self.negative = negative
        self._lock = threading.Lock()
        self._l1: OrderedDict = OrderedDict()  # key -> [row f32[dim], ver]
        self._freq: dict = {}                  # key -> hits (lfu)
        self._l2: OrderedDict = OrderedDict()  # key -> (blob, ver)
        self.probe_interval_s = float(probe_interval_s)
        self._degraded = False
        self._degrade_start_us = 0.0
        self._degrade_n = 0
        self._next_probe = 0.0
        # accounting (exact, exported through `registry`)
        if registry is None:
            from hetu_tpu.telemetry import default_registry as registry
        self.registry = registry
        if name is None:
            # per-instance default: metric objects are shared by NAME
            # within a registry, and two caches silently pooling their
            # hit counters would misreport both
            n = next(_cache_ids)
            name = "serve.recsys.cache" + (str(n) if n else "")
        self._name = name
        c = registry.counter
        self._c_lookups = c(f"{name}.lookups",
                            help="in-vocab rows looked up (positions; "
                                 "negative_rows counted separately)")
        self._c_hits = c(f"{name}.hits", help="rows served from the hot "
                         "tier within the staleness bound")
        self._c_l2_hits = c(f"{name}.l2_hits", help="rows decompressed "
                            "from the evicted tier instead of re-pulled")
        self._c_cold = c(f"{name}.cold_misses", help="rows pulled with no "
                         "cached version")
        self._c_stale = c(f"{name}.stale_refreshes", help="cached rows "
                          "re-pulled past the staleness bound")
        self._c_negative = c(f"{name}.negative_rows", help="out-of-vocab "
                             "ids served as zeros without touching the PS")
        self._c_degraded = c(f"{name}.degraded_lookups", help="lookups "
                             "served stale while the PS was unreachable")
        self._c_unknown = c(f"{name}.degraded_unknown_rows", help="rows "
                            "served as zeros during degrade (never cached)")
        self._c_saved = c(f"{name}.ps_bytes_saved", help="row bytes NOT "
                          "re-pulled thanks to the cache")
        self._c_pulled = c(f"{name}.ps_bytes_pulled", help="row bytes "
                           "actually pulled from the PS")
        self._g_hit_rate = registry.gauge(f"{name}.hit_rate")
        self._g_size = registry.gauge(f"{name}.size")
        registry.gauge(f"{name}.pull_bound").set(self.pull_bound)
        self._h_staleness = registry.histogram(
            f"{name}.staleness_versions", STALENESS_BUCKETS,
            help="version lag observed when a cached row was refreshed "
                 "(served hits are <= pull_bound by construction)")

    # ---- internals (caller holds _lock) ----
    def _touch(self, key: int) -> None:
        if self.policy == "lru":
            self._l1.move_to_end(key)
        else:
            self._freq[key] = self._freq.get(key, 0) + 1

    def _store_l1(self, key: int, row: np.ndarray, ver: int) -> None:
        if self.capacity <= 0:
            return
        self._l1[key] = [row, int(ver)]
        self._touch(key)

    def _evict_locked(self) -> None:
        excess = len(self._l1) - self.capacity
        if excess <= 0:
            return
        if self.policy == "lru":
            # OrderedDict iteration order IS recency order (oldest first)
            it = iter(self._l1)
            victims = [next(it) for _ in range(excess)]
        else:
            scored = sorted(self._l1, key=lambda k: self._freq.get(k, 0))
            victims = scored[:excess]
        if self.codec is not None and victims:
            vrows = np.stack([self._l1[k][0] for k in victims])
            blobs = self.codec.compress(vrows)
            q, scale = blobs
            for i, k in enumerate(victims):
                self._l2[k] = ((q[i], scale[i:i + 1]), self._l1[k][1])
                self._l2.move_to_end(k)
            while len(self._l2) > self.l2_capacity:
                self._l2.popitem(last=False)
        for k in victims:
            del self._l1[k]
            self._freq.pop(k, None)

    def _l2_row(self, key: int):
        """Decompressed row + version for an L2 entry, or None."""
        ent = self._l2.get(key)
        if ent is None:
            return None
        (q, scale), ver = ent
        row = self.codec.decompress((q[None, :], scale))[0]
        return row, ver

    def _recovered_locked(self) -> None:
        if not self._degraded:
            return
        self._degraded = False
        trace.complete("serve.recsys_degrade", self._degrade_start_us,
                       {"degraded_lookups": self._degrade_n}, cat="serve")
        self._degrade_n = 0

    def _degraded_lookup_locked(self, keys, counts, exc) -> np.ndarray:
        """Serve what we have: hot rows (any staleness), L2, else zeros.
        ``counts``: per-key position counts — degraded accounting stays
        PER POSITION like every other counter here."""
        if not self._degraded:
            self._degraded = True
            self._degrade_start_us = trace.now_us()
            self._degrade_n = 0
            trace.instant("serve.recsys.degrade_enter",
                          {"error": type(exc).__name__}, cat="serve")
        self._degrade_n += 1
        rows = np.zeros((keys.shape[0], self.dim), np.float32)
        unknown = 0
        for i in range(keys.shape[0]):
            k = int(keys[i])
            ent = self._l1.get(k)
            if ent is not None:
                rows[i] = ent[0]
                self._touch(k)
                continue
            l2 = self._l2_row(k) if self.codec is not None else None
            if l2 is not None:
                rows[i] = l2[0]
            else:
                unknown += int(counts[i])
        self._c_degraded.inc(int(counts.sum()))
        self._c_unknown.inc(unknown)
        return rows

    # ---- the lookup ----
    def lookup(self, indices) -> np.ndarray:
        """rows for ``indices`` (any shape): ``[*indices.shape, dim]``
        f32, every row at most ``pull_bound`` versions stale (or best
        effort while degraded)."""
        idx = np.ascontiguousarray(indices, np.int64)
        flat = idx.reshape(-1)
        with self._lock:
            keys, inverse, counts = np.unique(flat, return_inverse=True,
                                              return_counts=True)
            valid = (keys >= 0) & (keys < self.rows)
            n_invalid_pos = int((~valid[inverse]).sum())
            if n_invalid_pos and self.negative == "error":
                bad = keys[~valid]
                raise KeyError(f"ids outside [0, {self.rows}): "
                               f"{bad[:8].tolist()}")
            self._c_negative.inc(n_invalid_pos)
            vmask = valid
            vkeys = keys[vmask]
            # hit/miss accounting is PER POSITION (a batch repeating one
            # hot key 26x counts 26 served rows), wire-byte accounting is
            # per UNIQUE key (one pull feeds every duplicate)
            vcounts = counts[vmask]
            vers = np.full(vkeys.shape[0], NOT_CACHED, np.uint64)
            if self.capacity > 0:
                for i, k in enumerate(vkeys):
                    k = int(k)
                    ent = self._l1.get(k)
                    if ent is not None:
                        vers[i] = ent[1]
                    elif self.codec is not None and k in self._l2:
                        vers[i] = self._l2[k][1]
            rows_valid = np.zeros((vkeys.shape[0], self.dim), np.float32)
            if vkeys.shape[0]:
                if self._degraded and \
                        time.monotonic() < self._next_probe:
                    # between probes: serve from host memory without
                    # paying the dead PS's connect/retry latency again
                    rows_valid = self._degraded_lookup_locked(
                        vkeys, vcounts, None)
                    full = np.zeros((keys.shape[0], self.dim), np.float32)
                    full[vmask] = rows_valid
                    return full[inverse].reshape(*idx.shape, self.dim)
                try:
                    sel, svers, srows = self.table.sync_pull(
                        vkeys, vers, bound=self.pull_bound)
                except Exception as e:
                    self._next_probe = time.monotonic() + \
                        self.probe_interval_s
                    rows_valid = self._degraded_lookup_locked(
                        vkeys, vcounts, e)
                    full = np.zeros((keys.shape[0], self.dim), np.float32)
                    full[vmask] = rows_valid
                    return full[inverse].reshape(*idx.shape, self.dim)
                self._recovered_locked()
                refreshed = np.zeros(vkeys.shape[0], bool)
                refreshed[sel] = True
                cold = stale = 0
                for j, pos in enumerate(sel):
                    pos = int(pos)
                    k = int(vkeys[pos])
                    old_v = vers[pos]
                    if old_v != NOT_CACHED:
                        # lag can read "negative" across a shard
                        # recreation (fresh incarnations start at a later
                        # base) — clamp: the meaningful signal is "how
                        # stale was the copy we replaced"
                        lag = max(int(svers[j]) - int(old_v), 0)
                        self._h_staleness.observe(lag)
                        stale += int(vcounts[pos])
                    else:
                        cold += int(vcounts[pos])
                    rows_valid[pos] = srows[j]
                    self._store_l1(k, srows[j].copy(), int(svers[j]))
                    self._l2.pop(k, None)
                n_hit = 0
                n_l2 = 0
                for pos in np.nonzero(~refreshed)[0]:
                    pos = int(pos)
                    k = int(vkeys[pos])
                    ent = self._l1.get(k)
                    if ent is not None:
                        rows_valid[pos] = ent[0]
                        self._touch(k)
                        n_hit += int(vcounts[pos])
                        continue
                    l2 = self._l2_row(k)
                    if l2 is None:  # pragma: no cover - server contract
                        raise RuntimeError(
                            f"sync_pull withheld row {k} that is cached "
                            f"nowhere (version bookkeeping bug)")
                    row, ver = l2
                    rows_valid[pos] = row
                    del self._l2[k]
                    self._store_l1(k, row, ver)
                    n_l2 += int(vcounts[pos])
                self._evict_locked()
                row_bytes = self.dim * 4
                n_valid_pos = int(vcounts.sum())
                self._c_lookups.inc(n_valid_pos)
                self._c_hits.inc(n_hit)
                self._c_l2_hits.inc(n_l2)
                self._c_cold.inc(cold)
                self._c_stale.inc(stale)
                # wire bytes: one pull serves every duplicate position
                self._c_saved.inc(
                    (int(vkeys.shape[0]) - len(sel)) * row_bytes)
                self._c_pulled.inc(len(sel) * row_bytes)
                self._g_hit_rate.set(self.hit_rate_locked())
                self._g_size.set(len(self._l1))
                # the shared ps.cache.* aggregate, next to van.* metrics
                # — PER-POSITION deltas, the same unit the training
                # tiers export (mixing units would make the aggregate
                # counters disagree with the hit_rate gauge)
                from hetu_tpu.ps.client import export_cache_stats
                export_cache_stats(
                    n_valid_pos, cold + stale,
                    self._c_lookups.value,
                    self._c_cold.value + self._c_stale.value,
                    len(self._l1))
            full = np.zeros((keys.shape[0], self.dim), np.float32)
            full[vmask] = rows_valid
            return full[inverse].reshape(*idx.shape, self.dim)

    # ---- introspection ----
    def hit_rate_locked(self) -> float:
        total = self._c_lookups.value
        miss = self._c_cold.value + self._c_stale.value
        return 1.0 - miss / max(total, 1)

    @property
    def hit_rate(self) -> float:
        with self._lock:
            return self.hit_rate_locked()

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._l1)

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def stats(self) -> dict:
        with self._lock:
            return {
                "lookups": self._c_lookups.value,
                "hits": self._c_hits.value,
                "l2_hits": self._c_l2_hits.value,
                "cold_misses": self._c_cold.value,
                "stale_refreshes": self._c_stale.value,
                "negative_rows": self._c_negative.value,
                "degraded_lookups": self._c_degraded.value,
                "ps_bytes_saved": self._c_saved.value,
                "ps_bytes_pulled": self._c_pulled.value,
                "hit_rate": self.hit_rate_locked(),
                "size": len(self._l1),
                "l2_size": len(self._l2),
                "staleness": self._h_staleness.snapshot(),
            }

    def invalidate(self) -> None:
        """Drop every cached row (both tiers) — e.g. after a checkpoint
        load replaced the table wholesale."""
        with self._lock:
            self._l1.clear()
            self._l2.clear()
            self._freq.clear()

    def close(self) -> None:
        with self._lock:
            if self._degraded:
                # an outage that never recovered is NOT a recovery: tag
                # the span error so the chaos timeline refuses to pair it
                trace.complete("serve.recsys_degrade",
                               self._degrade_start_us,
                               {"degraded_lookups": self._degrade_n,
                                "error": "unrecovered"}, cat="serve")
                self._degraded = False


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class RecsysEngine:
    """Bucketed-batch jitted CTR forward over a cache-backed lookup path.

    Same compilation discipline as ``serve/engine.py``: request batches
    are right-padded to power-of-two BUCKETS (up to ``max_batch``), so
    one jitted forward compiles at most ``len(buckets)`` executables for
    the life of the server (``compiled_executables`` /
    ``max_executables`` — asserted in tests).

    ``caches``: one :class:`ServingEmbeddingCache` per sparse input of
    the model's ``apply(variables, dense_x, *sparse_rows)`` — one for
    WideDeep/DCN/DeepCrossing, two for DeepFM (emb + fm-linear), each
    looked up with the SAME ``[B, fields]`` ids.

    Overlap: :meth:`gather_launch` runs the host-side cache gather and
    DISPATCHES the device forward without waiting (jax async dispatch);
    :meth:`finish` blocks on the result.  The batcher launches batch k
    then resolves batch k-1, so the PS/cache gather of one batch hides
    under the previous batch's device step.
    """

    def __init__(self, model, variables, caches, *, max_batch: int = 256,
                 min_bucket: int = 8, dense_dim: Optional[int] = None,
                 fields: Optional[int] = None,
                 metrics: Optional[ServeMetrics] = None):
        import jax
        import jax.numpy as jnp

        from hetu_tpu.serve.engine import _pow2_buckets
        self.model = model
        self.caches = tuple(caches) if isinstance(caches, (tuple, list)) \
            else (caches,)
        if not self.caches:
            raise ValueError("need at least one serving cache")
        self.metrics = metrics or ServeMetrics()
        params = variables["params"] if "params" in variables \
            else variables
        state = variables.get("state", {}) \
            if isinstance(variables, dict) else {}
        # SNAPSHOT the dense weights: the natural caller shares
        # ``variables`` with a live trainer whose hybrid step DONATES its
        # params buffers (every hybrid_step_fn does) — without a copy the
        # first training step deletes the serving pool's weights out from
        # under every member ("Array has been deleted" mid-forward).
        # CTR dense towers are small; one copy per engine is nothing.
        copy = lambda a: jnp.array(a)  # noqa: E731 - jnp.array copies
        self._params = jax.tree_util.tree_map(copy, params)
        self._state = jax.tree_util.tree_map(copy, state)
        self.max_batch = int(max_batch)
        self.buckets = _pow2_buckets(min(int(min_bucket), self.max_batch),
                                     self.max_batch)
        self._fn = None
        self._seen_buckets: set = set()
        # per-request feature dims, for INTAKE validation: one request
        # with a wrong-length feature vector must be rejected at the
        # door, not blow up the whole jitted batch (which would strike
        # the member's engine loop out and hand the poison to every
        # surviving peer in turn).  Explicit kwargs win; else the model's
        # own attributes; else learned from the first successful batch.
        self.dense_dim = int(dense_dim) if dense_dim is not None else \
            getattr(model, "dense_dim", None)
        self.fields = int(fields) if fields is not None else \
            getattr(model, "num_sparse_fields",
                    getattr(model, "fields", None))

    # ---- compile accounting (the serve/engine.py contract) ----
    def compiled_executables(self) -> int:
        return self._fn._cache_size() if self._fn is not None else 0

    @property
    def max_executables(self) -> int:
        return len(self.buckets)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} requests exceeds max_batch "
                         f"{self.max_batch}")

    def _build(self):
        import jax
        model, state = self.model, self._state

        def fn(params, dense, *rows):
            logit, _ = model.apply({"params": params, "state": state},
                                   dense, *rows, train=False)
            return jax.nn.sigmoid(logit)

        return jax.jit(fn)

    # ---- the split step ----
    def gather_launch(self, dense, sparse):
        """Host-side cache gather + async device dispatch for one batch.
        ``dense``: [B, dense_dim] f32; ``sparse``: [B, fields] int64.
        Returns an opaque handle for :meth:`finish`."""
        import jax.numpy as jnp
        dense = np.ascontiguousarray(dense, np.float32)
        sparse = np.ascontiguousarray(sparse, np.int64)
        B = dense.shape[0]
        if B < 1:
            raise ValueError("empty batch")
        if self.dense_dim is None:
            self.dense_dim = int(dense.shape[1])
        if self.fields is None:
            self.fields = int(sparse.shape[1])
        s = self.bucket_for(B)
        if self._fn is None:
            self._fn = self._build()
        if s not in self._seen_buckets:
            self._seen_buckets.add(s)
            self.metrics.inc("recsys_compiles")
            trace.instant("serve.recompile", {"kind": "recsys",
                                              "bucket": s})
        with trace.span("serve.recsys.gather") as sp:
            sp.set("batch", B)
            rows = [c.lookup(sparse) for c in self.caches]
        dp = np.zeros((s, dense.shape[1]), np.float32)
        dp[:B] = dense
        rp = []
        for r in rows:
            p = np.zeros((s,) + r.shape[1:], np.float32)
            p[:B] = r
            rp.append(p)
        with trace.span("serve.recsys.dispatch") as sp:
            sp.set("bucket", s)
            dev = self._fn(self._params, jnp.asarray(dp),
                           *[jnp.asarray(p) for p in rp])
        return (dev, B)

    def finish(self, handle) -> np.ndarray:
        """Block on a :meth:`gather_launch` handle; ``[B]`` f32 CTR
        probabilities."""
        dev, B = handle
        with trace.span("serve.recsys.device_wait"):
            probs = np.asarray(dev)
        self.metrics.inc("recsys_batches")
        self.metrics.inc("recsys_scored", B)
        return probs[:B]

    def score(self, dense, sparse) -> np.ndarray:
        """Synchronous convenience: gather + forward + fetch."""
        return self.finish(self.gather_launch(dense, sparse))

    def close(self) -> None:
        for c in self.caches:
            c.close()


class EngineKilledError(RuntimeError):
    """The pool's kill switch fired for a CTR member's engine."""


class _GuardedRecsysEngine:
    """Kill-switch proxy over a :class:`RecsysEngine` — the CTR analog
    of ``pool._GuardedEngine`` (chaos runs SIGKILL-alike a member
    deterministically; every verb then raises)."""

    def __init__(self, inner):
        self.inner = inner
        self.killed = False

    @property
    def caches(self):
        return self.inner.caches

    @property
    def metrics(self):
        return self.inner.metrics

    @property
    def max_batch(self):
        return self.inner.max_batch

    @property
    def dense_dim(self):
        return self.inner.dense_dim

    @property
    def fields(self):
        return self.inner.fields

    def kill(self) -> None:
        self.killed = True

    def _check(self) -> None:
        if self.killed:
            raise EngineKilledError("pool member engine killed")

    def gather_launch(self, dense, sparse):
        self._check()
        return self.inner.gather_launch(dense, sparse)

    def finish(self, handle):
        self._check()
        return self.inner.finish(handle)

    def score(self, dense, sparse):
        self._check()
        return self.inner.score(dense, sparse)

    def close(self):
        # deliberately NOT kill-guarded: closing a killed member must
        # still record its caches' open degrade spans
        self.inner.close()


# ---------------------------------------------------------------------------
# requests + the micro-batching scheduler
# ---------------------------------------------------------------------------

@dataclass(eq=False)
class RecsysRequest:
    """One CTR scoring request (identity semantics, like serve Request)."""

    dense: np.ndarray = None     # [dense_dim] f32
    sparse: np.ndarray = None    # [fields] int64
    timeout_s: Optional[float] = None
    rid: int = field(default_factory=lambda: next(_req_ids))

    score: Optional[float] = None
    state: str = "new"           # new|queued|running|done
    status: str = ""             # ok|timeout|cancelled|error|shutdown
    requeues: int = 0
    rejected: bool = False       # intake-closed reject: the pool re-routes
    owner: object = field(default=None, repr=False)
    _term_lock: threading.Lock = field(default_factory=threading.Lock,
                                       repr=False)
    # finish_request compatibility (generated_tokens counter): always []
    tokens: list = field(default_factory=list)
    slot: Optional[int] = None   # scheduler-surface compat; always None
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)

    @property
    def ttfr_s(self) -> Optional[float]:
        """Time to first (and only) response."""
        if self.finished_at is None or self.submitted_at is None:
            return None
        return self.finished_at - self.submitted_at


class RecsysBatcher:
    """Micro-batching scheduler over a (guarded) :class:`RecsysEngine`.

    A single CTR request costs microseconds of device compute, so
    serving them one-by-one wastes the chip on dispatch overhead; this
    scheduler COALESCES queued requests into one bucketed forward per
    step, bounded by a latency budget: a batch launches when it is full
    (``max_batch``), when its oldest request has waited ``max_delay_s``,
    or immediately when the device is idle (an unloaded server adds zero
    coalescing latency; under load the in-flight batch IS the
    coalescing window).

    Pipelining: step k launches batch k (host gather + async dispatch)
    BEFORE blocking on batch k-1's result, so the embedding gather
    overlaps the previous device step (the engine's
    ``gather_launch``/``finish`` split).

    The scheduler surface matches ``ContinuousBatchingScheduler`` where
    the pool and the van server touch it (submit / load / export /
    adopt / requeue / drain / cancel / stop_intake / replace_engine), so
    :class:`RecsysServer` IS an ``InferenceServer`` and CTR members ride
    ``ServingPool`` unchanged.  CTR requests are STATELESS (no KV
    slots): exports carry ``slot=None`` pairs only and failover is a
    plain re-queue on the peer.
    """

    def __init__(self, engine, *, max_batch: Optional[int] = None,
                 max_delay_s: float = 0.002, metrics=None,
                 max_requeues: int = 3):
        self.engine = engine
        self.metrics = metrics or engine.metrics
        self.max_batch = int(max_batch or engine.max_batch)
        self.max_delay_s = float(max_delay_s)
        self.max_requeues = int(max_requeues)
        self._lock = threading.Lock()
        self._queue = deque()
        self._inflight: list = []      # requests of the launched batch
        self._handle = None            # engine handle for _inflight
        self._accepting = True
        self._reject_status = "shutdown"
        self._ttfr = self.metrics.registry.histogram(
            "recsys.ttfr_s", DEFAULT_LATENCY_BUCKETS,
            help="request submit to scored response")

    # ---- intake ----
    def _shape_mismatch(self, request: RecsysRequest) -> Optional[str]:
        """Feature-dim validation against what the engine serves: a
        wrong-length vector admitted into a batch would blow up the
        WHOLE jitted forward — an engine-level strike for a
        request-level mistake, which under a pool would poison every
        surviving peer in turn."""
        dd = getattr(self.engine, "dense_dim", None)
        ff = getattr(self.engine, "fields", None)
        if dd is not None and request.dense.reshape(-1).shape[0] != dd:
            return (f"dense vector has {request.dense.reshape(-1).shape[0]}"
                    f" features, engine serves {dd}")
        if ff is not None and request.sparse.reshape(-1).shape[0] != ff:
            return (f"sparse vector has "
                    f"{request.sparse.reshape(-1).shape[0]} fields, "
                    f"engine serves {ff}")
        return None

    def submit(self, request: RecsysRequest, *,
               resolve_on_reject: bool = True) -> RecsysRequest:
        request.submitted_at = time.monotonic()
        if self._shape_mismatch(request) is not None:
            # charged to the REQUEST (like the LLM scheduler's overflow
            # admissions), never to the engine
            finish_request(request, "overflow", self.metrics)
            return request
        with self._lock:
            if not self._accepting:
                # same contract as the LLM scheduler: flag the reject for
                # the pool's re-route; only resolve when nobody re-routes
                request.rejected = True
                if resolve_on_reject:
                    finish_request(request, self._reject_status, None)
                self.metrics.inc("requests_rejected")
                return request
            request.state = "queued"
            request.owner = self
            self._queue.append(request)
            self.metrics.inc("requests_submitted")
            self.metrics.set_gauge("queue_depth", len(self._queue))
        return request

    # ---- pool-facing signals ----
    @property
    def load(self) -> int:
        """Lock-free routing signal (see LLM scheduler ``load``)."""
        return len(self._queue) + len(self._inflight)

    @property
    def running_count(self) -> int:
        return len(self._inflight)

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._queue or self._inflight)

    def owns(self, request) -> bool:
        with self._lock:
            return request in self._queue or request in self._inflight

    # ---- the micro-batching step ----
    def _take_locked(self, now: float) -> list:
        """Form a batch if the latency budget says so (caller holds the
        lock); expires over-deadline queue heads as it goes."""
        while self._queue:
            head = self._queue[0]
            if head.timeout_s is not None and \
                    now - head.submitted_at > head.timeout_s:
                self._queue.popleft()
                self._finish(head, "timeout")
                continue
            break
        if not self._queue:
            return []
        ripe = (len(self._queue) >= self.max_batch
                or not self._inflight
                or now - self._queue[0].submitted_at >= self.max_delay_s)
        if not ripe:
            return []
        batch = []
        while self._queue and len(batch) < self.max_batch:
            req = self._queue.popleft()
            req.state = "running"
            batch.append(req)
        return batch

    def step(self) -> list:
        """Launch the next ripe batch, then resolve the previous one.
        Returns the requests completed this step."""
        completed = []
        with self._lock, trace.span("serve.recsys.step") as sp:
            now = time.monotonic()
            batch = self._take_locked(now)
            if batch:
                try:
                    handle = self.engine.gather_launch(
                        np.stack([r.dense for r in batch]),
                        np.stack([r.sparse for r in batch]))
                except Exception:
                    # engine-level failure: nothing ran — requests go
                    # back to the head unchanged modulo a requeue charge
                    # (a deterministically-poisonous batch must not kill
                    # every engine incarnation forever); the raise feeds
                    # the server loop's strike counter
                    for req in reversed(batch):
                        self._requeue_locked(req, completed)
                    raise
                try:
                    completed += self._resolve_locked()
                except Exception:
                    # the PREVIOUS batch's resolve blew up after this
                    # batch launched: the just-launched requests are in
                    # neither the queue nor _inflight — requeue them or
                    # they strand with done never set
                    for req in reversed(batch):
                        self._requeue_locked(req, completed)
                    raise
                self._inflight = batch
                self._handle = handle
            else:
                completed += self._resolve_locked()
            self.metrics.set_gauge("queue_depth", len(self._queue))
            sp.set("completed", len(completed))
        return completed

    def _resolve_locked(self) -> list:
        if not self._inflight:
            return []
        reqs, handle = self._inflight, self._handle
        try:
            probs = self.engine.finish(handle)
        except Exception:
            self._inflight, self._handle = [], None
            for req in reversed(reqs):
                self._requeue_locked(req, [])
            raise
        self._inflight, self._handle = [], None
        out = []
        now = time.monotonic()
        for i, req in enumerate(reqs):
            req.score = float(probs[i])
            if not req.done.is_set():
                self._ttfr.observe(now - req.submitted_at)
                self.metrics.observe_ttft(now - req.submitted_at)
            self._finish(req, req.status or "ok")
            out.append(req)
        return out

    def _requeue_locked(self, req: RecsysRequest, completed: list) -> bool:
        req.requeues += 1
        if req.requeues > self.max_requeues:
            self._finish(req, "error")
            completed.append(req)
            return False
        req.state = "queued"
        self._queue.appendleft(req)
        self.metrics.inc("requests_requeued")
        return True

    def requeue_inflight(self, *, max_requeues: Optional[int] = None) -> int:
        """Engine-failure path (the server loop calls this on a step
        exception): put the launched batch back at the queue head."""
        with self._lock:
            n = 0
            reqs, self._inflight, self._handle = self._inflight, [], None
            for req in reversed(reqs):
                if self._requeue_locked(req, []):
                    n += 1
            self.metrics.set_gauge("queue_depth", len(self._queue))
            return n

    # ---- migration / failover hand-off (pool surface) ----
    def _export_locked(self, fold: bool) -> list:
        out = []
        reqs, self._inflight, self._handle = self._inflight, [], None
        for req in reqs:
            if fold:
                # the batch was mid-flight when the member died: charge a
                # requeue so a poisonous batch cannot bounce forever
                req.requeues += 1
                if req.requeues > self.max_requeues:
                    self._finish(req, "error")
                    continue
            req.state = "queued"
            out.append((req, None))
        while self._queue:
            out.append((self._queue.popleft(), None))
        for req, _ in out:
            req.owner = None
        self.metrics.set_gauge("queue_depth", 0)
        return out

    def export_inflight(self, *, fold: bool = False) -> list:
        with self._lock:
            pairs = self._export_locked(fold)
            self.metrics.inc("requests_exported", len(pairs))
            return pairs

    def export_inflight_with_slots(self) -> tuple:
        """Pool-drain surface: CTR requests carry no KV slots, so the
        snapshot half is always empty (``migrate_inflight`` then skips
        the wire and re-queues on the peer)."""
        with self._lock:
            return self._export_locked(fold=False), []

    def adopt_inflight(self, pairs, snapshots=None, *,
                       return_count: bool = False):
        pairs = list(pairs)
        if snapshots:
            raise RuntimeError(
                "CTR members hold no KV slots; nothing can adopt "
                "snapshots")
        n = 0
        with self._lock:
            if not self._accepting:
                raise RuntimeError(
                    "scheduler is drained; cannot adopt migrated requests")
            for req, slot in pairs:
                if slot is not None:
                    raise RuntimeError(
                        f"CTR request {req.rid} carries slot {slot}")
                if req.done.is_set():
                    continue  # finished in transit (cancel race)
                req.state = "queued"
                req.owner = self
                self._queue.append(req)
                n += 1
            self.metrics.inc("requests_adopted", n)
            self.metrics.set_gauge("queue_depth", len(self._queue))
        if return_count:
            return {}, n
        return {}

    # ---- lifecycle ----
    def replace_engine(self, engine) -> None:
        with self._lock:
            self._accepting = True
            self._reject_status = "shutdown"
        self.requeue_inflight()
        with self._lock:
            self.engine = engine

    def cancel(self, request, status: str = "cancelled") -> None:
        with self._lock:
            already = request.done.is_set()
            if request in self._queue:
                self._queue.remove(request)
            # a request in the launched batch cannot be un-launched; the
            # resolve's finish_request no-ops against the settled status
            if not already:
                self._finish(request, status)

    def stop_intake(self, status: str = "shutdown") -> None:
        with self._lock:
            self._accepting = False
            self._reject_status = status

    def drain(self, status: str = "shutdown", *,
              stop_accepting: bool = False) -> None:
        with self._lock:
            if stop_accepting:
                self._accepting = False
                self._reject_status = status
            while self._queue:
                self._finish(self._queue.popleft(), status)
            reqs, self._inflight, self._handle = self._inflight, [], None
            for req in reqs:
                self._finish(req, status)

    def _finish(self, req: RecsysRequest, status: str) -> None:
        finish_request(req, status, self.metrics)

    # ---- convenience driver (tests / bench) ----
    def run(self, requests, *, max_steps: int = 100_000) -> dict:
        for r in requests:
            self.submit(r)
        for _ in range(max_steps):
            if not self.has_work():
                break
            self.step()
        return {r.rid: r.score for r in requests}


# ---------------------------------------------------------------------------
# the van front-end
# ---------------------------------------------------------------------------

class RecsysServer(InferenceServer):
    """The blob-channel front-end speaking CTR scoring instead of
    generation: ``{dense: [...], sparse: [...]} -> {score: p}``.  All
    the listener/dedup/engine-loop/failover machinery is inherited from
    :class:`~hetu_tpu.serve.server.InferenceServer` — only the wire
    format hooks differ."""

    def _build_request(self, msg: dict) -> RecsysRequest:
        dense = np.asarray(msg["dense"], np.float32).reshape(-1)
        sparse = np.asarray(msg["sparse"], np.int64).reshape(-1)
        if sparse.shape[0] == 0:
            raise ValueError("empty sparse feature vector")
        # wrong-length features answer 'bad_request' at the wire when the
        # engine's dims are known (a pool front door validates at the
        # member's intake instead — 'overflow' there)
        eng = getattr(self.scheduler, "engine", None)
        for have, want, what in (
                (dense.shape[0], getattr(eng, "dense_dim", None), "dense"),
                (sparse.shape[0], getattr(eng, "fields", None), "sparse")):
            if want is not None and have != want:
                raise ValueError(f"{what} vector has {have} features, "
                                 f"engine serves {want}")
        return RecsysRequest(
            dense=dense, sparse=sparse,
            timeout_s=min(float(msg.get("timeout_s",
                                        self.request_timeout_s)),
                          self.request_timeout_s))

    def _build_response(self, msg: dict, req: RecsysRequest) -> dict:
        return {"id": msg.get("id"), "status": req.status or "ok",
                "score": req.score, "ttfr_s": req.ttfr_s}

    def _bad_request(self, err: Exception) -> dict:
        return {"id": None, "status": "bad_request", "error": str(err),
                "score": None}


class RecsysClient(InferenceClient):
    """Blocking CTR client for one channel pair (same idempotent
    resubmission/dedup contract as the generation client)."""

    def score(self, dense, sparse, *, timeout_s: float = 30.0,
              deadline_s=None, wire_retries: int = 1) -> dict:
        self._rid += 1
        msg = {"id": self._rid, "cn": self._nonce,
               "dense": [float(x) for x in np.asarray(dense).reshape(-1)],
               "sparse": [int(x) for x in np.asarray(sparse).reshape(-1)],
               "timeout_s": timeout_s if deadline_s is None
               else float(deadline_s)}
        return self._roundtrip(msg, timeout_s, wire_retries)


class _PoolFrontDoor:
    """Scheduler-shaped shim that routes a listener's submit through the
    POOL (least-loaded healthy member) instead of one local queue — the
    glue that puts wire listeners in front of a :class:`RecsysPool`.
    The engine-loop half of the server surface is inert (members run
    their own loops)."""

    def __init__(self, pool: "RecsysPool"):
        self.pool = pool
        self.metrics = pool.metrics

    def submit(self, request, **kw):
        return self.pool.submit(request)

    def cancel(self, request, status: str = "cancelled") -> None:
        self.pool._cancel(request, status)

    def has_work(self) -> bool:
        return False

    def step(self) -> list:  # pragma: no cover - loop idles on has_work
        return []

    def requeue_inflight(self, **kw) -> int:
        return 0

    def drain(self, status: str = "shutdown", *,
              stop_accepting: bool = False) -> None:
        return None

    def replace_engine(self, engine) -> None:  # pragma: no cover
        return None


# ---------------------------------------------------------------------------
# pool membership
# ---------------------------------------------------------------------------

def recsys_member_factory(pool, name: str, factory):
    """``ServingPool member_factory`` building a CTR member: guarded
    engine + micro-batching scheduler + listener-less RecsysServer."""
    from hetu_tpu.serve.pool import PoolMember
    engine = _GuardedRecsysEngine(factory())
    sched = RecsysBatcher(engine, max_requeues=pool._max_requeues)
    srv = RecsysServer(
        sched, port=pool.port, own_van=False, max_clients=0,
        request_timeout_s=pool.request_timeout_s,
        max_loop_errors=pool._max_loop_errors,
        failover_grace_s=pool._failover_grace_s)
    return PoolMember(name, factory, sched, srv,
                      fresh_engine=lambda: _GuardedRecsysEngine(factory()))


class RecsysPool:
    """:class:`~hetu_tpu.serve.pool.ServingPool` whose members serve CTR
    scores: same health poll, least-loaded routing, ``serve_engine_kill``
    failover, planned drain and revive — requests are stateless so every
    hand-off is a re-queue (no KV wire transfer).

    Composition (not subclassing) keeps the pool's own surface intact;
    everything not overridden here delegates.
    """

    def __init__(self, engine_factories, **kwargs):
        from hetu_tpu.serve.pool import ServingPool
        kwargs.setdefault("member_factory", recsys_member_factory)
        self._pool = ServingPool(engine_factories, **kwargs)

    def __getattr__(self, name):
        if name == "_pool":
            # __init__ raised before assigning it: a plain AttributeError
            # (not infinite __getattr__ recursion) lets the caller's
            # cleanup see the REAL construction failure
            raise AttributeError(name)
        return getattr(self._pool, name)

    def frontend(self, *, max_clients: int = 4,
                 request_timeout_s: Optional[float] = None) -> RecsysServer:
        """Start wire listeners on the pool's van: clients connect with
        :class:`RecsysClient` and their requests route through the pool
        (the caller closes the returned server before the pool)."""
        return RecsysServer(
            _PoolFrontDoor(self), port=self._pool.port, own_van=False,
            max_clients=int(max_clients),
            request_timeout_s=float(request_timeout_s
                                    if request_timeout_s is not None
                                    else self._pool.request_timeout_s))

    def score(self, dense, sparse, *,
              timeout_s: Optional[float] = None) -> dict:
        """Blocking convenience: route one request to the healthiest
        member and wait; the response dict matches the wire shape."""
        pool = self._pool
        req = RecsysRequest(
            dense=np.asarray(dense, np.float32).reshape(-1),
            sparse=np.asarray(sparse, np.int64).reshape(-1),
            timeout_s=float(timeout_s if timeout_s is not None
                            else pool.request_timeout_s))
        pool.submit(req)
        if not req.done.wait(timeout=req.timeout_s + 15.0):
            pool._cancel(req, "timeout")
        return {"id": req.rid, "status": req.status or "ok",
                "score": req.score, "ttfr_s": req.ttfr_s}


__all__ = [
    "ServingEmbeddingCache", "RecsysEngine", "RecsysBatcher",
    "RecsysRequest", "RecsysServer", "RecsysClient", "RecsysPool",
    "recsys_member_factory", "EngineKilledError", "NOT_CACHED",
    "STALENESS_BUCKETS",
]
