"""Bucketed prefill + single-token decode over the model forwards.

Compilation discipline is the whole point of this module: serving traffic
has arbitrary prompt lengths, and a naive jit would compile one executable
per distinct length.  Instead prompts are right-padded to power-of-two
BUCKETS (plus the cache's max_len as the last bucket), so the engine
compiles at most ``len(buckets)`` prefill executables + 1 decode
executable for the whole life of the server — asserted in
tests/test_serve.py via :meth:`compiled_executables`.

Prefill runs one request at a time (batch 1, bounded compile count);
decode steps ALL cache slots at once with fixed shapes (``[num_slots]``
tokens/lengths), so continuous batching admissions never change the
decode executable.  Free slots ride along masked — wasted FLOPs on an
idle slot are cheaper than a recompile.

Tensor parallelism: pass ``mesh`` and the engine places the parameters
with the Megatron split points (qkv/ffn-in column, out/ffn-down row — the
same ``parallel.strategies.MegatronLM`` preset training uses, minus the
vocab split: serving reads full logits every step) and shards the cache
over the kv-head axis when it divides tp.  XLA SPMD then inserts the
row-parallel all-reduces inside both jitted steps.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_tpu.parallel.mesh import AXIS_TP
from hetu_tpu.parallel.strategies.simple import MegatronLM
from hetu_tpu.serve.kv_cache import KVCache, KVCacheSpec
from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.telemetry import trace


class _DecodeTP(MegatronLM):
    """MegatronLM splits with the vocab kept replicated: a decode step
    reads the full ``[V]`` logits row per sequence every token, so a
    vocab-parallel embedding would all-gather per step for no win at
    serving batch sizes."""

    VOCAB = ()


def _pow2_buckets(lo: int, hi: int) -> tuple:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


class ServeEngine:
    """Owns params + KV cache + the jitted prefill/decode executables.

    model: GPTModel or LlamaModel (anything with ``prefill_with_cache`` /
    ``decode_with_cache``).  num_slots bounds concurrent sequences;
    max_len bounds tokens per sequence (prompt + generation), defaulting
    to the model's max_position.
    """

    def __init__(self, model, variables, *, num_slots: int = 8,
                 max_len: Optional[int] = None, mesh=None,
                 min_bucket: int = 16,
                 metrics: Optional[ServeMetrics] = None):
        self.model = model
        self.metrics = metrics or ServeMetrics()
        c = model.c
        max_len = int(max_len or c.max_position)
        if max_len > c.max_position:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"max_position {c.max_position}")
        spec = KVCacheSpec.from_model(model)
        self.buckets = _pow2_buckets(min(min_bucket, max_len), max_len)

        self.mesh = mesh
        params = variables["params"] if "params" in variables else variables
        cache_sharding = None
        if mesh is not None:
            tp = mesh.shape.get(AXIS_TP, 1)
            params = _DecodeTP().place(params, mesh)
            # kv-head sharded cache when GQA heads divide tp, else
            # replicated (graceful, same policy as Strategy._fit)
            axes = (None, None, None,
                    AXIS_TP if spec.num_kv_heads % tp == 0 else None, None)
            cache_sharding = NamedSharding(mesh, P(*axes))
        self.params = params
        self.cache = KVCache(spec, num_slots, max_len,
                             sharding=cache_sharding)

        # newest token per slot (decode feeds all slots every step)
        self.last_tokens = np.zeros(num_slots, np.int32)
        self.active = np.zeros(num_slots, bool)

        # ONE jitted prefill: jax.jit's shape cache specializes it per
        # bucket width, so bucket_for() alone bounds the executable count
        self._prefill_fn = None
        self._decode_fn = None
        self._seen_buckets = set()

    # ---- compile accounting ----
    def compiled_executables(self) -> int:
        """Executables actually compiled so far (the recompile budget the
        tests assert): sum of jit-cache sizes across the step fns."""
        return sum(fn._cache_size()
                   for fn in (self._prefill_fn, self._decode_fn)
                   if fn is not None)

    @property
    def max_executables(self) -> int:
        """Hard ceiling: one per bucket + one decode."""
        return len(self.buckets) + 1

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds max_len "
                         f"{self.cache.max_len}")

    # ---- jitted step builders ----
    def _build_prefill(self):
        model = self.model

        def fn(params, k_cache, v_cache, ids, slot, true_len):
            # last_index: only the final real position's logits are
            # computed — the padded tail's head matmul is skipped
            logits, k, v = model.prefill_with_cache(
                {"params": params, "state": {}}, ids,
                last_index=true_len - 1)
            # k: [L, 1, S, nkv, hd] — batch dim 1 IS the slot slice, so it
            # writes into [L, slots, T, nkv, hd] at (0, slot, 0) directly
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype), (0, slot, 0, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype), (0, slot, 0, 0, 0))
            first = jnp.argmax(logits[0], -1).astype(jnp.int32)
            return k_cache, v_cache, first

        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_decode(self):
        model = self.model

        def fn(params, k_cache, v_cache, tokens, lengths):
            logits, k_cache, v_cache = model.decode_with_cache(
                {"params": params, "state": {}}, tokens, k_cache, v_cache,
                lengths)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return k_cache, v_cache, nxt

        return jax.jit(fn, donate_argnums=(1, 2))

    # ---- serving steps ----
    def prefill(self, slot: int, prompt_ids) -> int:
        """Run the prompt through the bucketed prefill into ``slot``;
        returns the first generated (greedy) token."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = prompt.shape[0]
        if n < 1:
            raise ValueError("empty prompt")
        if n >= self.cache.max_len:
            raise ValueError(f"prompt of {n} tokens leaves no room to "
                             f"generate within max_len {self.cache.max_len}")
        s = self.bucket_for(n)
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
        if s not in self._seen_buckets:
            self._seen_buckets.add(s)
            self.metrics.inc("prefill_compiles")
            trace.instant("serve.recompile",
                          {"kind": "prefill", "bucket": s})
        with trace.span("serve.prefill") as sp:
            sp.set("slot", int(slot))
            sp.set("tokens", n)
            sp.set("bucket", s)
            ids = np.zeros((1, s), np.int32)
            ids[0, :n] = prompt
            k, v, first = self._prefill_fn(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(ids), jnp.int32(slot), jnp.int32(n))
            # the value fetch is the sync point: inside the span, so the
            # span covers device execution, not just the async dispatch
            first = int(first)
        self.cache.update(k, v)
        self.cache.lengths[slot] = n
        self.last_tokens[slot] = first
        self.active[slot] = True
        self.metrics.inc("prefill_tokens", n)
        return first

    def decode(self) -> dict:
        """One decode step over every slot; returns {slot: token} for the
        active ones.  Inactive slots compute masked garbage (cheaper than
        a shape change) and are ignored."""
        if not self.active.any():
            return {}
        if (self.cache.lengths[self.active] >= self.cache.max_len).any():
            raise RuntimeError(
                "an active slot is at max_len; the scheduler must evict "
                "before decoding further")
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
            self.metrics.inc("decode_compiles")
            trace.instant("serve.recompile", {"kind": "decode"})
        with trace.span("serve.decode") as sp:
            if trace.enabled():  # the reduction is attr-only: skip when off
                sp.set("active", int(self.active.sum()))
            k, v, nxt = self._decode_fn(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(self.last_tokens),
                jnp.asarray(self.cache.lengths))
            # host fetch = the sync point; keep it inside the span (see
            # prefill)
            nxt = np.asarray(nxt)
        self.cache.update(k, v)
        out = {}
        for slot in np.nonzero(self.active)[0]:
            self.cache.lengths[slot] += 1
            self.last_tokens[slot] = nxt[slot]
            out[int(slot)] = int(nxt[slot])
        self.metrics.inc("decode_steps")
        self.metrics.observe_decode(len(out))
        return out

    # ---- live-slot migration ----
    def export_slots(self, slot_ids) -> list:
        """Snapshot mid-decode slots for hand-off to a peer engine: the
        cache's truncated K/V rows plus this engine's per-slot decode
        state (the last emitted token, which is NOT in the cache yet) in
        ``meta`` — everything a peer needs to continue decoding
        token-for-token with zero prefill.

        Exported slots are SUSPENDED (allocated but excluded from
        :meth:`decode`) until the caller either releases them (the
        migration committed) or :meth:`resume_slots` them (rollback).
        The wire transfer runs outside any lock, and a decode step
        admitted in that window would otherwise silently advance the
        exported slots past their requests' recorded tokens — tokens a
        rollback could never recover."""
        for slot in slot_ids:
            if not self.active[int(slot)]:
                raise ValueError(f"slot {int(slot)} is not mid-decode; "
                                 f"nothing to migrate")
        snaps = self.cache.export_slots(slot_ids)
        for s in snaps:
            s.meta["last_token"] = int(self.last_tokens[s.slot])
        for slot in slot_ids:  # suspend LAST: any failure above leaves
            self.active[int(slot)] = False  # every slot still decoding
        return snaps

    def resume_slots(self, slot_ids) -> None:
        """Re-activate slots suspended by :meth:`export_slots` — the
        rollback half of a failed migration: the source engine resumes
        decoding them exactly where they stopped (``last_tokens`` was
        kept through the suspension)."""
        slots = [int(s) for s in slot_ids]
        for slot in slots:  # validate-first: resume is all-or-nothing
            if self.cache.lengths[slot] < 1:
                raise ValueError(f"slot {slot} has no cached tokens to "
                                 f"resume")
        for slot in slots:
            self.active[slot] = True

    def adopt_slots(self, snapshots) -> dict:
        """Adopt peer-exported slots; returns ``{source_slot: slot}``.
        The next :meth:`decode` continues each adopted sequence exactly
        where the source left off — no prefill step runs (the
        ``serve.prefill`` span/metric stays flat, the zero-re-prefill
        contract tests assert)."""
        snaps = list(snapshots)
        for s in snaps:
            if "last_token" not in s.meta:
                raise ValueError(
                    f"slot snapshot {s.slot} has no last_token meta — "
                    f"exported from a cache, not an engine?")
        slot_map = self.cache.import_slots(snaps)
        for s in snaps:
            slot = slot_map[s.slot]
            self.last_tokens[slot] = int(s.meta["last_token"])
            self.active[slot] = True
        self.metrics.inc("slots_adopted", len(slot_map))
        return slot_map

    # ---- slot lifecycle (delegates; engine keeps its masks in sync) ----
    def alloc_slot(self) -> int:
        slot = self.cache.alloc()
        self.active[slot] = False
        return slot

    def release(self, slot: int) -> None:
        self.active[slot] = False
        self.last_tokens[slot] = 0
        self.cache.free(slot)
