"""Bucketed prefill + single-token decode over the model forwards.

Two engines share one serving surface: :class:`ServeEngine` over the
slot cache, and :class:`PagedServeEngine` over the paged pool (page
tables, prefix sharing, chunked prefill — see kv_cache.py).

Compilation discipline is the whole point of this module: serving traffic
has arbitrary prompt lengths, and a naive jit would compile one executable
per distinct length.  Instead prompts are right-padded to power-of-two
BUCKETS (plus the cache's max_len as the last bucket), so the engine
compiles at most ``len(buckets)`` prefill executables + 1 decode
executable for the whole life of the server — asserted in
tests/test_serve.py via :meth:`compiled_executables`.  (The paged
engine's analog: pow2 chunk buckets for prefill, pow2 active-batch x
page-count buckets for decode — tests/test_paged_kv.py.)

Prefill runs one request at a time (batch 1, bounded compile count);
decode steps ALL cache slots at once with fixed shapes (``[num_slots]``
tokens/lengths), so continuous batching admissions never change the
decode executable.  Free slots ride along masked — wasted FLOPs on an
idle slot are cheaper than a recompile.

Tensor parallelism: pass ``mesh`` and the engine places the parameters
with the Megatron split points (qkv/ffn-in column, out/ffn-down row — the
same ``parallel.strategies.MegatronLM`` preset training uses, minus the
vocab split: serving reads full logits every step) and shards the cache
over the kv-head axis when it divides tp.  XLA SPMD then inserts the
row-parallel all-reduces inside both jitted steps.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_tpu.parallel.mesh import AXIS_TP
from hetu_tpu.parallel.strategies.simple import MegatronLM
from hetu_tpu.serve.kv_cache import (
    KVCache, KVCacheSpec, PagedKVCache, pow2_ceil,
)
from hetu_tpu.serve.metrics import ServeMetrics
from hetu_tpu.telemetry import trace


class _DecodeTP(MegatronLM):
    """MegatronLM splits with the vocab kept replicated: a decode step
    reads the full ``[V]`` logits row per sequence every token, so a
    vocab-parallel embedding would all-gather per step for no win at
    serving batch sizes."""

    VOCAB = ()


def _pow2_buckets(lo: int, hi: int) -> tuple:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


class ServeEngine:
    """Owns params + KV cache + the jitted prefill/decode executables.

    model: GPTModel or LlamaModel (anything with ``prefill_with_cache`` /
    ``decode_with_cache``).  num_slots bounds concurrent sequences;
    max_len bounds tokens per sequence (prompt + generation), defaulting
    to the model's max_position.
    """

    def __init__(self, model, variables, *, num_slots: int = 8,
                 max_len: Optional[int] = None, mesh=None,
                 min_bucket: int = 16,
                 metrics: Optional[ServeMetrics] = None):
        self.model = model
        self.metrics = metrics or ServeMetrics()
        c = model.c
        max_len = int(max_len or c.max_position)
        if max_len > c.max_position:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"max_position {c.max_position}")
        spec = KVCacheSpec.from_model(model)
        self.buckets = _pow2_buckets(min(min_bucket, max_len), max_len)

        self.mesh = mesh
        # kv-head sharded cache when GQA heads divide tp, else
        # replicated (graceful, same policy as Strategy._fit)
        self.params, cache_sharding = _place_params_and_cache_spec(
            model, variables, mesh, spec)
        self.cache = KVCache(spec, num_slots, max_len,
                             sharding=cache_sharding)

        # newest token per slot (decode feeds all slots every step)
        self.last_tokens = np.zeros(num_slots, np.int32)
        self.active = np.zeros(num_slots, bool)

        # ONE jitted prefill: jax.jit's shape cache specializes it per
        # bucket width, so bucket_for() alone bounds the executable count
        self._prefill_fn = None
        self._decode_fn = None
        self._seen_buckets = set()

    # ---- compile accounting ----
    def compiled_executables(self) -> int:
        """Executables actually compiled so far (the recompile budget the
        tests assert): sum of jit-cache sizes across the step fns."""
        return sum(fn._cache_size()
                   for fn in (self._prefill_fn, self._decode_fn)
                   if fn is not None)

    @property
    def max_executables(self) -> int:
        """Hard ceiling: one per bucket + one decode."""
        return len(self.buckets) + 1

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds max_len "
                         f"{self.cache.max_len}")

    # ---- jitted step builders ----
    def _build_prefill(self):
        model = self.model

        def fn(params, k_cache, v_cache, ids, slot, true_len):
            # last_index: only the final real position's logits are
            # computed — the padded tail's head matmul is skipped
            logits, k, v = model.prefill_with_cache(
                {"params": params, "state": {}}, ids,
                last_index=true_len - 1)
            # k: [L, 1, S, nkv, hd] — batch dim 1 IS the slot slice, so it
            # writes into [L, slots, T, nkv, hd] at (0, slot, 0) directly
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype), (0, slot, 0, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype), (0, slot, 0, 0, 0))
            first = jnp.argmax(logits[0], -1).astype(jnp.int32)
            return k_cache, v_cache, first

        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_decode(self):
        model = self.model

        def fn(params, k_cache, v_cache, tokens, lengths):
            logits, k_cache, v_cache = model.decode_with_cache(
                {"params": params, "state": {}}, tokens, k_cache, v_cache,
                lengths)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return k_cache, v_cache, nxt

        return jax.jit(fn, donate_argnums=(1, 2))

    # ---- serving steps ----
    def prefill(self, slot: int, prompt_ids) -> int:
        """Run the prompt through the bucketed prefill into ``slot``;
        returns the first generated (greedy) token."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = prompt.shape[0]
        if n < 1:
            raise ValueError("empty prompt")
        if n >= self.cache.max_len:
            raise ValueError(f"prompt of {n} tokens leaves no room to "
                             f"generate within max_len {self.cache.max_len}")
        s = self.bucket_for(n)
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
        if s not in self._seen_buckets:
            self._seen_buckets.add(s)
            self.metrics.inc("prefill_compiles")
            trace.instant("serve.recompile",
                          {"kind": "prefill", "bucket": s})
        with trace.span("serve.prefill") as sp:
            sp.set("slot", int(slot))
            sp.set("tokens", n)
            sp.set("bucket", s)
            ids = np.zeros((1, s), np.int32)
            ids[0, :n] = prompt
            k, v, first = self._prefill_fn(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(ids), jnp.int32(slot), jnp.int32(n))
            # the value fetch is the sync point: inside the span, so the
            # span covers device execution, not just the async dispatch
            first = int(first)
        self.cache.update(k, v)
        self.cache.lengths[slot] = n
        self.last_tokens[slot] = first
        self.active[slot] = True
        self.metrics.inc("prefill_tokens", n)
        return first

    def decode(self) -> dict:
        """One decode step over every slot; returns {slot: token} for the
        active ones.  Inactive slots compute masked garbage (cheaper than
        a shape change) and are ignored."""
        if not self.active.any():
            return {}
        if (self.cache.lengths[self.active] >= self.cache.max_len).any():
            raise RuntimeError(
                "an active slot is at max_len; the scheduler must evict "
                "before decoding further")
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
            self.metrics.inc("decode_compiles")
            trace.instant("serve.recompile", {"kind": "decode"})
        with trace.span("serve.decode") as sp:
            if trace.enabled():  # the reduction is attr-only: skip when off
                sp.set("active", int(self.active.sum()))
            k, v, nxt = self._decode_fn(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(self.last_tokens),
                jnp.asarray(self.cache.lengths))
            # host fetch = the sync point; keep it inside the span (see
            # prefill)
            nxt = np.asarray(nxt)
        self.cache.update(k, v)
        out = {}
        for slot in np.nonzero(self.active)[0]:
            self.cache.lengths[slot] += 1
            self.last_tokens[slot] = nxt[slot]
            out[int(slot)] = int(nxt[slot])
        self.metrics.inc("decode_steps")
        self.metrics.observe_decode(len(out))
        return out

    # ---- live-slot migration ----
    def export_slots(self, slot_ids) -> list:
        """Snapshot mid-decode slots for hand-off to a peer engine: the
        cache's truncated K/V rows plus this engine's per-slot decode
        state (the last emitted token, which is NOT in the cache yet) in
        ``meta`` — everything a peer needs to continue decoding
        token-for-token with zero prefill.

        Exported slots are SUSPENDED (allocated but excluded from
        :meth:`decode`) until the caller either releases them (the
        migration committed) or :meth:`resume_slots` them (rollback).
        The wire transfer runs outside any lock, and a decode step
        admitted in that window would otherwise silently advance the
        exported slots past their requests' recorded tokens — tokens a
        rollback could never recover."""
        for slot in slot_ids:
            if not self.active[int(slot)]:
                raise ValueError(f"slot {int(slot)} is not mid-decode; "
                                 f"nothing to migrate")
        snaps = self.cache.export_slots(slot_ids)
        for s in snaps:
            s.meta["last_token"] = int(self.last_tokens[s.slot])
        for slot in slot_ids:  # suspend LAST: any failure above leaves
            self.active[int(slot)] = False  # every slot still decoding
        return snaps

    def resume_slots(self, slot_ids) -> None:
        """Re-activate slots suspended by :meth:`export_slots` — the
        rollback half of a failed migration: the source engine resumes
        decoding them exactly where they stopped (``last_tokens`` was
        kept through the suspension)."""
        slots = [int(s) for s in slot_ids]
        for slot in slots:  # validate-first: resume is all-or-nothing
            if self.cache.lengths[slot] < 1:
                raise ValueError(f"slot {slot} has no cached tokens to "
                                 f"resume")
        for slot in slots:
            self.active[slot] = True

    def adopt_slots(self, snapshots) -> dict:
        """Adopt peer-exported slots; returns ``{source_slot: slot}``.
        The next :meth:`decode` continues each adopted sequence exactly
        where the source left off — no prefill step runs (the
        ``serve.prefill`` span/metric stays flat, the zero-re-prefill
        contract tests assert)."""
        snaps = list(snapshots)
        for s in snaps:
            if "last_token" not in s.meta:
                raise ValueError(
                    f"slot snapshot {s.slot} has no last_token meta — "
                    f"exported from a cache, not an engine?")
        slot_map = self.cache.import_slots(snaps)
        for s in snaps:
            slot = slot_map[s.slot]
            self.last_tokens[slot] = int(s.meta["last_token"])
            self.active[slot] = True
        self.metrics.inc("slots_adopted", len(slot_map))
        return slot_map

    # ---- slot lifecycle (delegates; engine keeps its masks in sync) ----
    def alloc_slot(self) -> int:
        slot = self.cache.alloc()
        self.active[slot] = False
        return slot

    def release(self, slot: int) -> None:
        self.active[slot] = False
        self.last_tokens[slot] = 0
        self.cache.free(slot)


def _place_params_and_cache_spec(model, variables, mesh, spec):
    """The tp placement both engines share: Megatron split points on the
    params, kv-head-sharded cache when GQA heads divide tp."""
    params = variables["params"] if "params" in variables else variables
    cache_sharding = None
    if mesh is not None:
        tp = mesh.shape.get(AXIS_TP, 1)
        params = _DecodeTP().place(params, mesh)
        axes = (None, None, None,
                AXIS_TP if spec.num_kv_heads % tp == 0 else None, None)
        cache_sharding = NamedSharding(mesh, P(*axes))
    return params, cache_sharding


class _PrefillCursor:
    """Host-side state of one in-progress chunked prefill."""

    __slots__ = ("prompt", "pos", "n", "max_tokens", "matched")

    def __init__(self, prompt: np.ndarray, max_tokens: int):
        self.prompt = prompt
        self.pos = 0             # next un-prefilled position
        self.n = int(prompt.shape[0])
        self.max_tokens = int(max_tokens)
        self.matched = False     # prefix match ran (first chunk)

    @property
    def done(self) -> bool:
        return self.pos >= self.n


class PagedServeEngine:
    """ServeEngine over a :class:`PagedKVCache`: paged gather/scatter
    decode, chunked prefill, prefix sharing with copy-on-write.

    Drop-in for :class:`ServeEngine` everywhere the scheduler/pool/
    migration stack touches an engine (same prefill/decode/export/adopt/
    release surface, same ``cache.lengths``/``max_len``/``num_free``
    geometry) plus the paged additions the scheduler's page-budget
    admission and chunked-prefill interleave use: :meth:`admission_ok`,
    :meth:`begin_prefill`, :meth:`prefill_step`.

    Compilation discipline: chunked prefill compiles one executable per
    power-of-two CHUNK bucket (the page table always gathers the full
    per-slot table, so chunk width is the only specializing shape);
    decode compiles one executable per power-of-two PAGE-COUNT bucket —
    short sequences gather (and write back) a fraction of ``max_len``
    instead of every slot's worst case, which is where paged decode's
    per-step byte traffic win comes from.  Both are asserted via
    :meth:`compiled_executables` like the slot engine.
    """

    def __init__(self, model, variables, *, num_slots: int = 8,
                 max_len: Optional[int] = None, mesh=None,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 min_bucket: int = 16, prefix_sharing: bool = True,
                 max_prefix_entries: int = 256,
                 metrics: Optional[ServeMetrics] = None):
        self.model = model
        self.metrics = metrics or ServeMetrics()
        c = model.c
        max_len = int(max_len or c.max_position)
        if max_len > c.max_position:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"max_position {c.max_position}")
        spec = KVCacheSpec.from_model(model)
        self.mesh = mesh
        self.params, cache_sharding = _place_params_and_cache_spec(
            model, variables, mesh, spec)
        self.cache = PagedKVCache(
            spec, num_slots, max_len, page_size=page_size,
            num_pages=num_pages, sharding=cache_sharding,
            max_prefix_entries=max_prefix_entries if prefix_sharing else 0)
        # chunked prefill: chunk ends align to prefill_chunk boundaries
        # (a multiple of page_size), so chunks fill whole pages and the
        # prefix index's page-aligned entries match cleanly
        if prefill_chunk is None:
            prefill_chunk = max(4 * page_size, min_bucket)
        ps = self.cache.page_size
        self.prefill_chunk = -(-int(prefill_chunk) // ps) * ps
        self.chunk_buckets = _pow2_buckets(
            min(min_bucket, self.prefill_chunk), self.prefill_chunk)

        self.last_tokens = np.zeros(num_slots, np.int32)
        self.active = np.zeros(num_slots, bool)
        self._cursors: dict = {}  # slot -> _PrefillCursor

        self._chunk_fn = None      # hot path, specialized per chunk bucket
        self._chunk_fn_ext = None  # extended-view boundary path
        self._decode_fn = None     # one jit, specialized per page bucket
        self._seen_chunk_buckets = set()
        self._seen_page_buckets = set()

    # ---- compile accounting ----
    def compiled_executables(self) -> int:
        return sum(fn._cache_size()
                   for fn in (self._chunk_fn, self._chunk_fn_ext,
                              self._decode_fn)
                   if fn is not None)

    @property
    def max_executables(self) -> int:
        """One per chunk bucket per view family (hot + extended
        boundary) + one per (pow2 active-batch, pow2 page-count) decode
        bucket pair."""
        n_page_buckets = 1
        b = 1
        while b < self.cache.pages_per_slot:
            b *= 2
            n_page_buckets += 1
        n_batch_buckets = 1
        b = 1
        while b < self.cache.num_slots:
            b *= 2
            n_batch_buckets += 1
        return (2 * len(self.chunk_buckets)
                + n_page_buckets * n_batch_buckets)

    def chunk_bucket_for(self, n: int) -> int:
        for b in self.chunk_buckets:
            if n <= b:
                return b
        raise ValueError(f"chunk of {n} tokens exceeds prefill_chunk "
                         f"{self.prefill_chunk}")

    # ---- jitted step builders ----
    def _build_chunk(self, n_table: int):
        """One chunk executable family over a gathered view of
        ``n_table`` pages.  TWO families exist: the hot path gathers
        exactly ``pages_per_slot`` pages, and a BOUNDARY path
        (:attr:`_chunk_fn_ext`) extends the view by one max-chunk of
        scratch columns — a padded final chunk near max_len writes (and
        re-extracts) rows at ``start + bucket``, which can run past
        ``pages_per_slot * ps``, and without the extension
        dynamic_update_slice/dynamic_slice would CLAMP the start and
        silently smear pad junk over real history (wrong tokens on
        exactly the near-full-context shared-prompt resubmit).  Keeping
        the extension off the hot path keeps the common chunk's gather
        at its minimum width."""
        model = self.model
        cache = self.cache
        ps = cache.page_size
        L = cache.spec.num_layers
        H, D = cache.spec.num_kv_heads, cache.spec.head_dim

        def fn(params, k_pool, v_pool, aux):
            # aux [3*sc + n_table + 2] int32 packs the chunk's host
            # operands (ids | write pages | write offsets | page table |
            # start | last) into one device_put, like the decode step
            sc = (aux.shape[0] - n_table - 2) // 3
            ids = aux[:sc][None]
            wpage = aux[sc:2 * sc]
            woff = aux[2 * sc:3 * sc]
            table = aux[3 * sc:3 * sc + n_table]
            start = aux[3 * sc + n_table]
            last = aux[3 * sc + n_table + 1]
            k_seq = k_pool[:, table].reshape(L, 1, n_table * ps, H, D)
            v_seq = v_pool[:, table].reshape(L, 1, n_table * ps, H, D)
            logits, k_seq, v_seq = model.prefill_chunk_with_cache(
                {"params": params, "state": {}}, ids, k_seq, v_seq,
                start, last_index=last)
            tok = jnp.argmax(logits[0], -1).astype(jnp.int32)
            rows_k = jax.lax.dynamic_slice_in_dim(k_seq[:, 0], start, sc,
                                                  axis=1)
            rows_v = jax.lax.dynamic_slice_in_dim(v_seq[:, 0], start, sc,
                                                  axis=1)
            # per-token scatter through the host-built write map: real
            # positions land in their pages, pad positions in scratch 0
            k_pool = k_pool.at[:, wpage, woff].set(rows_k)
            v_pool = v_pool.at[:, wpage, woff].set(rows_v)
            return k_pool, v_pool, tok

        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_decode(self):
        model = self.model
        cache = self.cache
        ps = cache.page_size
        L = cache.spec.num_layers
        H, D = cache.spec.num_kv_heads, cache.spec.head_dim

        def fn(params, k_pool, v_pool, aux):
            # aux [B, n_pg + 4] int32 packs every host-side operand of
            # the step (page table | length | token | write page | write
            # offset) into ONE device_put — five small uploads per step
            # cost more wall time than the decode math at serving batch
            # sizes
            b = aux.shape[0]
            n_pg = aux.shape[1] - 4
            tables = aux[:, :n_pg]
            lengths = aux[:, n_pg]
            tokens = aux[:, n_pg + 1]
            wpage = aux[:, n_pg + 2]
            woff = aux[:, n_pg + 3]
            k_seq = k_pool[:, tables].reshape(L, b, n_pg * ps, H, D)
            v_seq = v_pool[:, tables].reshape(L, b, n_pg * ps, H, D)
            logits, k_seq, v_seq = model.decode_with_cache(
                {"params": params, "state": {}}, tokens, k_seq, v_seq,
                lengths)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            # only the newly written token row goes back to the pool —
            # a decode step moves O(B) token rows, never the gathered
            # sequence view
            tok_k = jax.vmap(
                lambda kb, i: jax.lax.dynamic_index_in_dim(
                    kb, i, axis=1, keepdims=False),
                in_axes=(1, 0), out_axes=1)(k_seq, lengths)
            tok_v = jax.vmap(
                lambda vb, i: jax.lax.dynamic_index_in_dim(
                    vb, i, axis=1, keepdims=False),
                in_axes=(1, 0), out_axes=1)(v_seq, lengths)
            k_pool = k_pool.at[:, wpage, woff].set(tok_k)
            v_pool = v_pool.at[:, wpage, woff].set(tok_v)
            return k_pool, v_pool, nxt

        return jax.jit(fn, donate_argnums=(1, 2))

    # ---- admission (the scheduler's page-budget backpressure) ----
    def admission_pages(self, prompt_len: int, max_tokens: int,
                        shared_tokens: int = 0) -> int:
        """Worst-case pages an admission can touch: prompt + generation
        (capped at max_len) minus already-shared pages, plus one page of
        copy-on-write headroom."""
        total = min(int(prompt_len) + int(max_tokens) + 1,
                    self.cache.max_len)
        return max(self.cache.pages_for_tokens(total)
                   - self.cache.pages_for_tokens(int(shared_tokens)), 0) + 1

    def admission_ok(self, prompt, max_tokens: int) -> bool:
        """True when the page pool can hold this request's worst case
        alongside every outstanding reservation.  Prefix-shared pages
        are credited — the dedup is what lets a pool of identical system
        prompts admit far past the slot cache's capacity.

        The uncredited check runs first: when the worst case fits
        anyway (the common uncontended admission), no prefix probe runs
        at all — a backpressured queue head re-probes every scheduler
        step, and hashing its full prompt each time is wasted work
        unless the shared credit is what decides.  When the probe does
        run it is LRU-neutral (``touch=False``): a request must not pin
        index entries it never adopted."""
        avail = self.cache.available_pages()
        if self.admission_pages(len(prompt), max_tokens, 0) <= avail:
            return True
        n_shared, _ = self.cache.match_prefix(prompt, touch=False)
        if not n_shared:
            return False
        return self.admission_pages(len(prompt), max_tokens,
                                    n_shared) <= avail

    # ---- chunked prefill ----
    def begin_prefill(self, slot: int, prompt_ids, *,
                      max_tokens: int = 0) -> None:
        """Start a chunked prefill into ``slot``: reserve the worst-case
        page budget and park a cursor for :meth:`prefill_step` to
        advance.  The prefix match runs on the FIRST chunk, not here —
        so a burst of identical prompts admitted in one scheduler sweep
        still shares whenever an earlier request's prefill COMPLETES
        (register_prefix runs on its final chunk) before a later
        request's first chunk.  Multi-chunk prompts whose first chunks
        all land in the same interleave window can still prefill
        privately — the match is one-shot, and adopting a prefix after
        a chunk has written would mean merging half-built tables (a
        known residual, not attempted).  The slot stays INACTIVE (no
        decode) until the final chunk emits the first token."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = prompt.shape[0]
        if n < 1:
            raise ValueError("empty prompt")
        if n >= self.cache.max_len:
            raise ValueError(f"prompt of {n} tokens leaves no room to "
                             f"generate within max_len {self.cache.max_len}")
        self.cache.reserve(slot, self.admission_pages(n, max_tokens, 0))
        self._cursors[slot] = _PrefillCursor(prompt, max_tokens)
        self.active[slot] = False

    def _match_on_first_chunk(self, slot: int, cur: _PrefillCursor) -> None:
        cur.matched = True
        n_shared, pages = self.cache.match_prefix(cur.prompt)
        if n_shared and not self.cache.tables[slot]:
            self.cache.adopt_prefix(slot, n_shared, pages)
            cur.pos = n_shared
            # shrink the admission's reservation by the shared credit
            self.cache.reserve(slot, self.admission_pages(
                cur.n, cur.max_tokens, n_shared))
            self.metrics.inc("prefix_hits")
            self.metrics.inc("prefix_hit_tokens", n_shared)
            trace.instant("serve.prefix_hit",
                          {"slot": int(slot), "tokens": int(n_shared)})
        self.metrics.inc("prefix_miss_tokens", cur.n - cur.pos)

    def prefill_step(self, slot: int) -> Optional[int]:
        """Run the next page-aligned chunk of ``slot``'s prefill.
        Returns the first generated (greedy) token when the final chunk
        completes (the slot then decodes), else None."""
        cur = self._cursors.get(slot)
        if cur is None:
            raise ValueError(f"slot {slot} has no prefill in progress")
        if not cur.matched:
            self._match_on_first_chunk(slot, cur)
        start = cur.pos
        end = min(cur.n, (start // self.prefill_chunk + 1)
                  * self.prefill_chunk)
        size = end - start
        s = self.chunk_bucket_for(size)
        ps = self.cache.page_size
        n_table = self.cache.pages_per_slot
        # boundary path: the PADDED window [start, start+s) runs past
        # the slot's own page view — use the extended-view executable
        # family so nothing clamps (see _build_chunk)
        if start + s > n_table * ps:
            n_table += -(-self.prefill_chunk // ps)
            if self._chunk_fn_ext is None:
                self._chunk_fn_ext = self._build_chunk(n_table)
            chunk_fn = self._chunk_fn_ext
        else:
            if self._chunk_fn is None:
                self._chunk_fn = self._build_chunk(n_table)
            chunk_fn = self._chunk_fn
        if (s, n_table) not in self._seen_chunk_buckets:
            self._seen_chunk_buckets.add((s, n_table))
            self.metrics.inc("prefill_compiles")
            trace.instant("serve.recompile",
                          {"kind": "prefill_chunk", "bucket": s})
        cow0 = self.cache.cow_copies
        wp, wo = self.cache.prepare_write(slot, start, size)
        wp, wo = self.cache.padded_write_map(wp, wo, s)
        aux = np.zeros(3 * s + n_table + 2, np.int32)
        aux[:size] = cur.prompt[start:end]
        aux[s:2 * s] = wp
        aux[2 * s:3 * s] = wo
        t = self.cache.tables[slot]
        aux[3 * s:3 * s + len(t)] = t
        aux[3 * s + n_table] = start
        aux[3 * s + n_table + 1] = size - 1
        with trace.span("serve.prefill_chunk") as sp:
            sp.set("slot", int(slot))
            sp.set("start", int(start))
            sp.set("tokens", int(size))
            sp.set("bucket", int(s))
            k, v, tok = chunk_fn(
                self.params, self.cache.k, self.cache.v, jnp.asarray(aux))
            tok = int(tok)  # sync point inside the span (see ServeEngine)
        self.cache.update(k, v)
        self.cache.lengths[slot] = end
        cur.pos = end
        self.metrics.inc("prefill_tokens", size)
        self.metrics.inc("prefill_chunks")
        if self.cache.cow_copies > cow0:
            self.metrics.inc("cow_copies", self.cache.cow_copies - cow0)
        if not cur.done:
            return None
        del self._cursors[slot]
        self.cache.register_prefix(slot, cur.prompt)
        self.last_tokens[slot] = tok
        self.active[slot] = True
        return tok

    def prefill(self, slot: int, prompt_ids) -> int:
        """Whole-prompt prefill (the slot-engine-compatible surface):
        begin + advance every chunk in one call."""
        self.begin_prefill(slot, prompt_ids)
        while True:
            tok = self.prefill_step(slot)
            if tok is not None:
                return tok

    # ---- decode ----
    def decode(self) -> dict:
        """One decode step over the ACTIVE slots (paged gather/scatter);
        returns {slot: token} for them.

        Unlike the slot engine (which steps every slot, active or not —
        its cache rows exist anyway), the paged decode gathers only a
        power-of-two BUCKET of active slots: per-step work scales with
        live traffic, not the engine's concurrency ceiling, which is
        what lets a paged engine carry 4x the slots of a slot engine at
        the same per-step cost.  Pad rows in the bucket duplicate a real
        slot's table (harmless gather) but their write map points at the
        scratch page, so they can never corrupt the pool."""
        act = np.nonzero(self.active)[0]
        if len(act) == 0:
            return {}
        if (self.cache.lengths[act] >= self.cache.max_len).any():
            raise RuntimeError(
                "an active slot is at max_len; the scheduler must evict "
                "before decoding further")
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        cow0 = self.cache.cow_copies
        bb = pow2_ceil(len(act), self.cache.num_slots)
        sl = np.zeros(bb, np.int32)
        sl[:len(act)] = act
        # grow/COW the write target of every active slot BEFORE the step
        wp = np.zeros(bb, np.int32)
        wo = np.zeros(bb, np.int32)
        for i, slot in enumerate(act):
            p, o = self.cache.prepare_write(
                int(slot), int(self.cache.lengths[slot]), 1)
            wp[i], wo[i] = p[0], o[0]
        # page bucket over ACTIVE slots only (after prepare_write grew
        # them): an inactive mid-chunked-prefill long prompt must not
        # inflate every interleaved decode's gather to its table width —
        # that would re-create exactly the long-arrival latency spike
        # the chunk interleave exists to remove
        n_pg = pow2_ceil(
            max(len(self.cache.tables[int(s)]) for s in act),
            self.cache.pages_per_slot)
        if (bb, n_pg) not in self._seen_page_buckets:
            self._seen_page_buckets.add((bb, n_pg))
            self.metrics.inc("decode_compiles")
            trace.instant("serve.recompile",
                          {"kind": "decode", "pages": int(n_pg),
                           "batch": int(bb)})
        aux = np.zeros((bb, n_pg + 4), np.int32)
        for i, slot in enumerate(sl):
            t = self.cache.tables[slot][:n_pg]
            aux[i, :len(t)] = t
        aux[:, n_pg] = self.cache.lengths[sl]
        aux[:, n_pg + 1] = self.last_tokens[sl]
        aux[:, n_pg + 2] = wp
        aux[:, n_pg + 3] = wo
        with trace.span("serve.decode") as sp:
            if trace.enabled():
                sp.set("active", int(len(act)))
                sp.set("pages", int(n_pg))
            k, v, nxt = self._decode_fn(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(aux))
            nxt = np.asarray(nxt)  # host fetch = sync point, in the span
        self.cache.update(k, v)
        out = {}
        for i, slot in enumerate(act):
            self.cache.lengths[slot] += 1
            self.last_tokens[slot] = nxt[i]
            out[int(slot)] = int(nxt[i])
        if self.cache.cow_copies > cow0:
            self.metrics.inc("cow_copies", self.cache.cow_copies - cow0)
        self.metrics.inc("decode_steps")
        self.metrics.observe_decode(len(out))
        self.metrics.set_gauge("pages_in_use", self.cache.pages_in_use)
        self.metrics.set_gauge("prefix_entries", self.cache.prefix_entries)
        return out

    # ---- live-slot migration (same contract as ServeEngine) ----
    def export_slots(self, slot_ids) -> list:
        for slot in slot_ids:
            if not self.active[int(slot)]:
                raise ValueError(f"slot {int(slot)} is not mid-decode; "
                                 f"nothing to migrate")
        snaps = self.cache.export_slots(slot_ids)
        for s in snaps:
            s.meta["last_token"] = int(self.last_tokens[s.slot])
        for slot in slot_ids:
            self.active[int(slot)] = False
        return snaps

    def resume_slots(self, slot_ids) -> None:
        slots = [int(s) for s in slot_ids]
        for slot in slots:
            if self.cache.lengths[slot] < 1:
                raise ValueError(f"slot {slot} has no cached tokens to "
                                 f"resume")
        for slot in slots:
            self.active[slot] = True

    def adopt_slots(self, snapshots) -> dict:
        snaps = list(snapshots)
        for s in snaps:
            if "last_token" not in s.meta:
                raise ValueError(
                    f"slot snapshot {s.slot} has no last_token meta — "
                    f"exported from a cache, not an engine?")
        slot_map = self.cache.import_slots(snaps)
        for s in snaps:
            slot = slot_map[s.slot]
            self.last_tokens[slot] = int(s.meta["last_token"])
            self.active[slot] = True
        self.metrics.inc("slots_adopted", len(slot_map))
        return slot_map

    def reindex_prefix(self, slot: int, tokens) -> None:
        """Re-dedup an ADOPTED slot into this engine's prefix index:
        register the page-boundary hashes of ``tokens`` (the slot's
        cached token stream — the scheduler knows it; the cache only
        holds K/V rows) against the freshly imported pages.  Without
        this, post-drain traffic sharing the migrated requests' prompts
        re-prefills the prefix from scratch until the imported pages
        age out — the receiver keeps the source's hit rate only if the
        hashes move with the pages.  Page-aligned entries only: the
        tail page is mid-decode (``register_prefix(aligned_only)``)."""
        n = int(self.cache.lengths[slot])
        toks = list(tokens)[:n]
        if len(toks) < n or n < self.cache.page_size:
            return  # stream shorter than the cached rows (defensive),
            # or no complete page to index
        before = self.cache.prefix_entries
        self.cache.register_prefix(slot, toks, aligned_only=True)
        added = self.cache.prefix_entries - before
        if added > 0:
            self.metrics.inc("prefix_reindexed", added)

    # ---- slot lifecycle ----
    def alloc_slot(self) -> int:
        slot = self.cache.alloc()
        self.active[slot] = False
        return slot

    def release(self, slot: int) -> None:
        self.active[slot] = False
        self.last_tokens[slot] = 0
        self._cursors.pop(slot, None)
        self.cache.free(slot)
