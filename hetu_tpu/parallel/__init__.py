from hetu_tpu.parallel.mesh import (
    MeshConfig, make_mesh, local_mesh, AXIS_DP, AXIS_TP, AXIS_PP, AXIS_EP,
    AXIS_SP,
)
from hetu_tpu.parallel.spec import ShardSpec, NodeStatus
from hetu_tpu.parallel.hetpipe import HetPipeWorker, make_weight_table
