"""PipeDream-style 1F1B pipeline runtime.

Reference: python/hetu/gpu_ops/pipedream_subexecutor.py — the 1F1B
generator schedule (:25-48) with per-micro-batch weight stashing (:93-120).

TPU runtime: unlike GPipe (parallel/pipeline.py), whose autodiff reversal
stores EVERY microbatch's stage activations, this executor interleaves
forward and backward ticks explicitly so a stage holds at most
``2*n_stages`` stashed microbatch INPUTS (activation checkpointing at stage
granularity — backward recomputes the stage forward from the stashed input
via jax.vjp).  Memory: O(n_stages) stashes vs GPipe's O(n_microbatches).

Weight stashing note: the reference stashes WEIGHTS per in-flight
microbatch so delayed backwards use the weights their forward saw.  Here
parameters are functionally frozen for the whole step (grads apply once at
the end — the PipeDream-Flush / 1F1B-with-flush variant Galvatron uses),
so forward/backward always agree by construction and the stash holds only
activations.

Schedule (flush variant): tick t runs, per stage s,
  forward  of microbatch f whenever the warmup/steady pattern admits one,
  backward of microbatch b once the next stage has returned its cotangent,
interleaved exactly as pipedream_schedule(n_stages, M) prescribes; the
implementation runs BOTH phases each tick (masked) which realizes that
order with the same bubble structure.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from jax.sharding import Mesh, PartitionSpec as P


class PipeDream1F1B:
    """1F1B (flush) pipeline over a homogeneous block stack.

    block_fn(stage_params, h) -> h; stage s applies its [L/S] slice via
    scan.  Usage:

        pipe = PipeDream1F1B(block_fn, mesh, n_microbatches=8)
        stacked = pipe.stack_params(per_layer_params)   # [S, L/S, ...]
        out, grads = pipe.forward_and_grad(stacked, h, cotangent)
    or, with a scalar loss on the last stage's outputs, use
    `value_and_grad(stacked, h, loss_fn)`.
    """

    def __init__(self, block_fn: Callable, mesh: Mesh, *, axis: str = "pp",
                 n_microbatches: int = 4):
        self.block_fn = block_fn
        self.mesh = mesh
        self.axis = axis
        self.n_stages = mesh.shape[axis]
        self.n_microbatches = n_microbatches

    def stack_params(self, per_layer_params):
        from hetu_tpu.parallel.pipeline import stack_stage_params
        return stack_stage_params(per_layer_params, self.n_stages)

    # ---- core: forward outputs + parameter grads in ONE pipelined pass ----
    def _run(self, stacked_params, xs, gout, *, fwd_only: bool = False):
        """xs [M, mb, ...] stage-0 inputs; gout [M, mb, ...] cotangents of
        the last stage's outputs.  Returns (outs [M, mb, ...], grads like
        stacked_params local slice).  fwd_only skips the whole backward
        phase (used by value_and_grad's output pass)."""
        M = self.n_microbatches
        n = self.n_stages
        axis = self.axis
        block = self.block_fn

        def stage_fwd(params, h):
            def body(carry, p_l):
                return block(p_l, carry), None
            out, _ = lax.scan(body, h, params)
            return out

        def local(params, xs, gout):
            params = jax.tree_util.tree_map(lambda a: a[0], params)
            mask_shape = xs.shape[1:]
            s = lax.axis_index(axis)

            # a forward for microbatch f runs on this stage at tick f + s;
            # its backward returns here at tick 2*n - 2 + 2*(f - ... ) —
            # with the flush schedule below, bwd of f runs at stage s at
            # tick T_b(f, s) = (n - 1) + f + (n - 1 - s) = 2n - 2 + f - s.
            T = (n - 1 + M) if fwd_only else (2 * n - 2 + M)

            # stash depth 2n: with fwd pacing f+s and bwd at 2n-2-s+f, a
            # stage holds at most 2n-2-2s in-flight inputs; 2n slots make
            # slot reuse (f and f+2n) always land after the consume tick
            dt = xs.dtype  # keep activations in the input precision
            stash = jnp.zeros((2 * n, *mask_shape), dt)  # in-flight inputs
            fwd_buf = jnp.zeros(mask_shape, dt)   # activation hop fwd
            bwd_buf = jnp.zeros(mask_shape, dt)   # cotangent hop bwd
            outs = jnp.zeros_like(xs)
            grads0 = jax.tree_util.tree_map(jnp.zeros_like, params)
            perm_f = [(j, (j + 1) % n) for j in range(n)]
            perm_b = [(j, (j - 1) % n) for j in range(n)]

            def tick(carry, t):
                stash, fwd_buf, bwd_buf, outs, grads = carry

                # ---- forward phase of this tick ----
                f_id = t - s                       # microbatch this stage fwds
                fwd_live = (f_id >= 0) & (f_id < M)
                h_in = jnp.where(s == 0, xs[jnp.clip(f_id, 0, M - 1)],
                                 fwd_buf)
                h_out = stage_fwd(params, h_in)
                # stash the INPUT for this microbatch's backward
                slot = jnp.clip(f_id, 0, M - 1) % (2 * n)
                stash = stash.at[slot].set(
                    jnp.where(fwd_live, h_in, stash[slot]))
                # last stage records its outputs
                o_idx = jnp.clip(f_id, 0, M - 1)
                outs = outs.at[o_idx].set(
                    jnp.where(fwd_live & (s == n - 1), h_out, outs[o_idx]))

                # ---- backward phase of this tick ----
                if not fwd_only:
                    b_id = t - (2 * n - 2 - s)     # microbatch this stage bwds
                    bwd_live = (b_id >= 0) & (b_id < M)
                    g_in = jnp.where(s == n - 1,
                                     gout[jnp.clip(b_id, 0, M - 1)], bwd_buf)
                    x_saved = stash[jnp.clip(b_id, 0, M - 1) % (2 * n)]
                    _, vjp = jax.vjp(stage_fwd, params, x_saved)
                    gp, gx = vjp(g_in)
                    grads = jax.tree_util.tree_map(
                        lambda acc, g: acc + jnp.where(bwd_live, g, 0.0),
                        grads, gp)
                    bwd_buf_next = lax.ppermute(
                        jnp.where(bwd_live, gx, 0.0), axis, perm_b)
                else:
                    bwd_buf_next = bwd_buf

                # ---- hops ----
                fwd_buf = lax.ppermute(
                    jnp.where(fwd_live, h_out, jnp.zeros_like(h_out)),
                    axis, perm_f)
                return (stash, fwd_buf, bwd_buf_next, outs, grads), None

            (stash, fwd_buf, bwd_buf, outs, grads), _ = lax.scan(
                tick, (stash, fwd_buf, bwd_buf, outs, grads0),
                jnp.arange(T))
            # broadcast last stage's outputs everywhere (zero elsewhere)
            outs = jnp.where(s == n - 1, outs, jnp.zeros_like(outs))
            outs = lax.psum(outs, axis)
            return outs, jax.tree_util.tree_map(lambda g: g[None], grads)

        in_param_spec = jax.tree_util.tree_map(
            lambda _: P(self.axis), stacked_params)
        outs, grads = shard_map(
            local, mesh=self.mesh,
            in_specs=(in_param_spec, P(), P()),
            out_specs=(P(), in_param_spec),
            check_vma=False)(stacked_params, xs, gout)
        return outs, grads

    # ---- public API ----
    def forward_and_grad(self, stacked_params, h, cotangent):
        """h [B, ...] stage-0 inputs; cotangent [B, ...] = dL/d(outputs).
        Returns (outputs [B, ...], param grads like stacked_params)."""
        M = self.n_microbatches
        B = h.shape[0]
        assert B % M == 0
        mb = B // M
        xs = h.reshape(M, mb, *h.shape[1:])
        gs = cotangent.reshape(M, mb, *h.shape[1:])
        outs, grads = self._run(stacked_params, xs, gs)
        return outs.reshape(B, *h.shape[1:]), grads

    def value_and_grad(self, stacked_params, h, loss_fn):
        """loss_fn(outputs [B, ...]) -> scalar, computed (replicated) on the
        last stage's outputs; returns (loss, param grads).

        Two pipelined passes: one to get outputs (for the loss cotangent),
        one interleaved fwd/bwd pass for the grads — still O(n_stages)
        activation stash per stage.
        """
        M = self.n_microbatches
        B = h.shape[0]
        assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
        mb = B // M
        xs = h.reshape(M, mb, *h.shape[1:])
        zero_g = jnp.zeros_like(xs)
        outs, _ = self._run(stacked_params, xs, zero_g, fwd_only=True)
        outs_flat = outs.reshape(B, *h.shape[1:])
        loss, back = jax.vjp(loss_fn, outs_flat)
        (cot,) = back(jnp.ones_like(loss))
        _, grads = self.forward_and_grad(stacked_params, h, cot)
        return loss, grads
