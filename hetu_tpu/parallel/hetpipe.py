"""HetPipe: pipelined virtual workers with PS-synced weights (WSP).

Reference: python/hetu/gpu_ops/pipedream_subexecutor.py — the
``pipeline == "hetpipe"`` mode: per-weight gradient accumulation across the
wave (`grad_accum_map`, :77-87), a LOCAL optimizer update between PS syncs
(`update_gradient_local` + `run_optimizer`, :149-176), and a push of the
accumulated gradients through the parameter server every `pp_nrank`
microbatches (`need_sync`, :293-318).  Cross-worker staleness is bounded by
the PS's SSP clocks (ssp_handler.h), realizing the HetPipe paper's Wave
Synchronous Parallel.

TPU form: one *virtual worker* = one `PipeDream1F1B` pipeline over a `pp`
mesh axis (the wave's microbatch grads come back already accumulated from
the single compiled 1F1B pass).  The PS plane is the native C++ table core
— local (`PSTable`), remote (`van.RemotePSTable`), or range-partitioned
over many servers (`van.PartitionedPSTable`) — whose *server-side*
optimizer applies pushed gradients to the global weights (DDPushPull).
Between syncs the worker advances a local weight copy with plain SGD
exactly like the reference's `run_optimizer` (w -= lr * g), then discards
the lookahead when the fresh global weights arrive.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.parallel.pipedream import PipeDream1F1B


def flatten_params(tree) -> np.ndarray:
    """Pytree -> flat f32 vector (jax.flatten_util.ravel_pytree order)."""
    from jax.flatten_util import ravel_pytree
    flat, _ = ravel_pytree(tree)
    return np.asarray(flat, np.float32)


def unflatten_params(flat: np.ndarray, template):
    """Inverse of flatten_params against a same-structure template.  For
    the hot path, HetPipeWorker caches the unravel closure instead."""
    from jax.flatten_util import ravel_pytree
    _, unravel = ravel_pytree(template)
    return unravel(jnp.asarray(flat))


# the van server bounds one sparse op at 2^24 rows and a 1 GiB frame; stay
# comfortably under both for any dim
_PUBLISH_CHUNK = 1 << 20


def publish_weights(table, params) -> None:
    """Write a parameter pytree into a PS weight table (chunked: models
    larger than the van's per-request row bound need multiple sets).  Also
    the caller-driven restore path after a server recovery."""
    flat = flatten_params(params)
    n = flat.shape[0]
    for off in range(0, n, _PUBLISH_CHUNK):
        end = min(off + _PUBLISH_CHUNK, n)
        table.sparse_set(np.arange(off, end),
                         flat[off:end].reshape(end - off, 1))


class HetPipeWorker:
    """One HetPipe virtual worker: 1F1B pipeline + PS weight sync.

    Parameters
    ----------
    pipe : PipeDream1F1B
        The compiled pipeline runtime (the wave = its n_microbatches).
    params : pytree
        Initial stacked stage parameters (`pipe.stack_params(...)`).
    table
        A PS table handle with ``dense_push/dense_pull/sparse_set`` and
        ``rows == total param count, dim == 1`` — `ps.PSTable`,
        `ps.van.RemotePSTable`, or `ps.van.PartitionedPSTable`.  Its
        server-side optimizer is the GLOBAL optimizer.
    publish_init : bool
        True on exactly one worker: seeds the server table with `params`.
    sync_every : int
        Waves between PS syncs (reference `need_sync`: every pp_nrank
        microbatches == 1 wave here; >1 stretches the lookahead run).
    local_lr : float
        SGD rate for the local lookahead updates between syncs
        (reference `run_optimizer`).
    ssp : ps.SSPController, optional
        Bounded-staleness clocks across virtual workers; `worker_id`
        indexes this worker's clock.
    """

    def __init__(self, pipe: PipeDream1F1B, params, table, *,
                 publish_init: bool = False, sync_every: int = 1,
                 local_lr: float = 0.01, worker_id: int = 0,
                 ssp=None, ssp_timeout_ms: int = 60_000):
        self.pipe = pipe
        self.params = params
        self.table = table
        self.sync_every = max(1, sync_every)
        self.local_lr = local_lr
        self.worker_id = worker_id
        self.ssp = ssp
        self.ssp_timeout_ms = ssp_timeout_ms
        self.wave = 0
        self._accum = None
        from jax.flatten_util import ravel_pytree
        flat0, self._unravel = ravel_pytree(params)
        n = int(flat0.size)
        if table.rows * table.dim != n:
            raise ValueError(
                f"PS table holds {table.rows * table.dim} floats but the "
                f"model has {n} parameters")
        if publish_init:
            publish_weights(table, params)

    def pull_weights(self) -> None:
        """Replace local weights with the server's global weights."""
        flat = np.asarray(self.table.dense_pull(), np.float32).ravel()
        self.params = self._unravel(jnp.asarray(flat))

    def step(self, h, loss_fn: Callable) -> float:
        """Run one wave (M microbatches through the 1F1B pipeline) and the
        HetPipe weight protocol; returns the wave's loss."""
        loss, grads = self.pipe.value_and_grad(self.params, h, loss_fn)
        self._accum = grads if self._accum is None else \
            jax.tree_util.tree_map(jnp.add, self._accum, grads)
        self.wave += 1
        if self.wave % self.sync_every == 0:
            # DDPushPull: server optimizer applies the accumulated wave
            # grads to the global weights; the local lookahead is discarded
            flat_g = flatten_params(self._accum)
            self.table.dense_push(flat_g.reshape(self.table.rows,
                                                 self.table.dim))
            self._accum = None
            self.pull_weights()
            if self.ssp is not None:
                ok = self.ssp.clock_and_wait(self.worker_id,
                                             self.ssp_timeout_ms)
                if not ok:
                    raise RuntimeError(
                        f"HetPipe worker {self.worker_id}: staleness bound "
                        "not restored within timeout (straggler?)")
        else:
            # local lookahead between syncs (reference run_optimizer)
            self.params = jax.tree_util.tree_map(
                lambda w, g: w - self.local_lr * g, self.params, grads)
        return float(loss)


def make_weight_table(params, *, optimizer: str = "sgd", lr: float = 0.01,
                      remote: Optional[tuple] = None, **opt_kwargs):
    """Create the PS weight table for a HetPipe worker group.

    Local by default; pass ``remote=(host, port)`` for a van server, or a
    list of ``(host, port)`` endpoints for a range-partitioned multi-server
    group."""
    from hetu_tpu import ps
    n = flatten_params(params).shape[0]
    if remote is None:
        return ps.PSTable(n, 1, init="zeros", optimizer=optimizer, lr=lr,
                          **opt_kwargs)
    from hetu_tpu.ps import van
    if isinstance(remote, list):
        return van.PartitionedPSTable(remote, n, 1, init="zeros",
                                      optimizer=optimizer, lr=lr,
                                      **opt_kwargs)
    host, port = remote
    return van.RemotePSTable(host, port, n, 1, init="zeros",
                             optimizer=optimizer, lr=lr, **opt_kwargs)
