"""Fault-tolerant cross-process MPMD pipeline training.

:mod:`hetu_tpu.parallel.mpmd` runs heterogeneous per-stage programs in
separate processes, but a dead stage kills the whole run.  This module
lifts the pipeline onto the membership/barrier plane the rest of the
cross-process stack already uses (arXiv 2412.14374's MPMD
pipeline-parallelism frame over the multi-controller coordination of
:mod:`hetu_tpu.resilience.multicontroller`):

* each pipeline STAGE is its own OS process (spawned through the
  ``resilience/shardproc.py``/``launcher.py`` harness) with a row on the
  :mod:`hetu_tpu.ps.membership` join/heartbeat/lease blackboard;
* stage weights AND momentum slots live on a per-stage PS table, so
  replacing a stage moves zero parameter bytes from the controller — the
  replacement pulls them;
* activations/cotangents hop stages over :class:`~hetu_tpu.parallel.
  mpmd.VanMailbox` blob channels with quantwire ``bf16``/``int8`` codecs
  and per-edge byte counters;
* the microbatch order per step is a real GPipe or 1F1B schedule
  (:func:`~hetu_tpu.parallel.mpmd.schedule_ops`), driven by the same
  generation-counted van barriers as the multi-controller trainer.

The robustness contract (the chaos acceptance): SIGKILL of a
mid-pipeline stage → lease expiry → the controller spawns a replacement
process, freezes the survivors with a two-phase epoch (PREPARE published
BEFORE the spawn, so the replacement can never observe a runnable stale
epoch), collects frozen-progress acks, and publishes an exact
``resume_step``; the replacement pulls stage state from the PS and the
run finishes with params byte-identical to an un-killed same-seed run.

Why byte-identity holds across a kill: the step-``s`` weight update is
written as ONE atomic ``sparse_set`` frame carrying ``[w(s+1), m(s+1),
w(s), m(s), ver=s+1]`` — a version-gated double buffer.  A stage that
re-runs step ``s`` (because it, or a peer, died mid-step) pulls the
table, sees either ``ver == s`` (use the current buffer) or ``ver ==
s+1`` (its previous incarnation already applied the update; use the
PREVIOUS buffer, i.e. exactly ``w(s)``), recomputes the identical f32
math, and re-issues the byte-identical write.  In-flight microbatch
traffic is simply recomputed on fresh epoch-scoped channels —
activations are AT-LEAST-ONCE, optimizer updates EXACTLY-ONCE
(idempotent replay).  Both schedules emit backwards in ascending
microbatch order, so GPipe and 1F1B produce bitwise-equal gradients —
the schedule only moves the bubble and the activation stash.

A SLOW stage (injected ``stage_slow`` netem link, or a real congested
host) is not a membership change: its beats flow, its reported work time
grows, and the controller's straggler detector (PR 10's machinery)
opens a ``train.straggler`` span — the lockstep barriers already pace
the fleet at the slowest stage.  A SIGSTOPped stage is
suspected-then-cleared by the lease machine with zero replacements.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import time
import traceback
from collections import defaultdict
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

from hetu_tpu.parallel.mpmd import VanMailbox, schedule_ops
from hetu_tpu.ps import membership as _mb
from hetu_tpu.resilience.memberproc import (
    ControlPlaneMember, EpochChanged as _EpochChanged,
    drive_controller_harness,
)
from hetu_tpu.telemetry import trace

PIPE_BARRIER_BASE = 0x50424152         # 'PBAR'


@dataclass
class StageSpec:
    """Everything a stage process needs — JSON into the spawn config.
    The per-step batch and the stage's initial weights are REGENERATED
    from ``data_seed`` in every process (deterministic), so no training
    bytes cross the spawn boundary; only the PS tables do."""

    port: int
    stage: int
    n_stages: int
    steps: int
    n_microbatches: int
    width: int                  # feature dim D (stage weights are DxD)
    batch: int                  # global batch B; microbatch = B // M
    data_seed: int = 0
    lr: float = 0.05
    momentum: float = 0.9
    schedule: str = "1f1b"      # "gpipe" | "1f1b"
    stash_limit: int = 0        # gpipe activation-stash bound (0 = M)
    wire: str = "f32"           # activation/cotangent wire dtype
    hb_ms: int = 60
    membership_table: int = 0
    table_base: int = 0         # stage s weights table = table_base + s
    mail_base: int = 0
    barrier_base: int = PIPE_BARRIER_BASE
    barrier_wait_s: float = 0.5
    # per-op synthetic compute (the bench's bubble measurements need
    # compute to dominate the tiny matmuls) and per-step pacing so chaos
    # lands inside a run
    compute_sleep_s: float = 0.0
    step_sleep_s: float = 0.0
    # park when the CONTROLLER's blackboard beat is silent this long
    # (0 disables): a headless pipeline freezes at its next step
    # boundary and resumes on the first beat from ANY controller
    # incarnation — the member half of fenced control-plane takeover
    ctrl_lease_s: float = 0.0
    log_path: str = ""
    # replicated durable tier: a ReplicaSpec dict — non-empty means the
    # stage's blackboard + weights tables dual-write over the
    # primary+backup van pair and re-resolve on primary death
    van: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "StageSpec":
        return cls(**json.loads(s))


def step_batch(spec: StageSpec, step: int):
    """The step's global (X, Y): a pure function of (data_seed, step),
    identical in every process — stage 0 slices X per microbatch, the
    last stage slices the targets Y."""
    rng = np.random.default_rng((int(spec.data_seed), int(step)))
    X = rng.standard_normal((spec.batch, spec.width), dtype=np.float32)
    Y = (0.1 * rng.standard_normal((spec.batch, spec.width),
                                   dtype=np.float32)).astype(np.float32)
    return X, Y


def stage_init_weights(spec: StageSpec, stage: int) -> np.ndarray:
    """Stage ``stage``'s initial DxD weight — seeded, regenerable."""
    rng = np.random.default_rng((int(spec.data_seed), 1000 + int(stage)))
    return (0.4 * rng.standard_normal((spec.width, spec.width),
                                      dtype=np.float32)).astype(np.float32)


def stage_table_rows(width: int) -> int:
    """Stage-table layout: ``[w_cur (D) | m_cur (D) | w_prev (D) |
    m_prev (D) | version row]`` — 4*D+1 rows of D f32s.  The version row
    (element 0) holds ``last_applied_step + 1``; writing all rows in ONE
    ``sparse_set`` frame makes the update atomic on the van server."""
    return 4 * int(width) + 1


# ---------------------------------------------------------------------------
# stage worker process
# ---------------------------------------------------------------------------

class PipelineStageProcess(ControlPlaneMember):
    """One pipeline stage: pure-numpy ``y = tanh(x @ w)`` with a manual
    vjp (numpy, not jax — bitwise determinism across processes is the
    byte-identity contract, and the data plane here is the van).  The
    member control plane (beats, slow-link honoring, epoch barriers) is
    the shared :class:`~hetu_tpu.resilience.memberproc.
    ControlPlaneMember`; this class owns the microbatch schedule, the
    mailboxes, and the PS-resident stage state."""

    def __init__(self, spec: StageSpec):
        from hetu_tpu.ps import van
        self.spec = spec
        s = spec.stage
        D = spec.width
        if spec.batch % spec.n_microbatches:
            raise ValueError(f"batch {spec.batch} must divide into "
                             f"{spec.n_microbatches} microbatches")
        self.mb_size = spec.batch // spec.n_microbatches
        self._cap = self.mb_size * D
        from hetu_tpu.ps.replica import open_table
        self.member = _mb.MembershipClient(
            "127.0.0.1", spec.port, table_id=spec.membership_table,
            slot=s, n_slots=spec.n_stages, replica=spec.van or None)
        self.table = open_table(
            spec.van, "127.0.0.1", spec.port, stage_table_rows(D), D,
            table_id=spec.table_base + s, create=False)
        self._init_control_plane(van=van, netem_local=f"stage{s}",
                                 my_slot=s)
        self._mail: dict = {}
        self._seq: dict = {}
        self._mail_epoch = -1
        # run-cumulative edge bytes: epoch changes discard mailboxes,
        # so their counters are folded in here before the close
        self._wire_totals = {"logical": 0, "wire": 0}
        self._log = open(spec.log_path or f"stage_{s}.jsonl", "a")
        self.member.join(committed=-1.0)
        self._start_beat()

    # ---- epoch-scoped mailboxes ----
    def _chan(self, edge: int, backward: bool) -> VanMailbox:
        gen_key = (self._van_gen(), self.epoch)
        if self._mail_epoch != gen_key:
            for mbx in self._mail.values():
                self._wire_totals["logical"] += mbx.bytes_logical
                self._wire_totals["wire"] += mbx.bytes_wire
                try:
                    mbx.close()
                except Exception:
                    pass
            self._mail.clear()
            self._seq.clear()
            self._mail_epoch = gen_key
        key = (edge, backward)
        if key not in self._mail:
            # channel ids are EPOCH-scoped: a membership change abandons
            # every in-flight message (at-least-once activations) and
            # both endpoints restart seq-aligned on fresh channels.  A
            # van promotion does the same — the promoted van has no
            # channel state, so both endpoints of every edge discard
            # their mailboxes (the (van_gen, epoch) key above) and
            # restart seq-aligned against the new primary.
            cid = (self.spec.mail_base + (self.epoch << 8) + edge * 2 +
                   (1 if backward else 0))
            host, port = self._van_endpoint()
            self._mail[key] = VanMailbox(
                host, port, cid, self._cap,
                wire=self.spec.wire,
                metric_path=f"mpmd.edge{edge}."
                            f"{'bwd' if backward else 'fwd'}")
            self._seq[key] = 0
        return self._mail[key]

    def _mail_put(self, edge: int, backward: bool, arr) -> None:
        ch = self._chan(edge, backward)
        self._seq[(edge, backward)] += 1
        seq = self._seq[(edge, backward)]
        faults = 0
        while True:
            try:
                ch.put(arr, seq, timeout_s=self.spec.barrier_wait_s)
                return
            except TimeoutError:
                self._check_epoch()  # blob put is same-seq idempotent
            except (ConnectionError, RuntimeError) as e:
                faults += 1
                self._wire_fault(e, faults=faults)

    def _mail_get(self, edge: int, backward: bool, shape) -> np.ndarray:
        ch = self._chan(edge, backward)
        self._seq[(edge, backward)] += 1
        seq = self._seq[(edge, backward)]
        faults = 0
        while True:
            try:
                return ch.get(shape, seq,
                              timeout_s=self.spec.barrier_wait_s)
            except TimeoutError:
                self._check_epoch()
            except (ConnectionError, RuntimeError) as e:
                faults += 1
                self._wire_fault(e, faults=faults)

    # ---- PS-resident stage state (version-gated double buffer) ----
    def _pull_state(self, step: int):
        # hot path pulls the CURRENT buffer + version row only; the
        # prev buffer is fetched in the rare replay branch (this stage
        # is its table's sole writer, so the second pull is consistent)
        D = self.spec.width
        rows = self.table.sparse_pull(
            np.concatenate([np.arange(2 * D), [4 * D]]))
        ver = int(rows[2 * D, 0])
        if ver == step:
            return rows[0:D].copy(), rows[D:2 * D].copy()
        if ver == step + 1:
            # this step's update already applied (a previous incarnation
            # died between its write and the commit barrier): replay the
            # step from the PREVIOUS buffer — the recompute is bitwise
            # identical and the re-write idempotent
            prev = self.table.sparse_pull(np.arange(2 * D, 4 * D))
            return prev[0:D].copy(), prev[D:2 * D].copy()
        raise RuntimeError(
            f"stage {self.spec.stage}: table version {ver} incompatible "
            f"with step {step} (expected {step} or {step + 1})")

    def _write_state(self, step: int, w, mom, new_w, new_m) -> None:
        D = self.spec.width
        ver_row = np.zeros((1, D), np.float32)
        ver_row[0, 0] = float(step + 1)
        payload = np.concatenate(
            [new_w, new_m, w, mom, ver_row], axis=0).astype(np.float32)
        # ONE sparse_set frame: the van applies it atomically, so a kill
        # can never leave weights and version out of sync
        self.table.sparse_set(np.arange(stage_table_rows(D)), payload)

    # ---- one pipeline step ----
    def _run_step(self, step: int) -> dict:
        spec = self.spec
        s, S, M, D = spec.stage, spec.n_stages, spec.n_microbatches, \
            spec.width
        mbsz = self.mb_size
        first, last = s == 0, s == S - 1
        t0 = time.perf_counter()
        w, mom = self._pull_state(step)
        pull_s = time.perf_counter() - t0
        X = Y = None
        if first or last:
            X, Y = step_batch(spec, step)
        stash: dict = {}
        gy_stash: dict = {}
        loss_sum = 0.0
        gsum = np.zeros((D, D), np.float32)
        busy_s = 0.0
        peak = 0
        ops = schedule_ops(spec.schedule, stage=s, n_stages=S,
                           n_microbatches=M,
                           stash_limit=spec.stash_limit)
        for op, m in ops:
            if op == "F":
                if first:
                    x = X[m * mbsz:(m + 1) * mbsz]
                else:
                    x = self._mail_get(s - 1, False, (mbsz, D))
                tc = time.perf_counter()
                y = np.tanh(x @ w)
                if spec.compute_sleep_s > 0:
                    time.sleep(spec.compute_sleep_s)
                busy_s += time.perf_counter() - tc
                stash[m] = (x, y)
                peak = max(peak, len(stash))
                if last:
                    t = Y[m * mbsz:(m + 1) * mbsz]
                    loss_sum += float(np.mean((y - t) ** 2))
                    gy_stash[m] = ((2.0 / y.size) * (y - t)).astype(
                        np.float32)
                else:
                    self._mail_put(s, False, y)
            else:
                if last:
                    gy = gy_stash.pop(m)
                else:
                    gy = self._mail_get(s, True, (mbsz, D))
                x, y = stash.pop(m)
                tc = time.perf_counter()
                gz = (gy * (1.0 - y * y)).astype(np.float32)
                gw = x.T @ gz
                if not first:
                    gx = gz @ w.T
                if spec.compute_sleep_s > 0:
                    time.sleep(spec.compute_sleep_s)
                busy_s += time.perf_counter() - tc
                if not first:
                    self._mail_put(s - 1, True, gx)
                # backwards run in ascending microbatch order under BOTH
                # schedules, so this accumulation is schedule-invariant
                gsum += gw
        grad = gsum / np.float32(M)
        new_m = np.float32(spec.momentum) * mom + grad
        new_w = w - np.float32(spec.lr) * new_m
        tw = time.perf_counter()
        self._write_state(step, w, mom, new_w, new_m)
        write_s = time.perf_counter() - tw
        return {"loss": loss_sum / M if last else None,
                "busy_s": busy_s, "pull_s": pull_s, "write_s": write_s,
                "peak_stash": peak}

    # ---- main loop ----
    def run(self) -> None:
        spec = self.spec
        step = 0
        while not self._stop.is_set():
            e, width, mask, resume, phase, slow_slot, slow_ms = \
                self.member.read_control()
            self._apply_slow(slow_slot, slow_ms)
            if self._park_if_headless():
                continue  # controller silent: frozen at this boundary
                # until a (possibly new-incarnation) controller beats
            if e == 0:
                if self._stop.wait(0.05):
                    break
                continue
            if phase != 0:
                # PREPARE: freeze at this step boundary, ack with the
                # frozen committed step (the controller computes the
                # exact resume from these rows)
                if self.acked < e:
                    self.acked = e
                    try:
                        self._sync_row()
                    except Exception:
                        pass  # the beat thread resends the ack in hb_ms
                if self._stop.wait(0.02):
                    break
                continue
            if self._hold_for_republish(e, phase):
                # a van promotion voided the in-flight step: wait for
                # the controller's re-freeze before re-running it
                if self._stop.wait(0.02):
                    break
                continue
            if e != self.epoch:
                self.epoch = e
                self.acked = max(self.acked, e)
                step = resume
            if spec.stage not in _mb.MembershipService.slots_of(mask):
                if self._stop.wait(0.05):
                    break
                continue
            if step >= spec.steps:
                break
            bar_sync, bar_commit = self._epoch_barriers(spec.n_stages)
            try:
                t0 = time.perf_counter()
                self._await_barrier(bar_sync)
                t1 = time.perf_counter()
                rep = self._run_step(step)
                t2 = time.perf_counter()
                self._await_barrier(bar_commit)
                t3 = time.perf_counter()
            except _EpochChanged:
                continue  # step void; re-runs after the new epoch
            except Exception as e:
                # a table op mid-step hit the durable-tier failover
                # (VanFailover after the dance, or a raw wire error the
                # dance can absorb): void the step exactly like an
                # epoch change.  The re-run replays from the version-
                # gated double buffer — a half-applied step recomputes
                # bitwise identical and re-writes idempotently, so van
                # chaos preserves this plane's byte-identity contract.
                try:
                    self._wire_fault(e)
                except _EpochChanged:
                    pass
                continue
            self._work_ms = (rep["pull_s"] + rep["busy_s"] +
                             rep["write_s"]) * 1e3
            self.committed = step
            try:
                self._sync_row()
            except Exception:
                pass  # the beat thread re-writes it within hb_ms
            wire = {"logical": self._wire_totals["logical"] +
                    sum(m.bytes_logical for m in self._mail.values()),
                    "wire": self._wire_totals["wire"] +
                    sum(m.bytes_wire for m in self._mail.values())}
            self._log.write(json.dumps(
                {"step": step, "epoch": self.epoch, "stage": spec.stage,
                 "loss": rep["loss"], "peak_stash": rep["peak_stash"],
                 "busy_ms": round(rep["busy_s"] * 1e3, 3),
                 "wall_ms": round((t2 - t1) * 1e3, 3),
                 "wire_bytes": wire,
                 "ms": {"bar_sync": round((t1 - t0) * 1e3, 3),
                        "pull": round(rep["pull_s"] * 1e3, 3),
                        "write": round(rep["write_s"] * 1e3, 3),
                        "bar_commit": round((t3 - t2) * 1e3, 3)}}) + "\n")
            self._log.flush()
            step += 1
            if spec.step_sleep_s > 0:
                self._stop.wait(spec.step_sleep_s)
        self.close()

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._sync_row()
            self.member.leave()
        except Exception:
            pass
        for mbx in self._mail.values():
            try:
                mbx.close()
            except Exception:
                pass
        self._log.close()
        self.table.close()
        self._close_control_plane()


def stage_main(config_path: str) -> int:
    spec = StageSpec.from_json(open(config_path).read())
    # crash-durable span stream in the run workdir: the pipeline
    # stage's flight recorder (a SIGKILLed stage keeps its evidence)
    trace.open_process_stream(Path(config_path).resolve().parent,
                              f"stage_s{spec.stage}_p{os.getpid()}")
    worker = PipelineStageProcess(spec)
    print("READY", spec.stage, flush=True)
    worker.run()
    return 0


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class MPMDPipelineSupervisor:
    """Membership authority over S pipeline-stage PROCESSES.

    Owns the van, the per-stage weight tables (where the model lives —
    what makes a stage process stateless-but-for-activations), the
    blackboard, and the lease machine.  A ``lost`` stage is answered by
    a ``pipeline.stage_replace`` span: PREPARE-freeze the survivors,
    spawn a replacement, wait for its join + everyone's frozen-progress
    acks, publish the exact resume.  ``procs`` holds the live ``Popen``
    handles the ``stage_kill`` chaos fault targets.
    """

    def __init__(self, n_stages: int, *, workdir, steps: int,
                 n_microbatches: int = 4, width: int = 8,
                 batch: int = 8, schedule: str = "1f1b",
                 stash_limit: int = 0, wire: str = "f32",
                 data_seed: int = 0, lr: float = 0.05,
                 momentum: float = 0.9, hb_ms: int = 60,
                 lease_s: float = 0.6, suspect_grace_s: float = 0.4,
                 deaf_ack_s: Optional[float] = None,
                 compute_sleep_s: float = 0.0, step_sleep_s: float = 0.0,
                 ctrl_lease_s: float = 0.0,
                 injector=None, spawn_timeout_s: float = 120.0,
                 straggler_factor: float = 4.0,
                 straggler_slow_ms: int = 120, port: int = 0,
                 own_van: bool = True,
                 van_spec: Optional[dict] = None,
                 _takeover_spec: Optional[StageSpec] = None):
        from hetu_tpu.ps import van
        if n_stages < 2:
            raise ValueError("a pipeline needs at least two stages")
        if batch % n_microbatches:
            raise ValueError(f"batch {batch} must divide into "
                             f"{n_microbatches} microbatches")
        self._van = van
        self._own_van = bool(own_van)
        if not van_spec and _takeover_spec is not None:
            van_spec = getattr(_takeover_spec, "van", None) or None
        # replicated durable tier: stage weights + blackboard dual-write
        # over a primary+backup van pair (see ps/replica.py); the model
        # then survives the van process itself
        self._replica = None
        self._van_spec = dict(van_spec) if van_spec else {}
        if self._van_spec:
            if own_van:
                raise ValueError(
                    "a replicated durable tier is external by "
                    "definition: pass own_van=False with van_spec")
            from hetu_tpu.ps.replica import VanReplica
            self._replica = VanReplica.from_spec(
                self._van_spec, bootstrap=_takeover_spec is None)
            if _takeover_spec is not None:
                self._replica.refresh()  # unconditional: a stale
                # cached view must not adopt the dead primary
            port = self._replica.primary[1]
            # a van promotion re-freezes from poll(): stages converge on
            # the re-keyed barriers/mailboxes themselves, but the fresh
            # epoch gives any still-parked stage a control-row edge and
            # records the event
            self._van_failover_pending = False
            self._replica.register(
                lambda _rep: setattr(self, "_van_failover_pending",
                                     True))
        if own_van:
            self.port = van.serve(port)
        else:
            # attach to an EXTERNAL van process: the durable tier
            # (stage tables, blackboard) must outlive the controller
            # for its death to be survivable
            if not port:
                raise ValueError("own_van=False needs the running "
                                 "van's port")
            self.port = int(port)
        self.workdir = Path(workdir)
        self.steps = int(steps)
        self.n_stages = int(n_stages)
        self.injector = injector
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._incarnations = 0
        self.epoch = 0
        self.resume_step = 0
        self.replacements: list = []
        self.counters = defaultdict(int)
        self.log_paths: list = []
        self._fired_through = 0
        self._committed_hw = -1
        self.straggler_factor = float(straggler_factor)
        self.straggler_slow_ms = int(straggler_slow_ms)
        D = int(width)
        self.tables: list = []
        self.procs: list = [None] * self.n_stages
        self._member_pids: dict = {}    # takeover-adopted pids (no Popen)
        from hetu_tpu.resilience.straggler import SupervisorStragglerPlane
        if _takeover_spec is not None:
            # ---- takeover: adopt a running pipeline whose controller
            # died.  Everything re-derives from the van: the control
            # row (epoch/resume/phase), lease rows (alive stages,
            # frozen committed), stage tables (the model), and spawn
            # configs on disk (every id).
            self.spec = StageSpec(**{**asdict(_takeover_spec),
                                     "stage": -1, "log_path": ""})
            # the whole attach sequence is guarded: a blackboard/claim
            # failure after some tables connected must close them, not
            # leak van connections for the process's life
            try:
                from hetu_tpu.ps.replica import open_table
                for s in range(self.n_stages):
                    self.tables.append(open_table(
                        self._replica, "127.0.0.1", self.port,
                        stage_table_rows(D), D,
                        table_id=self.spec.table_base + s, create=False))
                self._bb = _mb.attach_blackboard(
                    "127.0.0.1", self.port,
                    table_id=self.spec.membership_table,
                    n_slots=self.n_stages, replica=self._replica)
                self.svc = _mb.MembershipService(
                    self._bb, self.n_stages, lease_s=lease_s,
                    suspect_grace_s=suspect_grace_s,
                    deaf_ack_s=deaf_ack_s)
                self._stragglers = SupervisorStragglerPlane(
                    self.svc, factor=self.straggler_factor,
                    subject="stage", policy="wait",
                    slow_ms=self.straggler_slow_ms)
                self.log_paths = sorted(
                    str(p) for p in self.workdir.glob("stage_*_*.jsonl")
                    # the stages' telemetry span streams live in the
                    # same workdir and match the stem — they are NOT
                    # recompute/commit logs
                    if not p.name.endswith(".trace.jsonl"))
                self._incarnations = len(
                    list(self.workdir.glob("stage_*_*.json")))
                self._adopt()
            except Exception:
                self.close()
                raise
            return
        # ---- normal bring-up ----
        membership_table = _mb.fresh_table_id()
        table_base = _mb.fresh_table_id()
        mail_base = _mb.fresh_table_id()
        barrier_base = PIPE_BARRIER_BASE + (_mb.fresh_table_id() << 8)
        self.spec = StageSpec(
            port=self.port, stage=-1, n_stages=self.n_stages,
            steps=self.steps, n_microbatches=int(n_microbatches),
            width=int(width), batch=int(batch), data_seed=int(data_seed),
            lr=float(lr), momentum=float(momentum),
            schedule=str(schedule), stash_limit=int(stash_limit),
            wire=str(wire), hb_ms=int(hb_ms),
            membership_table=membership_table, table_base=table_base,
            mail_base=mail_base, barrier_base=barrier_base,
            compute_sleep_s=float(compute_sleep_s),
            step_sleep_s=float(step_sleep_s),
            ctrl_lease_s=float(ctrl_lease_s), van=self._van_spec)
        # everything after van.serve is guarded: a table/blackboard/
        # spawn failure must stop the in-process van server (and close
        # what was created) instead of leaking it for the process's life
        try:
            from hetu_tpu.ps.replica import open_table
            # per-stage weight tables, seeded — the model lives HERE
            for s in range(self.n_stages):
                t = open_table(
                    self._replica, "127.0.0.1", self.port,
                    stage_table_rows(D), D,
                    table_id=table_base + s, create=True, init="zeros",
                    optimizer="sgd", lr=0.0)
                self.tables.append(t)
                w0 = stage_init_weights(self.spec, s)
                zeros = np.zeros_like(w0)
                ver = np.zeros((1, D), np.float32)
                t.sparse_set(np.arange(stage_table_rows(D)),
                             np.concatenate([w0, zeros, w0, zeros,
                                             ver]))
            self._bb = _mb.create_blackboard(
                "127.0.0.1", self.port, table_id=membership_table,
                n_slots=self.n_stages, replica=self._replica)
            self.svc = _mb.MembershipService(
                self._bb, self.n_stages, lease_s=lease_s,
                suspect_grace_s=suspect_grace_s, deaf_ack_s=deaf_ack_s)
            self._stragglers = SupervisorStragglerPlane(
                self.svc, factor=self.straggler_factor, subject="stage",
                policy="wait", slow_ms=self.straggler_slow_ms)
            for s in range(self.n_stages):
                self._spawn(s)
            self._wait_joined(range(self.n_stages))
            # epoch numbering starts at 1: a zeroed control row must
            # not read as a published membership
            self.epoch = 1
            self.svc.publish_control(
                epoch=1, width=self.n_stages,
                alive_mask=_mb.MembershipService.mask_of(
                    range(self.n_stages)),
                resume_step=0)
        except Exception:
            self.close()
            raise

    @classmethod
    def takeover(cls, *, workdir, port, lease_s: float = 0.6,
                 suspect_grace_s: float = 0.4,
                 deaf_ack_s: Optional[float] = None,
                 spawn_timeout_s: float = 120.0,
                 injector=None, **kw) -> "MPMDPipelineSupervisor":
        """Become the pipeline's NEW controller after the old one died:
        re-derive everything from the stage spawn configs under
        ``workdir`` and the still-running van at ``port``, claim the
        controller row with a higher incarnation, and re-freeze the
        fleet (PREPARE → frozen acks → exact resume) under a
        ``ctrl.takeover`` span."""
        cfgs = sorted(Path(workdir).glob("stage_*_*.json"),
                      key=lambda p: p.stat().st_mtime)
        if not cfgs:
            raise FileNotFoundError(
                f"no stage spawn configs under {workdir}")
        spec = StageSpec.from_json(cfgs[-1].read_text())
        return cls(spec.n_stages, workdir=workdir, steps=spec.steps,
                   n_microbatches=spec.n_microbatches, width=spec.width,
                   batch=spec.batch, schedule=spec.schedule,
                   stash_limit=spec.stash_limit, wire=spec.wire,
                   data_seed=spec.data_seed, lr=spec.lr,
                   momentum=spec.momentum, hb_ms=spec.hb_ms,
                   lease_s=lease_s, suspect_grace_s=suspect_grace_s,
                   deaf_ack_s=deaf_ack_s,
                   compute_sleep_s=spec.compute_sleep_s,
                   step_sleep_s=spec.step_sleep_s,
                   ctrl_lease_s=spec.ctrl_lease_s, injector=injector,
                   spawn_timeout_s=spawn_timeout_s, port=port,
                   own_van=False, _takeover_spec=spec, **kw)

    def _adopt(self) -> None:
        """Adopt the pipeline: the control row carries the epoch (and a
        possibly half-open PREPARE the old controller died inside), the
        lease rows carry frozen progress — a fresh two-phase re-freeze
        supersedes whatever was in flight and resumes at the exact
        step."""
        ctrl = self.svc.read_control_row()
        self.epoch = int(ctrl["epoch"])
        self.resume_step = int(ctrl["resume_step"])
        # carry the predecessor's straggler injection forward: the
        # takeover republish must not silently heal an injected slow
        # link (the same rule every epoch transition honors)
        self.svc.adopt_slow(ctrl["slow_slot"], ctrl["slow_ms"])
        self.svc.wait_present(self._spawn_timeout_s)
        # stage pids off the lease rows: these processes are the DEAD
        # controller's children — the pid is the only handle
        # close()/_replace_stages have on them
        self._member_pids.update(self.svc.member_pids())
        self._committed_hw = max(
            self._committed_hw,
            max((self.svc.state_of(s).committed
                 for s in range(self.n_stages)), default=-1))
        with trace.span("ctrl.takeover", cat="ctrl") as sp:
            sp.set("plane", "mpmd")
            sp.set("incarnation", self.svc.ctrl_incarnation)
            sp.set("epoch_adopted", self.epoch)
            sp.set("phase_at_death", int(ctrl["phase"]))
            # refreeze whenever ANY stage is present — even a finished
            # fleet: a stage parked under a mid-takeover hold only
            # resumes (and exits) once the new incarnation republishes
            if self.svc.present_slots():
                self._refreeze()
            sp.set("epoch", self.epoch)
            sp.set("resume_step", self.resume_step)
        # a stage that died AROUND the controller kill: its one-shot
        # "lost" event was consumed by the nested polls above
        # (wait_present, the refreeze ack-wait) and will never re-fire
        # for the run loop — the same consumed-event case
        # _replace_stages re-checks by STATE; without this sweep the
        # pipeline runs a stage short until the deadline
        stranded = [s for s in range(self.n_stages)
                    if self.svc.state_of(s).state == "lost"]
        if stranded and self._committed_hw < self.steps - 1:
            self._replace_stages(stranded)
        self.takeover_report = {
            "incarnation": self.svc.ctrl_incarnation,
            "epoch": self.epoch, "resume_step": self.resume_step,
            "present": sorted(self.svc.present_slots()),
        }

    def _refreeze(self) -> None:
        """The takeover republish: a FRESH epoch's PREPARE supersedes
        any half-open transition the dead controller left behind,
        frozen acks are collected from every live stage, and the exact
        resume is published — the same two-phase contract as a stage
        replacement, minus the spawn."""
        full_mask = _mb.MembershipService.mask_of(range(self.n_stages))
        self.epoch += 1
        self.svc.publish_control(epoch=self.epoch, width=self.n_stages,
                                 alive_mask=full_mask, phase=1)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            self.svc.poll()
            if all(self.svc.state_of(s).epoch_ack >= self.epoch
                   for s in range(self.n_stages)
                   if self.svc.state_of(s).state not in
                   ("left", "lost", "empty")):
                break
            time.sleep(0.02)
        else:
            raise TimeoutError(
                f"takeover epoch {self.epoch} prepare not acked within "
                f"30s: "
                f"{[(m.slot, m.state, m.epoch_ack) for m in self.svc.members]}")
        frozen = [m.committed for m in self.svc.members
                  if m.state != "empty"]
        self.resume_step = max(max(frozen), self._committed_hw) + 1
        self.svc.publish_control(epoch=self.epoch, width=self.n_stages,
                                 alive_mask=full_mask,
                                 resume_step=self.resume_step)

    # ---- spawning ----
    def _spawn(self, stage: int) -> None:
        from hetu_tpu.resilience.shardproc import spawn_module
        self._incarnations += 1
        tag = f"stage_{stage}_{self._incarnations}"
        if self._replica is not None:
            # spawn configs carry the CURRENT pair membership: after a
            # failover + re-silver the original endpoints may both be
            # dead, and a fresh process has no other rendezvous
            self.spec = StageSpec(**{**asdict(self.spec),
                                     "van": self._replica.current_spec()})
        spec = StageSpec(**{**asdict(self.spec), "stage": int(stage),
                            "log_path": str(self.workdir /
                                            f"{tag}.jsonl")})
        cfg = self.workdir / f"{tag}.json"
        cfg.write_text(spec.to_json())
        self.log_paths.append(spec.log_path)
        self.procs[stage] = spawn_module(
            self.workdir, tag, "hetu_tpu.parallel.mpmd_elastic",
            [str(cfg)], extra_env={"JAX_PLATFORMS": "cpu"},
            timeout_s=self._spawn_timeout_s)

    def _wait_joined(self, slots, timeout_s: Optional[float] = None):
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self._spawn_timeout_s)
        want = set(int(s) for s in slots)
        while time.monotonic() < deadline:
            self.svc.poll()
            if want <= set(self.svc.present_slots()):
                return
            time.sleep(0.05)
        raise TimeoutError(f"stages {sorted(want)} did not join in time")

    # ---- stage replacement (the tentpole recovery path) ----
    def _replace_stages(self, slots) -> None:
        t0 = time.perf_counter()
        with trace.span("pipeline.stage_replace") as sp:
            sp.set("stage", int(sorted(slots)[0]))
            sp.set("stages", sorted(int(s) for s in slots))
            pending = {int(s) for s in slots}
            full_mask = _mb.MembershipService.mask_of(
                range(self.n_stages))
            while True:
                # PREPARE first, spawn second: survivors freeze before
                # the replacement's first control read, so it can never
                # adopt a runnable stale epoch (and run from step 0
                # against a mid-run table)
                self.epoch += 1
                self.svc.publish_control(
                    epoch=self.epoch, width=self.n_stages,
                    alive_mask=full_mask, phase=1)
                for sl in sorted(pending):
                    p = self.procs[sl]
                    if p is not None and p.poll() is None:
                        p.kill()
                        p.wait()
                    elif sl in self._member_pids:
                        # a takeover-adopted stage (the dead
                        # controller's child): the pid is the only
                        # handle — without the kill a SIGSTOPped-then-
                        # resumed old stage and its replacement both
                        # heartbeat the same slot
                        try:
                            os.kill(self._member_pids[sl],
                                    _signal.SIGKILL)
                        except OSError:
                            pass
                    self._member_pids.pop(sl, None)
                    self._spawn(sl)
                self._wait_joined(pending)
                pending.clear()
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    for k, sl in self.svc.poll():
                        if k == "lost":
                            pending.add(int(sl))  # a second death
                    # a loss whose event was consumed by a nested poll
                    # (e.g. inside _wait_joined) still shows as state
                    # "lost" — it would never ack, so re-prepare
                    pending |= {s for s in range(self.n_stages)
                                if self.svc.state_of(s).state == "lost"}
                    if pending:
                        break
                    # a stage that finished-and-LEFT will never ack a
                    # later epoch; only live membership gates the
                    # publish (its frozen committed still counts below)
                    if all(self.svc.state_of(s).epoch_ack >= self.epoch
                           for s in range(self.n_stages)
                           if self.svc.state_of(s).state != "left"):
                        break
                    time.sleep(0.02)
                else:
                    raise TimeoutError(
                        f"epoch {self.epoch} prepare not acked by all "
                        f"stages within 30s")
                if pending:
                    continue  # re-prepare around the newest death
                # every row is frozen: survivors carry the committed
                # step (barrier-atomic, so they agree), the replacement
                # -1 — the high-water mark guards the all-dead corner
                frozen = [m.committed for m in self.svc.members
                          if m.state != "empty"]
                self.resume_step = max(max(frozen), self._committed_hw) \
                    + 1
                self.svc.publish_control(
                    epoch=self.epoch, width=self.n_stages,
                    alive_mask=full_mask,
                    resume_step=self.resume_step)
                rec = {"stages": sorted(int(s) for s in slots),
                       "epoch": self.epoch,
                       "resume_step": self.resume_step,
                       "downtime_s": round(
                           time.perf_counter() - t0, 3)}
                self.replacements.append(rec)
                sp.set("epoch", self.epoch)
                sp.set("resume_step", self.resume_step)
                return

    # ---- straggler plane (PR 10's detector, wait policy: a pipeline
    # stage is not redundant, so eviction is not an option — the
    # lockstep barriers already pace the fleet) ----
    def inject_stage_slow(self, slot: int, duration_s: float,
                          slow_ms: Optional[int] = None) -> None:
        self._stragglers.inject(slot, duration_s, slow_ms)

    @property
    def straggle_records(self) -> list:
        return self._stragglers.records

    def _check_stragglers(self) -> None:
        slots = [s for s in self.svc.present_slots()
                 if self.svc.state_of(s).state == "alive"]
        # wait policy only (evict_after=0): the shared plane opens/
        # closes the train.straggler spans; a pipeline has no redundant
        # member to reshard around, so crossing never evicts
        self._stragglers.observe(slots)

    # ---- driving ----
    def poll(self) -> list:
        """One membership sweep: drives the injector by observed
        committed step, answers losses with stage replacement, applies
        stage_slow injections, and runs the straggler detector."""
        if self.injector is not None:
            cur = max((self.svc.state_of(s).committed
                       for s in range(self.n_stages)), default=-1)
            for t in range(self._fired_through + 1, cur + 1):
                self.injector.on_step(t)
            self._fired_through = max(self._fired_through, cur)
            for _, idx, dur in self.injector.pop_net_events(
                    kinds=("stage_slow",)):
                self.inject_stage_slow(int(idx) % self.n_stages, dur)
        # serialized with every other control-row write (the shared
        # SupervisorStragglerPlane's heal-in-poll rule)
        self._stragglers.maybe_heal()
        if self._replica is not None and self._van_failover_pending:
            self._van_failover_pending = False
            self.counters["van_failover"] += 1
            with trace.span("pipeline.van_failover") as sp:
                sp.set("van_incarnation", self._replica.incarnation)
                if self.svc.present_slots() and \
                        self._committed_hw < self.steps - 1:
                    self._refreeze()
                sp.set("epoch", self.epoch)
        events = self.svc.poll()
        self._committed_hw = max(
            self._committed_hw,
            max((self.svc.state_of(s).committed
                 for s in range(self.n_stages)), default=-1))
        for kind, slot in events:
            self.counters[kind] += 1
        lost = [int(slot) for kind, slot in events if kind == "lost"]
        if lost:
            if self._committed_hw >= self.steps - 1:
                # commits are barrier-atomic, so ANY stage at steps-1
                # means the WHOLE run committed its final step: a stage
                # dying between that commit and its leave() needs no
                # replacement (one would adopt resume==steps, do
                # nothing, and leave with committed=-1 — unfinishable)
                self.counters["lost_after_finish"] += len(lost)
            else:
                # one replace epoch covers every loss in the batch: a
                # per-slot replace would park the first epoch's ack
                # wait on a stage known dead
                self._replace_stages(lost)
        self._check_stragglers()
        return events

    def run(self, *, deadline_s: float = 300.0,
            poll_s: float = 0.05) -> dict:
        """Poll until every stage committed the final step (or left
        after doing so).  Returns a report dict with the final per-stage
        params (pulled from the PS tables — the byte-identity
        evidence)."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            self.poll()
            states = [self.svc.state_of(s)
                      for s in range(self.n_stages)]
            present = [m for m in states
                       if m.state in ("alive", "suspect")]
            if present and all(m.committed >= self.steps - 1
                               for m in present):
                break
            # nobody live: done iff the final step COMMITTED fleet-wide
            # (barrier-atomic, so the high-water mark is the fleet's) —
            # covers both an all-left finish and a stage lost between
            # its final commit and its leave()
            if not present and self._committed_hw >= self.steps - 1:
                break
            time.sleep(poll_s)
        else:
            raise TimeoutError(
                f"pipeline did not finish {self.steps} steps within "
                f"{deadline_s}s: "
                f"{[(m.slot, m.state, m.committed) for m in states]}")
        self._stragglers.close_all(resolution="run_end")
        return {
            "steps": self.steps,
            "epochs": self.epoch,
            "replacements": list(self.replacements),
            "counters": dict(self.counters),
            "straggle_records": list(self.straggle_records),
            "final_params": self.final_params(),
            "log_paths": list(self.log_paths),
        }

    def final_params(self) -> dict:
        """``{stage: w}`` from each stage table's CURRENT buffer.
        Meaningful after :meth:`run` returned; mid-run it reads
        whatever step the fleet is on."""
        D = self.spec.width
        out = {}
        for s, t in enumerate(self.tables):
            rows = t.sparse_pull(np.arange(stage_table_rows(D)))
            out[s] = rows[0:D].copy()
        return out

    def close(self) -> None:
        # a FENCED controller no longer owns the fleet: its close()
        # must not kill stage processes the new incarnation adopted
        # (the same rule as the serving pool's fenced close)
        svc = getattr(self, "svc", None)
        fenced = bool(getattr(svc, "fenced", False))
        for p in self.procs if not fenced else ():
            if p is None:
                continue
            try:
                if p.poll() is None:
                    p.kill()
                p.wait()
            except Exception:
                traceback.print_exc()
        # takeover-adopted stages have no Popen handle — the pid off
        # the lease row is the only one.  Only still-present slots are
        # signalled (a finished fleet left cleanly; killing a recycled
        # pid would hit an innocent process), and they were reparented
        # when their spawner died, so there is no zombie-reap concern
        for slot, pid in (() if fenced else
                          list(getattr(self, "_member_pids",
                                       {}).items())):
            if svc is not None and \
                    svc.state_of(slot).state not in ("alive", "suspect"):
                continue
            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                pass
        for t in (*getattr(self, "tables", ()),
                  getattr(self, "_bb", None)):
            if t is not None:
                try:
                    t.close()
                except Exception:
                    pass
        if getattr(self, "_own_van", True):
            self._van.stop()


# ---------------------------------------------------------------------------
# controller process harness (the chaos kill target)
# ---------------------------------------------------------------------------

def controller_main(config_path: str) -> int:
    """Entry point for a spawned CONTROLLER process over an EXTERNAL
    van: drive the pipeline and print the progress markers the chaos
    harness keys on (``STEP k`` per committed-high-water advance,
    ``ALLDONE``, ``FENCED``)."""
    cfg = json.loads(open(config_path).read())
    trace.open_process_stream(cfg["workdir"],
                              f"controller_p{os.getpid()}")
    sup = MPMDPipelineSupervisor(
        int(cfg["n_stages"]), workdir=cfg["workdir"],
        steps=int(cfg["steps"]),
        n_microbatches=int(cfg.get("n_microbatches", 4)),
        width=int(cfg.get("width", 8)), batch=int(cfg.get("batch", 8)),
        schedule=cfg.get("schedule", "1f1b"),
        wire=cfg.get("wire", "f32"),
        data_seed=int(cfg.get("data_seed", 0)),
        lease_s=float(cfg.get("lease_s", 0.6)),
        suspect_grace_s=float(cfg.get("suspect_grace_s", 0.4)),
        step_sleep_s=float(cfg.get("step_sleep_s", 0.0)),
        ctrl_lease_s=float(cfg.get("ctrl_lease_s", 0.0)),
        hb_ms=int(cfg.get("hb_ms", 60)),
        port=int(cfg["port"]), own_van=False)

    def done():
        states = [sup.svc.state_of(s) for s in range(sup.n_stages)]
        present = [m for m in states
                   if m.state in ("alive", "suspect")]
        return bool((present and all(m.committed >= sup.steps - 1
                                     for m in present)) or
                    (not present and
                     sup._committed_hw >= sup.steps - 1))

    rc = drive_controller_harness(
        sup.poll, lambda: sup._committed_hw, done,
        deadline_s=float(cfg.get("deadline_s", 300.0)))
    return 0 if rc is None else rc


if __name__ == "__main__":
    import sys
    if sys.argv[1] == "--controller":
        sys.exit(controller_main(sys.argv[2]))
    sys.exit(stage_main(sys.argv[1]))
