"""Explicit collective helpers over mesh axes.

Reference: python/hetu/communicator/mpi_nccl_comm.py (NCCL_Communicator
:164 — global/group/rank-tuple communicators, collectives :295-336) and
src/communication/mpi_nccl_communication.cu (custom grouped-send/recv
AllToAll :245-278 and hierarchical AllToAll :152-213).

TPU translation: communicators ARE mesh axes — a "device group" is an axis
(or axis tuple) of the Mesh, and arbitrary subgroup communicators correspond
to sub-axes obtained by reshaping the mesh, not runtime unique-id exchange.
These wrappers run inside shard_map; under plain pjit XLA usually inserts
the same collectives from sharding constraints, so these exist for (a) the
explicit-planner path (parallel/planner.py), (b) pipeline/ring primitives
that SPMD cannot infer, (c) parity with the reference's API surface.

Hierarchical A2A: the reference gathers intra-node, exchanges across node
leaders, then scatters (HAllToAll).  On TPU the same two-level structure is
expressed by factoring 'ep' into ('ep_outer','ep_inner') — inner axis on
ICI, outer on DCN — and running a2a per level; XLA routes each over the
right fabric because axis order encodes locality (mesh.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def psum(x, axis):
    """AllReduce(sum) over a mesh axis (dlarrayNcclAllReduce analog)."""
    return lax.psum(x, axis)


def pmean(x, axis):
    return lax.pmean(x, axis)


def all_gather(x, axis, *, tiled_dim: int = 0):
    """AllGather along a mesh axis, concatenating on tiled_dim."""
    return lax.all_gather(x, axis, axis=tiled_dim, tiled=True)


def reduce_scatter(x, axis, *, scatter_dim: int = 0):
    """ReduceScatter(sum) along a mesh axis."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                            tiled=True)


def all_to_all(x, axis, *, split_dim: int = 0, concat_dim: int = 0):
    """AllToAll: split `split_dim` across the axis, concat received chunks on
    `concat_dim` (the reference's _ncclAllToAll, grouped send/recv)."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def hierarchical_all_to_all(x, outer_axis: str, inner_axis: str,
                            *, split_dim: int = 0, concat_dim: int = 0):
    """Two-level A2A (reference _ncclHAllToAll): exchange within the inner
    (ICI) axis, then across the outer (DCN) axis.

    Destination rank order matches a FLAT all_to_all over the composite
    ('outer', 'inner') axis: send chunks (outer-major destination order) are
    pre-permuted to inner-major so the two-stage exchange delivers them in
    flat order — verified chunk-for-chunk against the composite-axis a2a in
    tests/test_moe.py.
    """
    n_o = lax.axis_size(outer_axis)
    n_i = lax.axis_size(inner_axis)
    L = x.shape[split_dim]
    assert L % (n_o * n_i) == 0
    rest = L // (n_o * n_i)
    # view split_dim as [n_o, n_i, rest] and swap to [n_i, n_o, rest]
    pre = x.shape[:split_dim]
    post = x.shape[split_dim + 1:]
    xr = x.reshape(*pre, n_o, n_i, rest, *post)
    xr = jnp.swapaxes(xr, split_dim, split_dim + 1)
    x = xr.reshape(*pre, L, *post)
    y = lax.all_to_all(x, inner_axis, split_axis=split_dim,
                       concat_axis=concat_dim, tiled=True)
    return lax.all_to_all(y, outer_axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def quantized_psum(x, axis, *, wire: str = "int8", block: int = 256):
    """Block-scaled quantized AllReduce(sum) over a mesh axis — the
    EQuARX scheme (PAPERS.md, arXiv 2506.17615) built from jax
    primitives so it stays INSIDE jit and XLA fuses quantize →
    collective → dequantize:

      flatten → blocks of ``block`` elts → symmetric int8 with one f32
      scale per block → ``all_gather`` of codes+scales in low precision
      → dequantize + sum in f32 → reshape back.

    Wire bytes per element: 1 + 4/block (int8) or 2 (bf16) vs 4 for the
    exact f32 path — ``wire="f32"`` IS the exact path (plain
    ``lax.psum``), so call sites can select precision per op with no
    structural change.  Per-replica quantization error is bounded by
    half a quantum: |err| <= max|block| / 254 per element per replica
    (asserted in tests/test_quant_wire.py); gradient call sites that
    need the bias removed over time pair this with error feedback the
    same way the PS wire does.

    Only valid where ``lax.psum`` is (inside ``shard_map``/``pmap`` over
    ``axis``).  Byte accounting happens at the call site (the executor's
    gradient-sync path records ``train.grad_sync.bytes_*``) — a traced
    function cannot touch host counters.
    """
    if wire in (None, "f32", "exact"):
        return lax.psum(x, axis)
    if wire == "bf16":
        g = lax.all_gather(x.astype(jnp.bfloat16), axis)
        return jnp.sum(g.astype(jnp.float32), axis=0).astype(x.dtype)
    if wire != "int8":
        raise ValueError(f"unknown wire dtype {wire!r}; expected "
                         f"'f32'/'bf16'/'int8'")
    from hetu_tpu.quantwire import jnp_block_encode
    q, scale = jnp_block_encode(x, block)
    qg = lax.all_gather(q, axis)          # [n_dev, nblk, block] int8
    sg = lax.all_gather(scale, axis)      # [n_dev, nblk, 1] f32
    out = jnp.sum(qg.astype(jnp.float32) * sg, axis=0)
    return (out.reshape(-1)[:x.size].reshape(x.shape)).astype(x.dtype)


def quantized_pmean(x, axis, *, wire: str = "int8", block: int = 256):
    """AllReduce(mean) counterpart of :func:`quantized_psum` (the
    gradient-sync shape: data-parallel gradients average over dp)."""
    if wire in (None, "f32", "exact"):
        return lax.pmean(x, axis)
    return quantized_psum(x, axis, wire=wire, block=block) / \
        lax.psum(1, axis)


def ppermute_shift(x, axis, shift: int = 1):
    """Ring shift over a mesh axis (PipelineSend/Receive analog and the ring-
    attention building block)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def grouped_allreduce(mesh: Mesh, axis, fn=None):
    """Build a jitted allreduce over one mesh axis for replicated-elsewhere
    arrays — the reference's per-param grouped communicators
    (context.py:1827 get_allreduce_devices).  Returns f(x) -> psum over axis.
    """
    in_spec = P(axis)

    @partial(shard_map, mesh=mesh, in_specs=in_spec, out_specs=P())
    def _ar(x):
        return lax.psum(x, axis)

    return _ar
