"""Device mesh construction — the TPU-native DeviceGroup/DistConfig.

Reference: python/hetu/context.py: `DeviceGroup` (:28) is an ordered worker
list with tuple entries for model-parallel groups; `DistConfig` (:2204) parses
a yaml cluster spec and the heturun launcher spawns MPI ranks.

TPU design: the cluster IS a mesh.  One `jax.sharding.Mesh` with named axes
('dp','tp','pp','ep','sp') replaces DeviceGroup/worker indices; XLA binds
collectives to axes and routes them over ICI (within slice) / DCN (across
slices).  Axis ordering matters for locality: we put 'tp' innermost so
tensor-parallel collectives ride the fastest ICI links, then 'ep'/'sp', with
'dp'/'pp' outermost (cross-slice friendly) — the mesh-layout recipe from the
public scaling playbooks.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"  # data parallel
AXIS_TP = "tp"  # tensor/model parallel
AXIS_PP = "pp"  # pipeline stages
AXIS_EP = "ep"  # expert parallel
AXIS_SP = "sp"  # sequence/context parallel

# outermost-to-innermost default ordering (innermost = fastest ICI)
DEFAULT_AXIS_ORDER = (AXIS_PP, AXIS_DP, AXIS_SP, AXIS_EP, AXIS_TP)


@dataclass
class MeshConfig:
    """Named-axis sizes; unspecified axes default to 1.

    The analog of the reference's yaml DistConfig + DeviceGroup nesting: e.g.
    reference `DeviceGroup([(gpu0,gpu1),(gpu2,gpu3)])` (2-way DP of 2-way MP)
    == MeshConfig(dp=2, tp=2).
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    axis_order: Sequence[str] = field(default=DEFAULT_AXIS_ORDER)

    def sizes(self):
        return {AXIS_DP: self.dp, AXIS_TP: self.tp, AXIS_PP: self.pp,
                AXIS_EP: self.ep, AXIS_SP: self.sp}

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.pp * self.ep * self.sp


def make_mesh(config: Optional[MeshConfig] = None, *, devices=None,
              **axis_sizes) -> Mesh:
    """Build a Mesh from a MeshConfig or axis sizes (make_mesh(dp=2, tp=4)).

    Axes of size 1 are kept in the mesh so shardings can always name every
    axis; XLA drops trivial axes at lowering.
    """
    if config is None:
        config = MeshConfig(**axis_sizes)
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = config.num_devices
    if devices.size < n:
        raise ValueError(
            f"mesh needs {n} devices, have {devices.size}")
    order = [a for a in config.axis_order]
    sizes = config.sizes()
    shape = [sizes[a] for a in order]
    dev = devices.reshape(-1)[:n].reshape(shape)
    return Mesh(dev, tuple(order))


def local_mesh(axis: str = AXIS_DP) -> Mesh:
    """All local devices on one axis — the default DP mesh (reference analog:
    heturun's single-host allreduce config)."""
    devs = np.asarray(jax.devices())
    return Mesh(devs, (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = AXIS_DP) -> NamedSharding:
    """Shard dim 0 (batch) along the dp axis."""
    return NamedSharding(mesh, P(axis))
