"""Device mesh construction — the TPU-native DeviceGroup/DistConfig.

Reference: python/hetu/context.py: `DeviceGroup` (:28) is an ordered worker
list with tuple entries for model-parallel groups; `DistConfig` (:2204) parses
a yaml cluster spec and the heturun launcher spawns MPI ranks.

TPU design: the cluster IS a mesh.  One `jax.sharding.Mesh` with named axes
('dp','tp','pp','ep','sp') replaces DeviceGroup/worker indices; XLA binds
collectives to axes and routes them over ICI (within slice) / DCN (across
slices).  Axis ordering matters for locality: we put 'tp' innermost so
tensor-parallel collectives ride the fastest ICI links, then 'ep'/'sp', with
'dp'/'pp' outermost (cross-slice friendly) — the mesh-layout recipe from the
public scaling playbooks.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"  # data parallel
AXIS_TP = "tp"  # tensor/model parallel
AXIS_PP = "pp"  # pipeline stages
AXIS_EP = "ep"  # expert parallel
AXIS_SP = "sp"  # sequence/context parallel

# outermost-to-innermost default ordering (innermost = fastest ICI)
DEFAULT_AXIS_ORDER = (AXIS_PP, AXIS_DP, AXIS_SP, AXIS_EP, AXIS_TP)


@dataclass
class MeshConfig:
    """Named-axis sizes; unspecified axes default to 1.

    The analog of the reference's yaml DistConfig + DeviceGroup nesting: e.g.
    reference `DeviceGroup([(gpu0,gpu1),(gpu2,gpu3)])` (2-way DP of 2-way MP)
    == MeshConfig(dp=2, tp=2).
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    axis_order: Sequence[str] = field(default=DEFAULT_AXIS_ORDER)

    def sizes(self):
        return {AXIS_DP: self.dp, AXIS_TP: self.tp, AXIS_PP: self.pp,
                AXIS_EP: self.ep, AXIS_SP: self.sp}

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.pp * self.ep * self.sp


def make_mesh(config: Optional[MeshConfig] = None, *, devices=None,
              **axis_sizes) -> Mesh:
    """Build a Mesh from a MeshConfig or axis sizes (make_mesh(dp=2, tp=4)).

    Axes of size 1 are kept in the mesh so shardings can always name every
    axis; XLA drops trivial axes at lowering.
    """
    if config is None:
        config = MeshConfig(**axis_sizes)
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = config.num_devices
    if devices.size < n:
        raise ValueError(
            f"mesh needs {n} devices, have {devices.size}")
    order = [a for a in config.axis_order]
    sizes = config.sizes()
    shape = [sizes[a] for a in order]
    dev = devices.reshape(-1)[:n].reshape(shape)
    return Mesh(dev, tuple(order))


def elastic_mesh(config: MeshConfig, alive: Sequence[int], *,
                 devices=None) -> Mesh:
    """Re-form the mesh with only the ``alive`` data-parallel workers.

    ``config`` describes the NOMINAL layout (dp = fleet width); ``alive``
    lists the surviving dp indices (sorted, each < config.dp).  Each dp
    worker owns one contiguous group of ``tp*pp*ep*sp`` devices in the
    nominal device array; the elastic mesh is built from the survivors'
    groups only, in rank order, so a worker that was never lost keeps its
    exact devices across resizes (its replica of the state never moves —
    only the lost/joined worker's shard placement changes).

    Mesh membership as a runtime input (arxiv 2412.14374): the same
    ``MeshConfig`` reshapes to any width 1..dp without re-describing the
    cluster.  Used by resilience/elastic.ElasticSupervisor.
    """
    alive = sorted(int(i) for i in alive)
    if not alive:
        raise ValueError("elastic mesh needs at least one alive worker")
    if alive[0] < 0 or alive[-1] >= config.dp:
        raise ValueError(
            f"alive indices {alive} out of range for nominal dp={config.dp}")
    if len(set(alive)) != len(alive):
        raise ValueError(f"duplicate alive indices {alive}")
    nominal = make_mesh(config, devices=devices)
    dp_axis = nominal.axis_names.index(AXIS_DP)
    dev = np.take(nominal.devices, alive, axis=dp_axis)
    return Mesh(dev, nominal.axis_names)


def local_mesh(axis: str = AXIS_DP) -> Mesh:
    """All local devices on one axis — the default DP mesh (reference analog:
    heturun's single-host allreduce config)."""
    devs = np.asarray(jax.devices())
    return Mesh(devs, (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_to_device(arr, sharding):
    """``jax.device_put`` with the CPU zero-copy-adoption guard.

    On CPU targets device_put can ADOPT a host numpy buffer zero-copy,
    and a later DONATED step then frees memory numpy still owns —
    observed as NaN state / heap corruption.  Route through a jax-owned
    copy there.  Non-CPU targets always copy host→device, so direct
    placement keeps sharded transfers single-pass (no full-leaf
    materialization on one device).  Shared by train/checkpoint.load and
    resilience/elastic's resharding — keep the workaround in ONE place.
    """
    import jax.numpy as jnp
    if any(d.platform == "cpu" for d in sharding.device_set):
        arr = jnp.array(arr)
    return jax.device_put(arr, sharding)


def batch_sharding(mesh: Mesh, axis: str = AXIS_DP) -> NamedSharding:
    """Shard dim 0 (batch) along the dp axis."""
    return NamedSharding(mesh, P(axis))
