"""Pipeline parallelism over the 'pp' mesh axis.

Reference: python/hetu/gpu_ops/pipeline_subexecutor.py (stage partitioning
:29-85, round-robin for unequal stage DP :87-128), gpipe_subexecutor.py
(all-forward-then-all-backward :33-89), pipedream_subexecutor.py (1F1B
generator :25-48, weight stashing :93-120), PipelineSend/ReceiveOp with
NCCL group calls (executor.py:1196-1205).

TPU design (SPMD collective pipelining): stages hold equal-structure block
stacks, stacked on a leading dim sharded over 'pp'.  A fori_loop runs
M + n_stages - 1 ticks; every tick each device applies its stage and
ppermutes activations to the next stage — the PipelineSend/Recv pair is one
ICI hop.  The schedule emerges from XLA autodiff: differentiating the loop
replays it in reverse, which IS all-forward-then-all-backward (GPipe).
Per-stage rematerialization (jax.checkpoint) gives the activation-memory
profile the reference gets from micro-batch array maps.  The 1F1B
(PipeDream) interleaving is provided as an explicit schedule object
(`pipedream_schedule`, same contract as the reference's generator) — used by
the simulator/planner; on-TPU execution uses the SPMD loop, where XLA
already overlaps the fwd/bwd halves it can.

Heterogeneous per-stage DP (reference round-robin skip schedules) maps to a
dp axis alongside pp in the same mesh: every stage runs the same dp degree
in SPMD, which subsumes the reference's unequal-DP machinery for the common
case; truly unequal degrees would need MPMD (multi-controller), out of scope
for a single jit program.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_layer_params, n_stages: int):
    """[L, ...] stacked layer params → [n_stages, L/n_stages, ...] (shared
    by the GPipe and 1F1B executors)."""
    def reshape(leaf):
        L = leaf.shape[0]
        assert L % n_stages == 0, (
            f"{L} layers not divisible by {n_stages} stages")
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])
    return jax.tree_util.tree_map(reshape, per_layer_params)


def pipedream_schedule(n_stages: int, n_microbatches: int):
    """1F1B order per stage (reference pipedream_subexecutor.py:25-48).

    Yields per-stage lists of ("fwd"|"bwd", microbatch_id): warmup of
    (n_stages - stage - 1) forwards, then alternating 1F1B, then drain.
    """
    out = []
    for s in range(n_stages):
        warmup = min(n_stages - s - 1, n_microbatches)
        order = []
        f = b = 0
        for _ in range(warmup):
            order.append(("fwd", f)); f += 1
        while f < n_microbatches:
            order.append(("fwd", f)); f += 1
            order.append(("bwd", b)); b += 1
        while b < n_microbatches:
            order.append(("bwd", b)); b += 1
        out.append(order)
    return out


class GPipe:
    """SPMD GPipe executor for a homogeneous block stack.

    block_fn(block_params, h) -> h — one transformer-block-like unit.
    Stage s applies its slice of the stacked blocks via lax.scan.

    stacked params layout: each leaf [n_stages, layers_per_stage, ...],
    sharded P('pp') on dim 0.  Input/output h: [B, S, ...] (batch dim 0 is
    split into n_microbatches).

    Usage:
        pipe = GPipe(block_fn, mesh, n_microbatches=8)
        out = pipe(stacked_params, h)         # differentiable
    """

    def __init__(self, block_fn: Callable, mesh: Mesh, *, axis: str = "pp",
                 n_microbatches: int = 4, remat: bool = True):
        self.block_fn = block_fn
        self.mesh = mesh
        self.axis = axis
        self.n_stages = mesh.shape[axis]
        self.n_microbatches = n_microbatches
        self.remat = remat

    def stack_params(self, per_layer_params):
        """[L, ...] stacked layer params → [n_stages, L/n_stages, ...]."""
        return stack_stage_params(per_layer_params, self.n_stages)

    def stack_params_unequal(self, per_layer_params, stage_bounds):
        """Pack UNEQUAL stages (a searcher's Plan.stage_bounds) by padding
        every stage to the longest one; returns (stacked, layer_mask) where
        layer_mask [n_stages, L_max] marks real (non-padding) layer slots.

        per_layer_params: leaves stacked on a leading layer dim [L, ...]
        (same layout stack_params takes).  stage_bounds: ascending layer
        end-indices, one per stage (GPipeSearching output).
        """
        bounds = list(stage_bounds)
        assert len(bounds) == self.n_stages, (bounds, self.n_stages)
        starts = [0] + bounds[:-1]
        sizes = [e - s for s, e in zip(starts, bounds)]
        l_max = max(sizes)
        mask = jnp.asarray([[1.0] * n + [0.0] * (l_max - n) for n in sizes])

        def pack(leaf):
            segs = []
            for s, n in zip(starts, sizes):
                seg = leaf[s:s + n]
                if n < l_max:
                    pad = jnp.zeros((l_max - n, *leaf.shape[1:]), leaf.dtype)
                    seg = jnp.concatenate([seg, pad], axis=0)
                segs.append(seg)
            return jnp.stack(segs)

        return jax.tree_util.tree_map(pack, per_layer_params), mask

    def __call__(self, stacked_params, h, *, layer_mask=None):
        """layer_mask [n_stages, L_max]: 1 = real layer, 0 = padding slot
        (identity) — produced by stack_params_unequal for searched plans."""
        M = self.n_microbatches
        B = h.shape[0]
        assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
        mb = B // M
        xs = h.reshape(M, mb, *h.shape[1:])
        if layer_mask is None:
            n_per = jax.tree_util.tree_leaves(stacked_params)[0].shape[1]
            layer_mask = jnp.ones((self.n_stages, n_per))

        block = self.block_fn
        if self.remat:
            block = jax.checkpoint(block)
        axis = self.axis
        n = self.n_stages

        def local(params, xs, mask):
            # params leaves arrive [1, Lps, ...] (this stage's slice)
            params = jax.tree_util.tree_map(lambda a: a[0], params)
            mask = mask[0]
            s = lax.axis_index(axis)
            T = M + n - 1
            buf = jnp.zeros_like(xs[0])
            outs = jnp.zeros_like(xs)

            def stage_apply(h):
                def body(carry, xs_l):
                    p_l, valid = xs_l
                    out = block(p_l, carry)
                    # padding slots pass activations through unchanged
                    return jnp.where(valid > 0, out, carry), None
                out, _ = lax.scan(body, h, (params, mask))
                return out

            def tick(carry, t):
                buf, outs = carry
                inject = xs[jnp.clip(t, 0, M - 1)]
                h_in = jnp.where(s == 0, inject, buf)
                h_out = stage_apply(h_in)
                perm = [(j, (j + 1) % n) for j in range(n)]
                buf_next = lax.ppermute(h_out, axis, perm)
                done = t - (n - 1)
                valid = (done >= 0) & (s == n - 1)
                idx = jnp.clip(done, 0, M - 1)
                outs = outs.at[idx].set(
                    jnp.where(valid, h_out, outs[idx]))
                return (buf_next, outs), None

            # scan (not fori_loop): the tick loop must be reverse-mode
            # differentiable — its reversal IS the backward pipeline
            (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(T))
            # broadcast last stage's outputs to all stages (zero elsewhere,
            # psum over the pp axis)
            outs = jnp.where(s == n - 1, outs, jnp.zeros_like(outs))
            return lax.psum(outs, axis)

        in_param_spec = jax.tree_util.tree_map(
            lambda _: P(self.axis), stacked_params)
        out = shard_map(local, mesh=self.mesh,
                        in_specs=(in_param_spec, P(), P(self.axis)),
                        out_specs=P(),
                        check_vma=False)(stacked_params, xs, layer_mask)
        return out.reshape(B, *h.shape[1:])
