"""MPMD pipeline pieces: unequal per-stage DP over separate processes.

Reference: python/hetu/gpu_ops/pipeline_subexecutor.py:87-128 — stages with
DIFFERENT data-parallel degrees exchange activations through round-robin
PipelineSend/ReceiveOp pairs whose targets come from the context's
round-robin assignment (context.py:164-188).  SPMD (one jit over one mesh)
cannot express two stages running different programs at different dp
degrees; this module provides the TPU-native MPMD form:

  * each stage range runs in its OWN process (own jax runtime, own mesh,
    own dp degree) — `bin/heturun` can start them like any worker set;
  * activations/cotangents hop processes through `VanMailbox` channels —
    host-bridged transfers over the PS van plane (the DCN path; on real
    multi-host TPU the bridge rides the same network the PS plane uses);
  * `round_robin_assignments` reproduces the reference's microbatch ->
    (sender replica, receiver replica) schedule.

tests/test_mpmd.py runs the 2-process prototype (stage0 dp=2, stage1 dp=1)
and checks end-to-end gradients against the single-process oracle —
VERDICT #7's acceptance bar.
"""

from __future__ import annotations

import time
from typing import List, Tuple

import numpy as np

from hetu_tpu import quantwire  # numpy-only; safe at module import


def round_robin_assignments(n_microbatches: int, n_src: int,
                            n_dst: int) -> List[Tuple[int, int]]:
    """Microbatch i is produced by stage-A replica i % n_src and consumed
    by stage-B replica i % n_dst (reference context.py:164-188 round-robin
    send/recv target computation)."""
    return [(i % n_src, i % n_dst) for i in range(n_microbatches)]


# ---------------------------------------------------------------------------
# microbatch schedules (reference pipeline_subexecutor.py GPipe flush and
# the PipeDream-flush 1F1B order, as per-stage op lists)
# ---------------------------------------------------------------------------

PIPELINE_SCHEDULES = ("gpipe", "1f1b")


def schedule_ops(kind: str, *, stage: int, n_stages: int,
                 n_microbatches: int,
                 stash_limit: int = 0) -> List[Tuple[str, int]]:
    """The per-stage microbatch op order of a synchronous pipeline step,
    as ``[("F"|"B", microbatch), ...]``.

    ``gpipe``: all forwards then all backwards (the flush schedule).
    ``stash_limit`` bounds the activation stash by splitting the step
    into ceil(M/stash_limit) mini-flushes — the memory/bubble trade the
    bench measures (an unbounded GPipe stashes all M microbatches; 1F1B
    never stashes more than ``n_stages - stage``).

    ``1f1b``: PipeDream-flush — stage s runs ``min(M, S-1-s)`` warmup
    forwards, then strict one-forward-one-backward, then drains.  Same
    per-step weight semantics as gpipe (single flush, update at the
    end); only the ORDER — and with it stash depth and bubble — differs.

    Both schedules emit backwards in ascending microbatch order, so
    gradient accumulation order (and therefore the summed f32 gradient,
    bitwise) is schedule-invariant — the property the elastic trainer's
    byte-identity contract leans on.
    """
    M, S, s = int(n_microbatches), int(n_stages), int(stage)
    if not 0 <= s < S:
        raise ValueError(f"stage {s} outside [0, {S})")
    if kind == "gpipe":
        chunk = M if not stash_limit else max(1, min(int(stash_limit), M))
        ops: List[Tuple[str, int]] = []
        for lo in range(0, M, chunk):
            mbs = range(lo, min(lo + chunk, M))
            ops += [("F", m) for m in mbs]
            ops += [("B", m) for m in mbs]
        return ops
    if kind == "1f1b":
        warmup = min(M, S - 1 - s)
        ops = [("F", m) for m in range(warmup)]
        f, b = warmup, 0
        while f < M:
            ops.append(("F", f))
            f += 1
            ops.append(("B", b))
            b += 1
        while b < M:
            ops.append(("B", b))
            b += 1
        return ops
    raise ValueError(f"unknown schedule {kind!r}; "
                     f"expected one of {PIPELINE_SCHEDULES}")


def peak_stash(ops) -> int:
    """Max number of microbatches whose forward activations are held at
    once under an op order (F stashes, B frees)."""
    live = peak = 0
    for op, _ in ops:
        live += 1 if op == "F" else -1
        peak = max(peak, live)
    return peak


# ---------------------------------------------------------------------------
# activation/cotangent wire codecs (the quantwire conventions applied to
# the mailbox payloads: f32-logical tensors over a non-f32 wire)
# ---------------------------------------------------------------------------

Q8_BLOCK = 64  # elements per int8 scale block on mailbox payloads


def encode_wire(arr, wire: str) -> tuple:
    """f32-logical array -> ``(payload bytes, logical_bytes)`` in the
    given wire dtype.  bf16 rounds to nearest-even (the XLA convention);
    int8 is block-scaled (one f32 scale per :data:`Q8_BLOCK` elements,
    quantwire clamp semantics).  Both are pure functions of the input —
    two runs encoding the same activations emit identical bytes, so a
    quantized edge never breaks the replay/byte-identity contracts."""
    quantwire.check_wire(wire)
    flat = np.ascontiguousarray(arr, np.float32).ravel()
    logical = flat.size * 4
    if wire == "f32":
        return flat.tobytes(), logical
    if wire == "bf16":
        u = flat.view(np.uint32).astype(np.uint64)
        r = ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(np.uint16)
        nan = np.isnan(flat)
        if nan.any():
            # the rounding carry overflows a NaN's mantissa into the
            # exponent (0x7FFFFFFF would decode as -0.0): force the
            # canonical quiet bf16 NaN instead — a NaN activation must
            # PROPAGATE, not silently zero (the nan_grad contract)
            sign = ((u >> 16) & 0x8000).astype(np.uint16)
            r = np.where(nan, sign | np.uint16(0x7FC0), r)
        return r.tobytes(), logical
    pad = (-flat.size) % Q8_BLOCK
    if pad:
        flat = np.pad(flat, (0, pad))
    q, scale = quantwire.q8_encode_axes(flat.reshape(-1, Q8_BLOCK), (1,))
    return q.tobytes() + scale.tobytes(), logical


def decode_wire(payload: bytes, n: int, wire: str) -> np.ndarray:
    """Inverse of :func:`encode_wire` back to ``n`` f32 elements."""
    quantwire.check_wire(wire)
    if wire == "f32":
        a = np.frombuffer(payload, np.float32).copy()
        if a.size != n:
            raise ValueError(f"wire payload has {a.size} f32s, "
                             f"expected {n}")
        return a
    if wire == "bf16":
        u = np.frombuffer(payload, np.uint16)
        if u.size != n:
            raise ValueError(f"wire payload has {u.size} bf16s, "
                             f"expected {n}")
        return (u.astype(np.uint32) << 16).view(np.float32).copy()
    nblk = -(-int(n) // Q8_BLOCK)
    want = nblk * Q8_BLOCK + nblk * 4
    if len(payload) != want:
        raise ValueError(f"wire payload has {len(payload)} bytes, "
                         f"expected {want} for {n} int8-block elements")
    q = np.frombuffer(payload[:nblk * Q8_BLOCK],
                      np.int8).reshape(nblk, Q8_BLOCK)
    scales = np.frombuffer(payload[nblk * Q8_BLOCK:],
                           np.float32).reshape(nblk, 1)
    return quantwire.q8_decode_axes(q, scales).ravel()[:n].copy()


class VanMailbox:
    """One-way ACKED channel between two processes over the van.

    Default transport (``impl="blob"``): the van's bulk-blob channel
    (OP_BLOB_PUT/GET/ACK, csrc/hetu_ps_van.cpp) — one contiguous payload
    frame per message with server-side blocking, so a message costs the
    sender ONE round trip and the reader two (get + ack), no client
    polling.  This is the zmq_van.h SArray-send analog and the shipped
    path.

    Legacy transport (``impl="sparse"``): payload spread over f32 table
    rows with seq/ack flag rows polled at ``poll_s`` — kept as the
    measured baseline the blob path must beat (see
    tests/test_ps_van.py frame-count A/B) and as a fallback that needs
    nothing but table ops.  Flag rows are f32, exact only to 2**24, so
    the wire flag wraps into [1, 2**20] (``_wire``); the ack lockstep
    (at most one in-flight message) keeps wrapped flags unambiguous.

    ``wire`` (blob transport only) selects the payload encoding —
    ``"f32"`` exact, ``"bf16"``/``"int8"`` the quantwire codecs (both
    deterministic, so quantized edges keep the replay contract); the
    mailbox counts ``bytes_logical``/``bytes_wire`` per direction and,
    when ``metric_path`` is set, folds them into the shared
    ``<path>.bytes_*`` telemetry counters.
    """

    _SEQ_MOD = 1 << 20

    @classmethod
    def _wire(cls, seq: int) -> int:
        return (seq - 1) % cls._SEQ_MOD + 1 if seq > 0 else 0

    def __init__(self, host: str, port: int, channel_id: int,
                 capacity: int, *, impl: str = "blob", wire: str = "f32",
                 metric_path: str | None = None,
                 connect_timeout_s: float = 20.0):
        if impl not in ("blob", "sparse"):
            raise ValueError(f"unknown mailbox impl {impl!r}")
        if wire != "f32" and impl != "blob":
            raise ValueError("quantized wire needs the blob transport "
                             "(the sparse fallback is f32 rows)")
        self.capacity = capacity
        self.impl = impl
        self.wire = quantwire.check_wire(wire)
        self.metric_path = metric_path
        self.bytes_logical = 0
        self.bytes_wire = 0
        self._last_seq = 0
        if impl == "blob":
            from hetu_tpu.ps.van import BlobChannel
            self._chan = BlobChannel(host, port, channel_id,
                                     connect_timeout_s=connect_timeout_s)
            return
        from hetu_tpu.ps.van import RemotePSTable
        deadline = time.monotonic() + connect_timeout_s
        # both endpoints race to create; -2 (exists) means the peer won
        while True:
            try:
                self.table = RemotePSTable(
                    host, port, capacity + 2, 1, table_id=channel_id,
                    create=True, init="zeros",
                    connect_timeout_s=connect_timeout_s)
                break
            except RuntimeError:
                try:
                    self.table = RemotePSTable(
                        host, port, capacity + 2, 1, table_id=channel_id,
                        create=False,
                        connect_timeout_s=connect_timeout_s)
                    break
                except RuntimeError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)

    def _flag(self, row: int) -> float:
        return float(self.table.sparse_pull([row])[0, 0])

    def put(self, arr, seq: int, *, timeout_s: float = 60.0,
            poll_s: float = 0.002) -> None:
        flat = np.ascontiguousarray(arr, np.float32).ravel()
        if flat.size > self.capacity:
            raise ValueError(f"message {flat.size} > capacity "
                             f"{self.capacity}")
        if self.impl == "blob":
            payload, logical = encode_wire(flat, self.wire)
            self._chan.put(payload, seq, timeout_s=timeout_s)
            self._last_seq = seq
            self.bytes_logical += logical
            self.bytes_wire += len(payload)
            if self.metric_path:
                quantwire.record_wire_bytes(self.metric_path, logical,
                                            len(payload))
            return
        deadline = time.monotonic() + timeout_s
        # wait for the reader's ack of the previous message
        while self._last_seq and \
                int(self._flag(self.capacity + 1)) != \
                self._wire(self._last_seq):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"mailbox: ack of seq {self._last_seq} not observed "
                    f"within {timeout_s}s")
            time.sleep(poll_s)
        self.table.sparse_set(np.arange(flat.size), flat.reshape(-1, 1))
        self.table.sparse_set(
            [self.capacity],
            np.asarray([[float(self._wire(seq))]], np.float32))
        self._last_seq = seq

    def get(self, shape, seq: int, *, timeout_s: float = 60.0,
            poll_s: float = 0.002) -> np.ndarray:
        n = int(np.prod(shape))
        if self.impl == "blob":
            data = self._chan.get(seq, timeout_s=timeout_s)
            # decode_wire copies out of the read-only buffer, so
            # consumers may mutate in place (the sparse transport's
            # contract)
            a = decode_wire(data, n, self.wire)
            self.bytes_logical += n * 4
            self.bytes_wire += len(data)
            return a.reshape(shape)
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                flag = self._flag(self.capacity)
            except RuntimeError:
                flag = None  # table not created yet / transient
            if flag is not None and int(flag) == self._wire(seq):
                data = self.table.sparse_pull(np.arange(n))
                self.table.sparse_set(
                    [self.capacity + 1],
                    np.asarray([[float(self._wire(seq))]], np.float32))
                return data.ravel().reshape(shape)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"mailbox: seq {seq} not observed within {timeout_s}s "
                    f"(last flag: {flag})")
            time.sleep(poll_s)

    def close(self) -> None:
        if self.impl == "blob":
            self._chan.close()
        else:
            self.table.close()


class MPMDStageRunner:
    """General N-stage, unequal-DP MPMD pipeline worker (reference
    pipeline_subexecutor.py:87-128 + context.py:164-188 round-robin
    machinery, generalized from round 3's 2-stage prototype).

    Each PROCESS runs one (stage, replica) pair of a pipeline whose stage
    s has ``stage_dps[s]`` data-parallel replicas.  Microbatch i is
    produced by stage-s replica ``i % stage_dps[s]`` and consumed by
    stage-(s+1) replica ``i % stage_dps[s+1]`` — activations and
    cotangents hop processes through acked :class:`VanMailbox` channels on
    a shared van server; cross-replica gradient reduction rides a PS
    accumulator table with a first-class van barrier (the PS-DP path).

    ``run_step(params, loss_fn, data=...)`` executes one GPipe-flush
    fwd+bwd over all M microbatches and returns
    ``(loss_sum_of_my_microbatches, param_grads)`` where grads are the
    stage's microbatch-mean, already reduced across its replicas.
    """

    def __init__(self, stage_fn, *, stage: int, replica: int,
                 stage_dps: List[int], n_microbatches: int,
                 in_shape, out_shape, host: str, port: int,
                 base_channel: int = 5_000_000, grad_size: int,
                 wire: str = "f32", worker_uid: int | None = None):
        import jax

        self.fn = stage_fn
        self.wire = quantwire.check_wire(wire)
        self.stage, self.replica = stage, replica
        self.dps = list(stage_dps)
        self.S = len(stage_dps)
        self.M = n_microbatches
        self.in_shape, self.out_shape = tuple(in_shape), tuple(out_shape)
        self.host, self.port = host, port
        self.base = base_channel
        self.grad_size = grad_size
        self._jax = jax
        self._mail: dict = {}
        self._seq: dict = {}
        # unique worker id across ALL processes of this pipeline (kept for
        # callers that address workers globally, e.g. logging/launchers)
        self.uid = worker_uid if worker_uid is not None else \
            sum(self.dps[:stage]) + replica

    # channel id for edge (s -> s+1), sender replica a, receiver replica b;
    # backward cotangents use the mirrored id space
    def _chan(self, edge: int, a: int, b: int, backward: bool):
        key = (edge, a, b, backward)
        if key not in self._mail:
            cid = (self.base + edge * (1 << 14) + a * (1 << 7) + b
                   + ((1 << 22) if backward else 0))
            # edge e's messages (activations forward, cotangents backward)
            # have stage e's output size — which is my out_shape on my
            # downstream edge and my in_shape on my upstream edge
            cap = int(np.prod(self.out_shape)) if edge == self.stage \
                else int(np.prod(self.in_shape))
            self._mail[key] = VanMailbox(
                self.host, self.port, cid, cap, wire=self.wire,
                metric_path=f"mpmd.edge{edge}."
                            f"{'bwd' if backward else 'fwd'}")
            self._seq[key] = 0
        return self._mail[key]

    def _next_seq(self, edge, a, b, backward):
        key = (edge, a, b, backward)
        self._seq[key] += 1
        return self._seq[key]

    def _my_microbatches(self):
        return [m for m in range(self.M)
                if m % self.dps[self.stage] == self.replica]

    def _grad_plumbing(self):
        """One REUSABLE accumulator table + first-class OP_BARRIER for
        this stage, created lazily on the first reducing step (the
        barrier's server-side generation counter matches successive
        rounds natively; the table is cleared in place between steps —
        per-step table ids would leak server memory).  Preduce
        matchmaking is reserved for actual partial reduce."""
        if getattr(self, "_acc", None) is not None:
            return self._acc, self._barrier_cli
        from hetu_tpu.ps.van import RemoteBarrier, RemotePSTable
        tid = self.base + (1 << 23) + self.stage
        if self.replica == 0:
            self._acc = RemotePSTable(self.host, self.port, self.grad_size,
                                      1, table_id=tid, create=True,
                                      init="zeros", optimizer="sgd",
                                      lr=-1.0)  # push == add
        else:
            # wait until replica 0 created it (connecting with
            # create=False never probes; a 1-row pull does)
            self._acc = RemotePSTable(self.host, self.port, self.grad_size,
                                      1, table_id=tid, create=False)
            deadline = time.monotonic() + 20
            while True:
                try:
                    self._acc.sparse_pull([0])
                    break
                except RuntimeError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
        self._barrier_cli = RemoteBarrier(
            self.host, self.port,
            barrier_id=self.base + (1 << 23) + 64 + self.stage,
            n_workers=self.dps[self.stage])
        return self._acc, self._barrier_cli

    def _barrier(self, cli):
        cli.wait(timeout_s=60.0)

    def run_step(self, params, *, loss_fn=None, data=None):
        """One fwd+bwd over all microbatches this replica owns.

        data: stage 0 only — list of per-microbatch inputs indexed by
        GLOBAL microbatch id (entries for other replicas may be None).
        loss_fn: last stage only — scalar loss on one microbatch's output;
        the step optimizes mean-over-all-microbatches loss.
        """
        jax = self._jax
        s, dps = self.stage, self.dps
        first, last = s == 0, s == self.S - 1
        vjps, losses = {}, {}
        # ---- forward (microbatch order; per-channel seqs stay aligned
        # because both endpoints walk their shared microbatches in order)
        for m in self._my_microbatches():
            if first:
                x = np.asarray(data[m], np.float32)
            else:
                src = m % dps[s - 1]
                ch = self._chan(s - 1, src, self.replica, False)
                x = ch.get(self.in_shape,
                           self._next_seq(s - 1, src, self.replica, False))
            y, vjp = jax.vjp(lambda p, xx: self.fn(p, xx), params,
                             jax.numpy.asarray(x))
            vjps[m] = vjp
            if last:
                loss, gy = jax.value_and_grad(loss_fn)(y)
                losses[m] = float(loss)
                vjps[m] = (vjp, gy)
            else:
                dst = m % dps[s + 1]
                ch = self._chan(s, self.replica, dst, False)
                ch.put(np.asarray(y),
                       self._next_seq(s, self.replica, dst, False))
        # ---- backward (same order: flush schedule)
        gsum = None
        for m in self._my_microbatches():
            if last:
                vjp, gy = vjps[m]
            else:
                vjp = vjps[m]
                dst = m % dps[s + 1]
                ch = self._chan(s, self.replica, dst, True)
                gy = ch.get(self.out_shape,
                            self._next_seq(s, self.replica, dst, True))
            gp, gx = vjp(jax.numpy.asarray(np.asarray(gy, np.float32)))
            if not first:
                src = m % dps[s - 1]
                ch = self._chan(s - 1, src, self.replica, True)
                ch.put(np.asarray(gx),
                       self._next_seq(s - 1, src, self.replica, True))
            gsum = gp if gsum is None else jax.tree_util.tree_map(
                lambda a, b: a + b, gsum, gp)
        # ---- cross-replica grad reduction: PS accumulator + barrier
        leaves, treedef = jax.tree_util.tree_flatten(gsum)
        flat = np.concatenate([np.asarray(g, np.float32).ravel()
                               for g in leaves]) if leaves else \
            np.zeros(0, np.float32)
        if dps[s] > 1:
            acc, barrier = self._grad_plumbing()
            acc.sparse_push(np.arange(flat.size), flat.reshape(-1, 1))
            self._barrier(barrier)   # all replicas pushed
            flat = acc.sparse_pull(np.arange(flat.size)).ravel()
            self._barrier(barrier)   # all replicas pulled the sum
            if self.replica == 0:
                acc.clear()          # reuse next step: no per-step tables
            self._barrier(barrier)   # clear landed before anyone re-pushes
        flat /= self.M  # mean over the GLOBAL microbatch count
        out, off = [], 0
        for g in leaves:
            n = int(np.prod(np.asarray(g).shape))
            out.append(flat[off:off + n].reshape(np.asarray(g).shape))
            off += n
        grads = jax.tree_util.tree_unflatten(treedef, out)
        return sum(losses.values()), grads

    def close(self):
        for mb in self._mail.values():
            mb.close()
        if getattr(self, "_acc", None) is not None:
            self._acc.close()
            self._barrier_cli.close()
