"""MPMD pipeline pieces: unequal per-stage DP over separate processes.

Reference: python/hetu/gpu_ops/pipeline_subexecutor.py:87-128 — stages with
DIFFERENT data-parallel degrees exchange activations through round-robin
PipelineSend/ReceiveOp pairs whose targets come from the context's
round-robin assignment (context.py:164-188).  SPMD (one jit over one mesh)
cannot express two stages running different programs at different dp
degrees; this module provides the TPU-native MPMD form:

  * each stage range runs in its OWN process (own jax runtime, own mesh,
    own dp degree) — `bin/heturun` can start them like any worker set;
  * activations/cotangents hop processes through `VanMailbox` channels —
    host-bridged transfers over the PS van plane (the DCN path; on real
    multi-host TPU the bridge rides the same network the PS plane uses);
  * `round_robin_assignments` reproduces the reference's microbatch ->
    (sender replica, receiver replica) schedule.

tests/test_mpmd.py runs the 2-process prototype (stage0 dp=2, stage1 dp=1)
and checks end-to-end gradients against the single-process oracle —
VERDICT #7's acceptance bar.
"""

from __future__ import annotations

import time
from typing import List, Tuple

import numpy as np


def round_robin_assignments(n_microbatches: int, n_src: int,
                            n_dst: int) -> List[Tuple[int, int]]:
    """Microbatch i is produced by stage-A replica i % n_src and consumed
    by stage-B replica i % n_dst (reference context.py:164-188 round-robin
    send/recv target computation)."""
    return [(i % n_src, i % n_dst) for i in range(n_microbatches)]


class VanMailbox:
    """One-way single-slot channel over a PS van table.

    Layout: rows [0, capacity) hold the payload, row `capacity` holds the
    sequence flag.  `put` writes payload THEN flag; `get` polls the flag —
    the van server applies one connection's requests in order, so the
    reader observing seq implies the payload is complete.  A fresh `seq`
    per message makes the channel reusable (ping-pong for fwd/bwd).

    At most ONE message may be outstanding per channel: there is no reader
    ack, so a second `put` can overwrite the payload between the reader's
    flag poll and its (separate) payload pull, tearing the data.  Callers
    must externally order put(seq=n+1) after the consumer of seq=n has
    returned (the pipeline schedules here use one channel per microbatch
    or strict ping-pong, which satisfies this).
    """

    def __init__(self, host: str, port: int, channel_id: int,
                 capacity: int, *, connect_timeout_s: float = 20.0):
        from hetu_tpu.ps.van import RemotePSTable
        self.capacity = capacity
        deadline = time.time() + connect_timeout_s
        # both endpoints race to create; -2 (exists) means the peer won
        while True:
            try:
                self.table = RemotePSTable(
                    host, port, capacity + 1, 1, table_id=channel_id,
                    create=True, init="zeros",
                    connect_timeout_s=connect_timeout_s)
                break
            except RuntimeError:
                try:
                    self.table = RemotePSTable(
                        host, port, capacity + 1, 1, table_id=channel_id,
                        create=False,
                        connect_timeout_s=connect_timeout_s)
                    break
                except RuntimeError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.05)

    def put(self, arr, seq: int) -> None:
        flat = np.ascontiguousarray(arr, np.float32).ravel()
        if flat.size > self.capacity:
            raise ValueError(f"message {flat.size} > capacity "
                             f"{self.capacity}")
        self.table.sparse_set(np.arange(flat.size), flat.reshape(-1, 1))
        self.table.sparse_set([self.capacity],
                              np.asarray([[float(seq)]], np.float32))

    def get(self, shape, seq: int, *, timeout_s: float = 60.0,
            poll_s: float = 0.002) -> np.ndarray:
        n = int(np.prod(shape))
        deadline = time.time() + timeout_s
        while True:
            try:
                flag = float(self.table.sparse_pull([self.capacity])[0, 0])
            except RuntimeError:
                flag = None  # table not created yet / transient
            if flag is not None and int(flag) == seq:
                data = self.table.sparse_pull(np.arange(n))
                return data.ravel().reshape(shape)
            if time.time() > deadline:
                raise TimeoutError(
                    f"mailbox: seq {seq} not observed within {timeout_s}s "
                    f"(last flag: {flag})")
            time.sleep(poll_s)

    def close(self) -> None:
        self.table.close()
