"""Sharding plan analysis: what collectives did SPMD actually insert?

Reference: GraphStatus.assign_context_by_traverse_nodes (context.py:1469)
decides explicitly where AllReduce/AllGather/ReduceScatter/Send/Recv ops go,
and cross_send/cross_receive (context.py:1640-1826) price generic re-splits.

TPU inversion of control: XLA's SPMD partitioner makes those decisions from
the sharding annotations, so the planner's job flips from *inserting* comm
ops to *auditing* them — lower the jitted step under a candidate sharding,
extract the collectives XLA inserted (with byte counts), and price the plan
with the simulator's cost model.  This closes the loop the reference closed
with HetuSimulator.get_general_comm_time: searchers propose shardings,
the audit verifies what they actually cost.
"""

from __future__ import annotations

import re
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import numpy as np

from hetu_tpu.profiler.cost_model import (
    ChipSpec, allgather_time, allreduce_time, alltoall_time, detect_chip,
    p2p_time,
)

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1,
                "u8": 1, "f64": 8, "s64": 8, "u64": 8, "pred": 1, "s16": 2,
                "u16": 2, "f8e4m3fn": 1, "f8e5m2": 1}


@dataclass
class CollectiveInfo:
    kind: str
    dtype: str
    shape: tuple
    bytes: int
    count: int = 1


@dataclass
class PlanAudit:
    collectives: List[CollectiveInfo] = field(default_factory=list)
    flops: float = 0.0
    bytes_accessed: float = 0.0

    def total_comm_bytes(self) -> int:
        return sum(c.bytes * c.count for c in self.collectives)

    def scaled(self, kind_multipliers: Dict[str, int]) -> "PlanAudit":
        """Scale per-kind counts by known loop trip counts (collectives in
        while/scan bodies appear once in HLO text)."""
        out = PlanAudit(flops=self.flops, bytes_accessed=self.bytes_accessed)
        out.collectives = [
            CollectiveInfo(c.kind, c.dtype, c.shape, c.bytes,
                           c.count * kind_multipliers.get(c.kind, 1))
            for c in self.collectives]
        return out

    def by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = defaultdict(int)
        for c in self.collectives:
            out[c.kind] += c.bytes * c.count
        return dict(out)

    def estimate_time(self, chip: Optional[ChipSpec] = None,
                      n_devices: int = 8) -> float:
        """Roofline step-time estimate: compute + comm (no overlap)."""
        chip = chip or detect_chip()
        t = self.flops / (chip.bf16_flops * chip.mxu_util)
        t = max(t, self.bytes_accessed / chip.hbm_bw)
        for c in self.collectives:
            nbytes = c.bytes * c.count
            if c.kind == "all-reduce":
                t += allreduce_time(chip, nbytes, n_devices)
            elif c.kind in ("all-gather", "reduce-scatter"):
                t += allgather_time(chip, nbytes, n_devices)
            elif c.kind == "all-to-all":
                t += alltoall_time(chip, nbytes, n_devices)
            else:  # collective-permute
                t += p2p_time(chip, nbytes)
        return t


# op name with optional async suffix; '-done' halves of start/done pairs are
# skipped so async collectives (the TPU default) are not double-counted
_KIND_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(-start|-done)?\(")
_FIRST_SHAPE_RE = re.compile(r"\b([a-z0-9]+)\[([\d,]*)\]")


def audit(fn, *args, static_argnums=(), donate_argnums=()) -> PlanAudit:
    """Lower fn(*args) (args carry their shardings) and audit the result.

    Caveat: collectives inside while/scan bodies (e.g. the GPipe tick loop)
    are counted once, not per trip — scale those by the known trip count
    when comparing pipelined plans (PlanAudit.scaled()).
    """
    jfn = jax.jit(fn, static_argnums=static_argnums,
                  donate_argnums=donate_argnums)
    lowered = jfn.lower(*args)
    compiled = lowered.compile()
    txt = compiled.as_text()

    result = PlanAudit()
    agg: Dict[tuple, CollectiveInfo] = {}
    for line in txt.splitlines():
        line = line.strip()
        km = _KIND_RE.search(line)
        if not km or km.group(2) == "-done":
            continue
        kind = km.group(1)
        # result shape = first dtype[dims] on the line (for tuple results of
        # async starts this is the first element, which is the payload)
        sm = _FIRST_SHAPE_RE.search(line)
        if not sm:
            continue
        dtype, dims = sm.group(1), sm.group(2)
        shape = tuple(int(d) for d in dims.split(",") if d) if dims else ()
        nbytes = int(np.prod(shape, dtype=np.int64)) * _DTYPE_BYTES.get(
            dtype, 4) if shape else _DTYPE_BYTES.get(dtype, 4)
        key = (kind, dtype, shape)
        if key in agg:
            agg[key].count += 1
        else:
            agg[key] = CollectiveInfo(kind, dtype, shape, nbytes)
    result.collectives = list(agg.values())

    cost = compiled.cost_analysis()
    if cost:
        c = cost[0] if isinstance(cost, (list, tuple)) else cost
        result.flops = float(c.get("flops", 0.0))
        result.bytes_accessed = float(c.get("bytes accessed", 0.0))
    return result


def verify_spec_transition(mesh, shape, src, dst, dtype=None):
    """Assert XLA realizes a src→dst ShardSpec transition with the collective
    the NodeStatus algebra predicts (spec.predict_collective).

    This is the executable bridge between the reference's pattern checks
    (context.py:769-783) and GSPMD: we build the minimal program whose
    producer has spec `src` (partial specs are produced authentically, by a
    matmul whose contraction dim is sharded over the partial axes) and whose
    consumer demands `dst`, audit the compiled HLO, and compare.

    Returns (predicted_kind, audited_kinds).  Raises AssertionError on
    mismatch — a failing searcher/strategy would mis-price its plan.
    """
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hetu_tpu.parallel.spec import predict_collective

    dtype = dtype or jnp.float32
    pred = predict_collective(src, dst)
    dst_sh = NamedSharding(mesh, dst.pspec())

    if src.partial:
        # authentic partial producer: y = x @ w with the contraction dim
        # sharded over the partial axes — each device holds a partial sum
        k = 8 * int(np.prod([mesh.shape[a] for a in src.partial]))
        x = jnp.ones((shape[0], k), dtype)
        w = jnp.ones((k,) + tuple(shape[1:]), dtype)
        x = jax.device_put(x, NamedSharding(mesh, P(src.dims[0],
                                                    src.partial)))
        w = jax.device_put(w, NamedSharding(mesh, P(src.partial,
                                                    *src.dims[1:])))

        def prog(x, w):
            return jax.lax.with_sharding_constraint(x @ w, dst_sh)

        a = audit(prog, x, w)
    else:
        x = jax.device_put(jnp.ones(shape, dtype),
                           NamedSharding(mesh, src.pspec()))

        def prog(x):
            return jax.lax.with_sharding_constraint(x * 2, dst_sh)

        a = audit(prog, x)

    audited = sorted({c.kind for c in a.collectives})
    if pred is None:
        assert audited in ([], ["collective-permute"]), (
            f"algebra predicts a local transition but XLA inserted "
            f"{audited}")
        return None, audited
    kind = pred[0]
    # GSPMD may realize a reduce-scatter as all-reduce + local slice (it
    # does on the CPU backend); that is the same pattern priced pessimally,
    # so accept the superset collective for the RS check
    ok = {kind} | ({"all-reduce"} if kind == "reduce-scatter" else set())
    assert ok & set(audited), (
        f"algebra predicts {kind} for {src}→{dst} but XLA inserted "
        f"{audited or 'nothing'}")
    return kind, audited


def report(audit_result: PlanAudit, *, chip: Optional[ChipSpec] = None,
           n_devices: int = 8) -> str:
    lines = [f"flops/step:        {audit_result.flops:.3e}",
             f"hbm bytes/step:    {audit_result.bytes_accessed:.3e}",
             f"comm bytes/step:   {audit_result.total_comm_bytes():.3e}"]
    for kind, nbytes in sorted(audit_result.by_kind().items()):
        lines.append(f"  {kind:<20} {nbytes:.3e} B")
    lines.append(f"est step time:     "
                 f"{audit_result.estimate_time(chip, n_devices) * 1e3:.2f} ms")
    return "\n".join(lines)
