"""Ulysses (DeepSpeed-style) sequence parallelism: head <-> sequence A2A.

Reference: absent in Hetu core; the MoE AllToAll machinery
(gpu_ops/AllToAll.py, src/communication _ncclAllToAll) is the building block
(SURVEY.md §2.3 'Sequence parallelism' row).  Attention inputs arrive
sequence-sharded [B, H, S/n, D]; an all_to_all re-shards to head-sharded
[B, H/n, S, D], local full attention runs per device, and a reverse a2a
restores sequence sharding.  Requires num_heads %% n == 0.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from jax.sharding import Mesh, PartitionSpec as P

from hetu_tpu.ops.attention import attention, causal_attention


def _ulysses_local(q, k, v, *, axis: str, causal: bool, scale):
    # [B, H, S/n, D] --a2a--> [B, H/n, S, D]
    def to_heads(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    if causal:
        oh = causal_attention(qh, kh, vh, scale=scale)
    else:
        oh = attention(qh, kh, vh, scale=scale)
    return to_seq(oh)


def ulysses_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                      causal: bool = False, scale=None):
    """q,k,v: [B, H, S, D] with S sharded over `axis`; heads must divide the
    axis size."""
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(f"num_heads {q.shape[1]} not divisible by "
                         f"{axis}={n}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    fn = functools.partial(_ulysses_local, axis=axis, causal=causal,
                           scale=scale)
    spec = P(None, None, axis, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
