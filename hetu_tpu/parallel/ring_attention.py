"""Ring attention over the 'sp' mesh axis — long-context capability.

Reference: absent in Hetu core (SURVEY.md §2.3/§5: only Megatron
sequence-parallel in vendored Galvatron code); this is the planned new
capability: blockwise attention with online-softmax accumulation while K/V
chunks rotate around the ICI ring via ppermute, so sequence length scales
with the number of chips at O(S/n) memory per chip and compute overlaps
communication (Liu et al. ring attention; the standard TPU formulation).

Layout: q,k,v are [B, H, S, D] sharded on S over `axis`.  Inside shard_map
each device sees [B, H, S/n, D] and performs n blockwise steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, bias_mask, scale, o, m, l):
    """One blockwise online-softmax accumulation step.

    q [B,H,Sq,D]; k,v [B,H,Sk,D]; bias_mask [Sq,Sk] bool (True=keep).
    o,m,l are the running output / max / normalizer (f32).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(bias_mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (m_new stays NEG_INF): exp(NEG_INF - NEG_INF)=1
    # would pollute l; clamp the correction instead.
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(bias_mask[None, None], p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return o_new, m_new, l_new


def _ring_attention_local(q, k, v, *, axis: str, causal: bool, scale):
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    qf = q.astype(jnp.float32)

    o = jnp.zeros((B, H, Sq, D), jnp.float32)
    m = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Sq), jnp.float32)

    q_pos = my * Sq + jnp.arange(Sq)

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (my - i) % n  # rank whose chunk we currently hold
        if causal:
            k_pos = src * Sk + jnp.arange(Sk)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((Sq, Sk), bool)
        o, m, l = _block_attn(qf, k_cur, v_cur, mask, scale, o, m, l)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o, m, l, k, v))
    l = jnp.maximum(l, 1e-20)  # fully-masked rows → 0 output
    return (o / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                   causal: bool = False, scale=None):
    """q,k,v: [B, H, S, D] with S sharded over `axis` on `mesh`."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    fn = functools.partial(_ring_attention_local, axis=axis, causal=causal,
                           scale=scale)
    spec = P(None, None, axis, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
