from hetu_tpu.parallel.strategies.base import Strategy
from hetu_tpu.parallel.strategies.simple import (
    DataParallel, MegatronLM,
)
