from hetu_tpu.parallel.strategies.base import Strategy
from hetu_tpu.parallel.strategies.simple import (
    DataParallel, MegatronLM, ModelParallel4CNN, ModelParallel4LM,
    OneWeirdTrick4CNN,
)
from hetu_tpu.parallel.strategies.search import (
    FlexFlowSearching, GalvatronSearching, GPipeSearching, OptCNNSearching,
    PipeDreamSearching, PipeOptSearching, Plan,
)
from hetu_tpu.parallel.strategies.graph_plan import GraphPlanStrategy
