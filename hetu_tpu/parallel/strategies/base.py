"""Strategy base: maps a model's parameter tree to shardings over a mesh.

Reference: python/hetu/distributed_strategies/base.py:13 (`Strategy`): cluster
settings + per-node NodeStatus assignment + JSON save/load of per-layer
{splits, duplicate, partial, order, device} (:158-227).

TPU translation: a Strategy produces a pytree of PartitionSpec matching the
parameter tree (+ the batch spec), which the Executor materializes as
NamedShardings.  JSON round-trip keeps the same role as the reference's
strategy files: a searcher emits one, a run loads it.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _fit(spec: P, leaf, mesh: Mesh) -> NamedSharding:
    """Drop spec entries whose mesh-axis product does not divide the dim."""
    dims = []
    for i, entry in enumerate(spec):
        if i >= leaf.ndim:
            break  # truncate over-long specs (NamedSharding rejects
                   # len(spec) > rank even with trailing Nones)
        if entry is None:
            dims.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        k = 1
        for a in axes:
            k *= mesh.shape[a]
        dims.append(entry if leaf.shape[i] % k == 0 else None)
    return NamedSharding(mesh, P(*dims))


class Strategy:
    """Assign PartitionSpecs to parameters by tree-path pattern."""

    def param_spec(self, path: str, leaf) -> P:
        """Override: spec for one parameter, by its tree path string."""
        return P()

    def slot_spec(self, path: str, leaf) -> P:
        """Spec for one optimizer slot — defaults to the param's spec.
        Override for ZeRO-1 style layouts where slots shard over dp while
        params stay replicated."""
        return self.param_spec(path, leaf)

    def batch_spec(self) -> P:
        return P("dp")

    # ---- tree-level API ----
    def param_specs(self, params) -> Any:
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        specs = [self.param_spec(jax.tree_util.keystr(path), leaf)
                 for path, leaf in flat]
        return jax.tree_util.tree_unflatten(treedef, specs)

    def shardings(self, params, mesh: Mesh) -> Any:
        """Materialize NamedShardings; dims whose size does not divide the
        assigned axis product fall back to replication (the reference
        requires divisible splits — we degrade gracefully instead, e.g. a
        10-class FC head under tp=4)."""
        return jax.tree_util.tree_map(
            lambda spec, leaf: _fit(spec, leaf, mesh),
            self.param_specs(params), params,
            is_leaf=lambda x: isinstance(x, P))

    def slot_shardings(self, params, mesh: Mesh) -> Any:
        """NamedShardings for optimizer slots (one tree, reused per slot)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        shs = [_fit(self.slot_spec(jax.tree_util.keystr(path), leaf), leaf,
                    mesh) for path, leaf in flat]
        return jax.tree_util.tree_unflatten(treedef, shs)

    def place(self, params, mesh: Mesh):
        """device_put the parameter tree according to this strategy."""
        return jax.tree_util.tree_map(
            lambda leaf, sh: jax.device_put(leaf, sh), params,
            self.shardings(params, mesh))

    # ---- JSON round-trip (reference base.py:158-227) ----
    def save_json(self, params, path):
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        out = {}
        for p, leaf in flat:
            key = jax.tree_util.keystr(p)
            out[key] = {"spec": list(self.param_spec(key, leaf)),
                        "shape": list(leaf.shape)}
        Path(path).write_text(json.dumps(out, indent=1, default=str))

    @staticmethod
    def load_json(path) -> "Strategy":
        table = {k: tuple(None if s is None else s for s in v["spec"])
                 for k, v in json.loads(Path(path).read_text()).items()}

        class _Loaded(Strategy):
            def param_spec(self, path_str, leaf):
                return P(*table.get(path_str, ()))

        return _Loaded()
