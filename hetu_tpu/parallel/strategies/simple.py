"""Manual strategies: DataParallel and MegatronLM.

Reference: python/hetu/distributed_strategies/simple.py — `DataParallel` (:6),
`ModelParallel4CNN` (:46), `ModelParallel4LM` (:113), `OneWeirdTrick4CNN`
(:119), `MegatronLM` (:174): column-split QKV/FFN-in, row-split
out-proj/FFN-out with partial-sum→allreduce, vocab-parallel embedding.

TPU translation: the same split decisions expressed as PartitionSpecs; XLA's
SPMD partitioner inserts the psum exactly where the reference's partial-sum
NodeStatus triggered an AllReduceCommunicateOp.  Works for our transformer
models' parameter naming (models/bert.py, models/gpt.py, layers/transformer.py);
stacked scan-over-layers params have a leading layer dim, handled by prefixing
None.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from hetu_tpu.parallel.mesh import AXIS_DP, AXIS_TP
from hetu_tpu.parallel.strategies.base import Strategy


class DataParallel(Strategy):
    """All params replicated, batch over dp (simple.py:6)."""

    def param_spec(self, path, leaf):
        return P()


class ModelParallel4CNN(Strategy):
    """CNN model parallelism (simple.py:46): fully-connected layers split
    over tp (column-parallel), convolutions replicated."""

    FC_MARKERS = ("fc", "linear", "dense")

    def param_spec(self, path, leaf):
        ndim = leaf.ndim if hasattr(leaf, "ndim") else len(leaf.shape)
        low = path.lower()
        if any(m in low for m in self.FC_MARKERS) and "weight" in low \
                and ndim == 2:
            return P(None, AXIS_TP)   # column split
        if any(m in low for m in self.FC_MARKERS) and "bias" in low:
            return P(AXIS_TP)
        return P()


class OneWeirdTrick4CNN(ModelParallel4CNN):
    """Krizhevsky's one-weird-trick (simple.py:119): data parallel for the
    conv trunk, model parallel for the FC head — the spec is identical to
    ModelParallel4CNN (convs replicated so dp shards batch; FC tp-split);
    the difference is the runtime pairing with a dp axis in the mesh."""


class ModelParallel4LM(ModelParallel4CNN):
    """LM flavor of the CNN MP preset (simple.py:113 — upstream it is
    literally ModelParallel4CNN with an mp_4_lm flag): dense projection
    weights tp-column-split, everything else replicated.  MegatronLM is
    the recommended LM strategy; this exists for preset-name parity."""


class MegatronLM(Strategy):
    """Megatron-style tensor parallel for the transformer models.

    Column-parallel (output-dim split over tp): qkv_weight, ffn_in weight —
    and their biases — plus the SwiGLU gate/up pair (the Llama MLP's
    column points, models/llama.py).  Row-parallel (input-dim split,
    partial-sum output): out_weight, ffn_out weight and SwiGLU down —
    biases replicated.  Vocab-parallel: tok_emb (dim 0); the tied LM head
    / vocab-CE then computes with vocab partials (simple.py:174-283).
    """

    COL = ("qkv_weight", "qkv_bias", "ffn_in",  # split output dim
           "ffn_gate", "ffn_up")
    ROW = ("out_weight", "ffn_out", "ffn_down")  # split input dim
    VOCAB = ("tok_emb", "mlm_bias")

    def param_spec(self, path, leaf):
        ndim = leaf.ndim if hasattr(leaf, "ndim") else len(leaf.shape)
        def spec_with_layer_prefix(*tail):
            # stacked scan params carry a leading layer dim
            pad = ndim - len(tail)
            return P(*((None,) * pad + tail))

        if any(k in path for k in self.VOCAB):
            return P(AXIS_TP, *(None,) * (ndim - 1))
        if any(k in path for k in self.COL):
            return spec_with_layer_prefix(AXIS_TP)
        if any(k in path for k in self.ROW):
            if "bias" in path:  # row-parallel biases are replicated
                return P()
            if ndim >= 2:
                return spec_with_layer_prefix(AXIS_TP, None)
        return P()
