"""Execute a graph-searched Plan on a branching CNN (ResNet & friends).

Reference: the FlexFlow searcher's output is applied per-node to the real
graph (distributed_strategies/flexflow.py → executor NodeStatus); here the
searched per-node options become PartitionSpecs keyed by the GraphSpec's
node names, which `profiler.graph_ir.resnet_graph_spec` keeps aligned with
`models.resnet.ResNet` parameter paths.

Conv kernels are OIHW: 'tp_col' = output-channel split (dim 0), 'tp_row' =
input-channel split (dim 1; XLA inserts the partial-sum allreduce).  FC
weights are (in, out): 'tp_col' splits out (dim 1), 'tp_row' splits in
(dim 0).  Everything else (BN, biases) stays replicated — the indivisible
cases degrade to replication in Strategy._fit.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from hetu_tpu.parallel.mesh import AXIS_TP
from hetu_tpu.parallel.strategies.base import Strategy
from hetu_tpu.parallel.strategies.search import Plan


class GraphPlanStrategy(Strategy):
    """Adapt a `FlexFlowSearching.search_graph` Plan to parameter specs.

    The plan's meta['nodes'] gives the GraphSpec node names in option
    order; a node named 'layer0_1.conv2' governs the parameter at tree
    path "...['layer0_1']['conv2']['weight']"."""

    def __init__(self, plan: Plan, gspec=None):
        names = plan.meta.get("nodes")
        if names is None:
            if gspec is None:
                raise ValueError("plan lacks meta['nodes']; pass the "
                                 "GraphSpec it was searched on")
            names = [l.name for l in gspec.layers]
        if len(names) != len(plan.layer_options):
            raise ValueError("node-name/option count mismatch")
        self.node_opt = dict(zip(names, plan.layer_options))

    def _match(self, path: str):
        # node 'layer0_0.conv1' ↔ keystr "['layer0_0']['conv1']['weight']".
        # Anchor at the path START: the stem node 'conv1' must not shadow
        # every block's "...['conv1']..." parameter.
        for name, opt in self.node_opt.items():
            pat = "['" + name.replace(".", "']['") + "']"
            if path.startswith(pat):
                return opt
        return None

    def param_spec(self, path, leaf):
        ndim = leaf.ndim if hasattr(leaf, "ndim") else len(leaf.shape)
        opt = self._match(path)
        if opt is None or opt.tp <= 1 or "weight" not in path:
            return P()
        if ndim == 4:  # conv OIHW
            if opt.kind == "tp_col":
                return P(AXIS_TP, None, None, None)
            if opt.kind == "tp_row":
                return P(None, AXIS_TP, None, None)
        if ndim == 2:  # fc (in, out)
            if opt.kind == "tp_col":
                return P(None, AXIS_TP)
            if opt.kind == "tp_row":
                return P(AXIS_TP, None)
        return P()
