"""Auto-parallel searchers.

Reference: python/hetu/distributed_strategies/ — `FlexFlowSearching` MCMC
(flexflow.py:12), `OptCNNSearching` per-layer DP (optcnn.py:9),
`GPipeSearching` stage balancing (gpipe.py:6), `PipeDreamSearching` 2-level
planner (pipedream.py:7), `PipeOptSearching` PP x intra-stage hybrid
(pipeopt.py:9); all cost via HetuSimulator and emit JSON strategies
(base.py:158-227).  Galvatron's per-layer DP planner
(tools/Galvatron/csrc/dp_core.cpp:22) is the memory-budgeted variant.

All searchers here share the LayerSpec/ShardOption IR and Simulator from
hetu_tpu/profiler/simulator.py and return a `Plan` that serializes to JSON.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from hetu_tpu.profiler.simulator import LayerSpec, ShardOption, Simulator


@dataclass
class Plan:
    """Search result: per-layer option + pipeline split + predicted time."""

    layer_options: List[ShardOption]
    stage_bounds: List[int] = field(default_factory=list)  # layer idx per cut
    dp: int = 1
    n_microbatches: int = 1
    predicted_time: float = 0.0
    meta: dict = field(default_factory=dict)

    def to_json(self, layers: Sequence[LayerSpec]) -> str:
        return json.dumps({
            "layers": {l.name: {"kind": o.kind, "tp": o.tp,
                                "dp_type": o.dp_type}
                       for l, o in zip(layers, self.layer_options)},
            "stage_bounds": self.stage_bounds,
            "dp": self.dp,
            "n_microbatches": self.n_microbatches,
            "predicted_time": self.predicted_time,
            "meta": self.meta,
        }, indent=1)

    def save(self, path, layers):
        Path(path).write_text(self.to_json(layers))

    @staticmethod
    def load(path, layers: Sequence[LayerSpec]) -> "Plan":
        d = json.loads(Path(path).read_text())
        opts = [ShardOption(d["layers"][l.name]["kind"],
                            d["layers"][l.name]["tp"],
                            d["layers"][l.name].get("dp_type", "dp"))
                for l in layers]
        return Plan(opts, d["stage_bounds"], d["dp"], d["n_microbatches"],
                    d["predicted_time"], d.get("meta", {}))


class OptCNNSearching:
    """Exact per-layer DP over a chain graph (reference optcnn.py:9):
    state = (layer index, chosen option); edge cost = reshard time."""

    def __init__(self, sim: Simulator, dp: int = 1):
        self.sim = sim
        self.dp = dp

    def search(self, layers: Sequence[LayerSpec]) -> Plan:
        n = len(layers)
        # dp_cost[i][opt_idx] = best time of prefix ending with option
        INF = float("inf")
        best: List[Dict[int, Tuple[float, Optional[int]]]] = []
        for i, layer in enumerate(layers):
            cur: Dict[int, Tuple[float, Optional[int]]] = {}
            for oi, opt in enumerate(layer.options):
                lt = self.sim.layer_time(layer, opt, self.dp)
                if i == 0:
                    cur[oi] = (lt, None)
                    continue
                b = (INF, None)
                for pj, popt in enumerate(layers[i - 1].options):
                    prev_t = best[i - 1][pj][0]
                    rs = self.sim.reshard_time(popt, opt,
                                               layers[i - 1].act_bytes,
                                               self.dp)
                    t = prev_t + rs + lt
                    if t < b[0]:
                        b = (t, pj)
                cur[oi] = b
            best.append(cur)
        # backtrack
        end = min(best[-1].items(), key=lambda kv: kv[1][0])
        choice_idx = [0] * n
        choice_idx[n - 1] = end[0]
        t_total = end[1][0]
        for i in range(n - 1, 0, -1):
            choice_idx[i - 1] = best[i][choice_idx[i]][1]
        opts = [layers[i].options[choice_idx[i]] for i in range(n)]
        return Plan(opts, dp=self.dp, predicted_time=t_total,
                    meta={"searcher": "optcnn"})


class FlexFlowSearching:
    """MCMC over per-layer options (reference flexflow.py:12 delta-simulate
    + Metropolis acceptance)."""

    def __init__(self, sim: Simulator, dp: int = 1, *, iters: int = 2000,
                 temp: float = 0.05, seed: int = 0):
        self.sim = sim
        self.dp = dp
        self.iters = iters
        self.temp = temp
        self.rng = random.Random(seed)

    def search(self, layers: Sequence[LayerSpec]) -> Plan:
        cur = [self.rng.choice(l.options) for l in layers]
        cur_t = self.sim.chain_time(layers, cur, self.dp)
        best, best_t = list(cur), cur_t
        for _ in range(self.iters):
            i = self.rng.randrange(len(layers))
            if len(layers[i].options) <= 1:
                continue
            cand = list(cur)
            cand[i] = self.rng.choice(layers[i].options)
            t = self.sim.chain_time(layers, cand, self.dp)
            if t < cur_t or self.rng.random() < math.exp(
                    -(t - cur_t) / max(self.temp * cur_t, 1e-12)):
                cur, cur_t = cand, t
                if t < best_t:
                    best, best_t = list(cand), t
        return Plan(best, dp=self.dp, predicted_time=best_t,
                    meta={"searcher": "flexflow", "iters": self.iters})

    def search_graph(self, gspec) -> Plan:
        """Per-node MCMC over the real op DAG (reference flexflow.py:33
        mutates one node's (status, device-group) per step and
        delta-simulates).  Branch edges are priced, so a skip connection
        penalizes option flips that the chain IR ignored.  A final greedy
        coordinate-descent sweep polishes the MCMC result."""
        layers = gspec.layers
        cur = [l.options[0] for l in layers]
        cur_t = self.sim.graph_time(gspec, cur, self.dp)
        best, best_t = list(cur), cur_t
        for _ in range(self.iters):
            i = self.rng.randrange(len(layers))
            if len(layers[i].options) <= 1:
                continue
            cand = list(cur)
            cand[i] = self.rng.choice(layers[i].options)
            t = self.sim.graph_time(gspec, cand, self.dp)
            if t < cur_t or self.rng.random() < math.exp(
                    -(t - cur_t) / max(self.temp * cur_t, 1e-12)):
                cur, cur_t = cand, t
                if t < best_t:
                    best, best_t = list(cand), t
        # greedy polish: one full sweep of single-node improvements
        improved = True
        while improved:
            improved = False
            for i, layer in enumerate(layers):
                for o in layer.options:
                    if o.key() == best[i].key():
                        continue
                    cand = list(best)
                    cand[i] = o
                    t = self.sim.graph_time(gspec, cand, self.dp)
                    if t < best_t:
                        best, best_t = cand, t
                        improved = True
        return Plan(best, dp=self.dp, predicted_time=best_t,
                    meta={"searcher": "flexflow-graph",
                          "iters": self.iters,
                          "nodes": [l.name for l in layers]})


class GPipeSearching:
    """Balanced stage partitioning by DP minimizing sum of squared stage
    times (reference gpipe.py:6)."""

    def __init__(self, sim: Simulator, n_stages: int, dp: int = 1,
                 n_microbatches: int = 4):
        self.sim = sim
        self.n_stages = n_stages
        self.dp = dp
        self.M = n_microbatches

    def search(self, layers: Sequence[LayerSpec],
               options: Optional[Sequence[ShardOption]] = None) -> Plan:
        n = len(layers)
        S = self.n_stages
        opts = (list(options) if options is not None
                else [l.options[0] for l in layers])
        t = [self.sim.layer_time(l, o, self.dp) for l, o in zip(layers, opts)]
        prefix = [0.0]
        for x in t:
            prefix.append(prefix[-1] + x)

        INF = float("inf")
        # dp[s][i] = min cost splitting first i layers into s stages
        dp = [[INF] * (n + 1) for _ in range(S + 1)]
        cut = [[0] * (n + 1) for _ in range(S + 1)]
        dp[0][0] = 0.0
        for s in range(1, S + 1):
            for i in range(s, n + 1):
                for j in range(s - 1, i):
                    seg = prefix[i] - prefix[j]
                    c = dp[s - 1][j] + seg * seg
                    if c < dp[s][i]:
                        dp[s][i] = c
                        cut[s][i] = j
        bounds = []
        i = n
        for s in range(S, 0, -1):
            bounds.append(i)
            i = cut[s][i]
        bounds = sorted(set(bounds))
        stage_times = []
        lo = 0
        for b in bounds:
            stage_times.append(prefix[b] - prefix[lo])
            lo = b
        total = self.sim.pipeline_time(stage_times, self.M,
                                       layers[0].act_bytes)
        return Plan(opts, stage_bounds=bounds, dp=self.dp,
                    n_microbatches=self.M, predicted_time=total,
                    meta={"searcher": "gpipe",
                          "stage_times": stage_times})


class PipeDreamSearching(GPipeSearching):
    """PipeDream planner (reference pipedream.py:7): same stage partition
    and the SAME wall-clock price as GPipe — our 1F1B runtime is
    SPMD-lockstep, so the bubble is masked compute under either schedule
    and 1F1B's win is MEMORY (O(S) stashes vs O(M)), accounted in
    meta['stash_bytes'].  The async steady state the reference's
    pipedream_subexecutor approaches on independent devices is recorded as
    meta['ideal_1f1b_time'] (a lower bound), never used for ranking."""

    def search(self, layers, options=None) -> Plan:
        plan = super().search(layers, options)
        stage_times = plan.meta["stage_times"]
        # predicted_time stays the parent's gpipe price — schedule='1f1b'
        # is the identical lockstep formula (see Simulator.pipeline_time)
        plan.meta["ideal_1f1b_time"] = self.sim.pipeline_time(
            stage_times, self.M, layers[0].act_bytes,
            schedule="ideal_1f1b")
        plan.meta["searcher"] = "pipedream"
        # weight stashing: a stage holds up to (S - stage_idx) weight versions
        S = len(stage_times)
        lo = 0
        stash = []
        for si, b in enumerate(plan.stage_bounds):
            pb = sum(l.param_bytes for l in layers[lo:b])
            stash.append(pb * (S - si))
            lo = b
        plan.meta["stash_bytes"] = stash
        return plan


class PipeOptSearching:
    """Joint PP x (per-layer TP/DP) search (reference pipeopt.py:9): for
    each candidate stage count, run OptCNN within the chain, partition with
    GPipe DP, pick the best total."""

    def __init__(self, sim: Simulator, n_devices: int, *,
                 n_microbatches: int = 4):
        self.sim = sim
        self.n_devices = n_devices
        self.M = n_microbatches

    def search(self, layers: Sequence[LayerSpec]) -> Plan:
        best: Optional[Plan] = None
        S = 1
        while S <= self.n_devices:
            dp = self.n_devices // S
            inner = OptCNNSearching(self.sim, dp=dp).search(layers)
            if S == 1:
                cand = inner
                cand.meta["searcher"] = "pipeopt"
                cand.meta["pp"] = 1
            else:
                cand = GPipeSearching(self.sim, S, dp=dp,
                                      n_microbatches=self.M).search(
                    layers, inner.layer_options)
                cand.meta["searcher"] = "pipeopt"
                cand.meta["pp"] = S
            if best is None or cand.predicted_time < best.predicted_time:
                best = cand
            S *= 2
        return best


class GalvatronSearching:
    """Galvatron-style per-layer DP under a memory budget (reference
    tools/Galvatron/csrc/dp_core.cpp:22 dynamic_programming_core): each
    layer picks (option, remat flag); minimize time s.t. sum memory <=
    budget.  Memory is bucketed to keep the DP table small."""

    def __init__(self, sim: Simulator, dp: int = 1, *,
                 memory_budget_bytes: float, buckets: int = 64,
                 remat_overhead: float = 1.33):
        self.sim = sim
        self.dp = dp
        self.budget = memory_budget_bytes
        self.buckets = buckets
        self.remat_overhead = remat_overhead

    def search(self, layers: Sequence[LayerSpec]) -> Plan:
        # every layer consumes >=1 bucket, so the grid must be finer than
        # the layer count or deep models read as infeasible at any budget
        B = max(self.buckets, 4 * len(layers))
        unit = self.budget / B
        INF = float("inf")
        cur = {0: (0.0, [])}  # used_buckets -> (time, choices)
        dp_types = ("dp", "zero1", "sdp") if self.dp > 1 else ("dp",)
        for layer in layers:
            nxt: Dict[int, Tuple[float, List]] = {}
            for used, (t_acc, choices) in cur.items():
                for base_opt in layer.options:
                    for remat in (False, True):
                      for dpt in dp_types:
                        opt = ShardOption(base_opt.kind, base_opt.tp, dpt)
                        mem = self.sim.layer_memory(layer, opt, self.dp,
                                                    remat=remat)
                        nb = used + max(1, int(math.ceil(mem / unit)))
                        if nb > B:
                            continue
                        t = self.sim.layer_time(layer, opt, self.dp)
                        if remat:
                            t *= self.remat_overhead
                        cand = (t_acc + t, choices + [(opt, remat)])
                        if nb not in nxt or cand[0] < nxt[nb][0]:
                            nxt[nb] = cand
            # prune dominated states
            pruned: Dict[int, Tuple[float, List]] = {}
            best_t = INF
            for nb in sorted(nxt):
                if nxt[nb][0] < best_t:
                    pruned[nb] = nxt[nb]
                    best_t = nxt[nb][0]
            cur = pruned
            if not cur:
                raise ValueError("memory budget infeasible for every option")
        used, (t_total, choices) = min(cur.items(), key=lambda kv: kv[1][0])
        plan = Plan([c[0] for c in choices], dp=self.dp,
                    predicted_time=t_total,
                    meta={"searcher": "galvatron",
                          "remat": [c[1] for c in choices],
                          "dp_types": [c[0].dp_type for c in choices],
                          "memory_buckets_used": used,
                          "budget_bytes": self.budget})
        return plan
