"""Sharding specs — the NodeStatus algebra, TPU-native.

Reference: python/hetu/context.py `NodeStatus` (:248): a per-op sharding is
{dim→splits} + duplicate + partial + order; GraphStatus (:902) runs fixed-point
deduction over the graph and inserts collectives where producer/consumer specs
mismatch (cross_send/cross_receive :1640-1826).

TPU mapping:
  * {dim→splits}  → per-dim mesh-axis assignment (PartitionSpec)
  * duplicate     → axes not named (replication is the default in GSPMD)
  * partial       → value holds per-device partial sums pending a psum over
                    the listed axes (XLA: "unreduced"; we track it explicitly
                    and emit lax.psum / with_sharding_constraint)
  * order         → mesh axis ordering (mesh.py DEFAULT_AXIS_ORDER)

The deduction fixed-point largely dissolves into XLA's SPMD propagation; what
remains ours is the *planner* choosing annotation points and explicit
collectives (reduce vs allreduce vs reduce-scatter) — see
hetu_tpu/parallel/planner.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple, Union

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisName = str
DimSpec = Union[None, AxisName, Tuple[AxisName, ...]]


@dataclass(frozen=True)
class ShardSpec:
    """Sharding of one array: per-dim mesh axes + partial-sum axes."""

    dims: Tuple[DimSpec, ...]
    partial: Tuple[AxisName, ...] = ()

    # ---- constructors ----
    @staticmethod
    def replicated(ndim: int) -> "ShardSpec":
        return ShardSpec(dims=(None,) * ndim)

    @staticmethod
    def split(ndim: int, dim: int, axis: AxisName) -> "ShardSpec":
        dims = [None] * ndim
        dims[dim] = axis
        return ShardSpec(dims=tuple(dims))

    # ---- conversions ----
    def pspec(self) -> P:
        return P(*self.dims)

    def named(self, mesh: Mesh) -> NamedSharding:
        if self.partial:
            raise ValueError(
                "partial spec has no NamedSharding; reduce it first "
                "(reference analog: partial→allreduce in cross_receive)")
        return NamedSharding(mesh, self.pspec())

    # ---- the NodeStatus-style pattern checks (context.py:769-783) ----
    def check_allreduce(self, tgt: "ShardSpec") -> Optional[Tuple[AxisName, ...]]:
        """partial here, replicated there → psum over partial axes."""
        if self.partial and tgt.partial == () and tgt.dims == self.dims:
            return self.partial
        return None

    def check_reducescatter(self, tgt: "ShardSpec") -> Optional[Tuple[AxisName, int]]:
        """partial here, extra split there on some dim → reduce_scatter."""
        if not self.partial or tgt.partial:
            return None
        diff = [(i, a) for i, (a, b) in enumerate(zip(self.dims, tgt.dims))
                if a != b]
        if len(diff) == 1:
            i, _ = diff[0]
            if self.dims[i] is None and tgt.dims[i] in self.partial:
                return (tgt.dims[i], i)
        return None

    def check_allgather(self, tgt: "ShardSpec") -> Optional[Tuple[AxisName, int]]:
        """split here, replicated there on some dim → all_gather."""
        if self.partial or tgt.partial:
            return None
        diff = [(i, a, b) for i, (a, b) in enumerate(zip(self.dims, tgt.dims))
                if a != b]
        if len(diff) == 1:
            i, a, b = diff[0]
            if a is not None and b is None:
                return (a, i)
        return None

    def check_alltoall(self, tgt: "ShardSpec") -> Optional[Tuple[AxisName, int, int]]:
        """Split-dim migration — the SAME axis leaves dim i and lands on
        dim j (e.g. ``('tp', None)`` → ``(None, 'tp')``) → all_to_all.

        The reference's cross_send/cross_receive handles arbitrary
        re-splits (context.py:1640-1826); this is the common square case
        every sequence↔head-parallel transpose hits (Ulysses, MoE
        dispatch).  Earlier revisions classified it as free/local, which
        under-priced those plans (round-5 VERDICT).  Returns
        ``(axis, src_dim, dst_dim)``.
        """
        if self.partial or tgt.partial:
            return None
        diff = [(i, a, b) for i, (a, b) in enumerate(zip(self.dims, tgt.dims))
                if a != b]
        if len(diff) != 2:
            return None
        (i, a, b), (j, c, d) = diff
        if a is not None and b is None and c is None and d is not None \
                and a == d:
            return (a, i, j)   # axis migrates dim i → dim j
        if a is None and b is not None and c is not None and d is None \
                and b == c:
            return (b, j, i)   # axis migrates dim j → dim i
        return None

    def reduce_partial(self, x, mesh_axes=None):
        """Apply the pending psum (inside shard_map / collective contexts)."""
        y = x
        for ax in self.partial:
            y = lax.psum(y, ax)
        return y


# Name-parity alias: the reference calls this NodeStatus.
NodeStatus = ShardSpec


def predict_collective(src: ShardSpec, dst: ShardSpec):
    """Which collective a src→dst transition needs, by the NodeStatus
    pattern checks (context.py:769-783 check_allreduce/allgather + the
    reduce-scatter special case).

    Returns (kind, detail) with kind in {'all-reduce', 'reduce-scatter',
    'all-gather', 'all-to-all'} or None when the transition is local
    (slice/no-op).  The planner's audit asserts XLA's SPMD partitioner
    inserts exactly this collective — see
    parallel.planner.verify_spec_transition.
    """
    ar = src.check_allreduce(dst)
    if ar is not None:
        return ("all-reduce", ar)
    rs = src.check_reducescatter(dst)
    if rs is not None:
        return ("reduce-scatter", rs)
    ag = src.check_allgather(dst)
    if ag is not None:
        return ("all-gather", ag)
    a2a = src.check_alltoall(dst)
    if a2a is not None:
        return ("all-to-all", a2a)
    return None


def constrain(x, mesh: Mesh, spec: ShardSpec):
    """with_sharding_constraint under a spec — the annotation primitive the
    planner uses where the reference inserted comm ops."""
    return jax.lax.with_sharding_constraint(x, spec.named(mesh))
