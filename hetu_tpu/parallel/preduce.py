"""Partial-reduce DP training: straggler-tolerant dynamic-group averaging.

Reference: python/hetu/preduce.py (:8 PartialReduce) + ps-lite
preduce_handler — a worker asks the scheduler for this round's ready group,
then allreduces ONLY within that group (ncclAvg over a lazily-created
communicator for the member tuple).

TPU translation: one SPMD program cannot drop devices mid-step, but the
same semantics are a MASKED group mean inside shard_map over the dp axis:
every device computes its shard's gradient, members contribute to the
psum'd mean, non-members contribute zero (and receive the group mean, so
parameter state stays replicated-consistent — the reference's stragglers
simply skip pushing their stale grads).  The matchmaking is the host-side
PS service (hetu_tpu/ps/client.py PartialReduce); its member list becomes
this step's 0/1 mask.  Useful on multi-slice dp axes (DCN) where slice
speeds diverge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from jax.sharding import Mesh, PartitionSpec as P


def preduce_step_fn(loss_fn, optimizer, mesh: Mesh, *, axis: str = "dp"):
    """Build a DP train step whose gradient reduction averages only over the
    matched group (member_mask[i] == 1), the preduce/HetPipe DP mode.

    loss_fn(params, batch_shard) -> scalar loss for ONE dp shard.
    Returns step(params, opt_state, batch, member_mask) ->
    (params, opt_state, group_loss); batch dim 0 is sharded over `axis`,
    params replicated, member_mask [axis_size] of 0/1.
    """
    n = mesh.shape[axis]

    def local(params, batch, mask):
        i = lax.axis_index(axis)
        m = mask[i]
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        denom = jnp.maximum(lax.psum(m, axis), 1.0)
        grads = jax.tree_util.tree_map(
            lambda g: lax.psum(g * m, axis) / denom, grads)
        loss = lax.psum(loss * m, axis) / denom
        return loss, grads

    shmapped = shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(axis), P()),
        out_specs=(P(), P()),
        check_vma=False)

    def step(params, opt_state, batch, member_mask):
        mask = jnp.asarray(member_mask, jnp.float32)
        loss, grads = shmapped(params, batch, mask)
        # empty group = nobody pushed = NO update: stateful optimizers
        # (momentum decay, adam step) must not advance either
        new_params, new_opt = optimizer.update(grads, opt_state, params)
        has_members = jnp.sum(mask) > 0
        pick = lambda new, old: jax.tree_util.tree_map(
            lambda a, b: jnp.where(has_members, a, b), new, old)
        return pick(new_params, params), pick(new_opt, opt_state), loss

    return jax.jit(step, donate_argnums=(0, 1)), n
