"""Multi-stage compression training schedulers.

Reference: tools/EmbeddingMemoryCompression/methods/scheduler/
{base,compressor,multistage,switchinference}.py — training proceeds in
stages (e.g. dense warmup → prune schedule → frozen sparse finetune; or
full-precision train → quantized serving switch).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple


@dataclass
class Stage:
    name: str
    until_step: int                 # stage active while step < until_step
    on_enter: Optional[Callable] = None  # fn(variables) -> variables


class CompressionScheduler:
    """Drives stage transitions by step count (multistage.py analog).

    Example (DeepLight pruning):
        sched = CompressionScheduler([
            Stage("warmup", 1000),
            Stage("prune", 5000, on_enter=set_prune_rate(0.9)),
            Stage("finetune", 10000),
        ])
        variables = sched.maybe_transition(step, variables)
    """

    def __init__(self, stages: List[Stage]):
        assert stages and all(
            a.until_step < b.until_step for a, b in zip(stages, stages[1:]))
        self.stages = stages
        self._current = 0

    @property
    def current(self) -> Stage:
        return self.stages[self._current]

    def stage_at(self, step: int) -> int:
        for i, s in enumerate(self.stages):
            if step < s.until_step:
                return i
        return len(self.stages) - 1

    def maybe_transition(self, step: int, variables):
        """Advance stages; run on_enter hooks for each newly entered stage."""
        target = self.stage_at(step)
        while self._current < target:
            self._current += 1
            hook = self.stages[self._current].on_enter
            if hook is not None:
                variables = hook(variables)
        return variables


def prune_rate_setter(rate: float):
    """on_enter hook: set PrunedEmbedding's sparsity rate."""
    import jax.numpy as jnp

    def hook(variables):
        variables["state"]["rate"] = jnp.asarray(rate)
        return variables

    return hook


def switch_to_quantized(embedding_module, bits: int = 8):
    """on_enter hook: convert a dense table to int8 serving form
    (switchinference.py analog)."""
    from hetu_tpu.embedding_compress.layers import QuantizedEmbedding

    def hook(variables):
        q, scale = QuantizedEmbedding.from_table(variables["params"]["w"],
                                                 bits)
        return {"params": {}, "state": {"q": q, "scale": scale}}

    return hook
