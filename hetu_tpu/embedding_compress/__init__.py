from hetu_tpu.embedding_compress.layers import (
    HashEmbedding, CompositionalEmbedding, DPQEmbedding, MGQEEmbedding,
    TensorTrainEmbedding, DHEEmbedding, ROBEEmbedding, QuantizedEmbedding,
    ALPTEmbedding, PrunedEmbedding, PEPEmbedding, OptEmbedEmbedding,
    AutoSRHEmbedding, MixedDimEmbedding, AutoDimEmbedding, DedupEmbedding,
    AdaptiveEmbedding,
)
from hetu_tpu.embedding_compress.scheduler import CompressionScheduler
