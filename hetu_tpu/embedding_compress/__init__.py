from hetu_tpu.embedding_compress.layers import (
    HashEmbedding, CompositionalEmbedding, DPQEmbedding, MGQEEmbedding,
    TensorTrainEmbedding, DHEEmbedding, ROBEEmbedding, QuantizedEmbedding,
    ALPTEmbedding, PrunedEmbedding, PEPEmbedding, OptEmbedEmbedding,
    AutoSRHEmbedding, MixedDimEmbedding, AutoDimEmbedding, DedupEmbedding,
    AdaptiveEmbedding, SparseEmbedding, MaskedEmbedding,
    pep_to_retrain, autosrh_to_retrain, autodim_to_retrain,
    optembed_row_pruned,
)
from hetu_tpu.embedding_compress.scheduler import CompressionScheduler
from hetu_tpu.embedding_compress.recipes import (
    AutoDimBiLevelTrainer, MultiStageFlow, OptEmbedFlow, ServingRowCodec,
)
