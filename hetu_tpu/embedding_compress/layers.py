"""Embedding memory-compression method library.

Reference: tools/EmbeddingMemoryCompression (VLDB'24; 9,574 LoC) — 19
compression methods implemented as Hetu layers (methods/layers/*) plus
multi-stage training schedulers.  Each method here is a Module with the
Embedding contract: init(key) -> variables; apply(variables, indices) ->
([..., dim] rows, state).  Methods are grouped exactly like the reference:

  hashing        : HashEmbedding, CompositionalEmbedding (Q-R trick),
                   ROBEEmbedding, DHEEmbedding, DedupEmbedding
  quantization   : DPQEmbedding, MGQEEmbedding, QuantizedEmbedding,
                   ALPTEmbedding
  factorization  : TensorTrainEmbedding (TT-Rec)
  pruning        : PrunedEmbedding (DeepLight), PEPEmbedding,
                   OptEmbedEmbedding, AutoSRHEmbedding
  dim selection  : MixedDimEmbedding (MDE), AutoDimEmbedding,
                   AdaptiveEmbedding

TPU notes: every method keeps lookups as dense gathers + einsums (MXU/VPU
friendly, no host scatter), and compressed storage stays static-shaped so
the whole lookup fuses under jit.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.base import Module

_P1, _P2 = 1_000_000_007, 998_244_353  # universal-hash primes


def _hash(ids, salt: int, mod: int):
    h = (ids.astype(jnp.uint32) * jnp.uint32(2654435761)
         + jnp.uint32(salt * 40503 + 1))
    h = h ^ (h >> 16)
    return (h % jnp.uint32(mod)).astype(jnp.int32)


class HashEmbedding(Module):
    """Plain modulo-hash table (reference methods/layers/hash.py)."""

    def __init__(self, num_embeddings: int, dim: int, compress_ratio: float,
                 **kw):
        self.buckets = max(2, int(num_embeddings * compress_ratio))
        self.dim = dim
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        return {"params": {"table": self.w_init(key, (self.buckets, self.dim),
                                                jnp.float32)}, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        idx = _hash(ids, 0, self.buckets)
        return jnp.take(variables["params"]["table"], idx, axis=0), {}


class CompositionalEmbedding(Module):
    """Quotient-remainder compositional (reference compo.py): two small
    tables indexed by id//K and id%K, combined multiplicatively."""

    def __init__(self, num_embeddings: int, dim: int, *, combine: str = "mul",
                 **kw):
        self.K = max(2, int(math.isqrt(num_embeddings)) + 1)
        self.nq = (num_embeddings + self.K - 1) // self.K
        self.dim = dim
        self.combine = combine
        self.w_init = initializers.normal(stddev=0.05)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"params": {
            "q": self.w_init(k1, (self.nq, self.dim), jnp.float32),
            "r": self.w_init(k2, (self.K, self.dim), jnp.float32)},
            "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        eq = jnp.take(p["q"], ids // self.K, axis=0)
        er = jnp.take(p["r"], ids % self.K, axis=0)
        if self.combine == "mul":
            return eq * er, {}
        if self.combine == "add":
            return eq + er, {}
        return jnp.concatenate([eq, er], axis=-1), {}


class DPQEmbedding(Module):
    """Differentiable product quantization (reference dpq.py): ids map to
    per-subspace code logits; codebook rows are combined with softmax (soft,
    train) or argmax (hard, eval) with a straight-through estimator."""

    def __init__(self, num_embeddings: int, dim: int, *, n_codebooks: int = 4,
                 codes: int = 64, **kw):
        assert dim % n_codebooks == 0
        self.n, self.dim = num_embeddings, dim
        self.m = n_codebooks
        self.codes = codes
        self.sub = dim // n_codebooks
        self.w_init = initializers.normal(stddev=0.05)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"params": {
            "logits": self.w_init(k1, (self.n, self.m, self.codes),
                                  jnp.float32),
            "codebooks": self.w_init(k2, (self.m, self.codes, self.sub),
                                     jnp.float32)}, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        lg = jnp.take(p["logits"], ids.astype(jnp.int32), axis=0)  # [...,m,C]
        soft = jax.nn.softmax(lg, axis=-1)
        hard = jax.nn.one_hot(jnp.argmax(lg, axis=-1), self.codes)
        assign = soft + jax.lax.stop_gradient(hard - soft)  # straight-through
        out = jnp.einsum("...mc,mcs->...ms", assign, p["codebooks"])
        return out.reshape(*ids.shape, self.dim), {}

    def _serving_logits(self, p):
        """Logits used for serving argmax; subclasses apply their train-time
        masking here so serving picks the same codes as the hard path."""
        return p["logits"]

    def to_serving(self, variables):
        """Compress to the serving form: narrow-int codes [N, m] + codebooks —
        the actual memory win (logits are train-time only)."""
        p = variables["params"]
        # code ids range 0..codes-1, so int8 holds codes <= 128
        code_dtype = jnp.int8 if self.codes <= 128 else jnp.int16
        codes = jnp.argmax(self._serving_logits(p), axis=-1).astype(code_dtype)
        return {"params": {}, "state": {"codes": codes,
                                        "codebooks": p["codebooks"]}}

    def serving_lookup(self, serving_variables, ids):
        s = serving_variables["state"]
        codes = jnp.take(s["codes"], ids.astype(jnp.int32),
                         axis=0).astype(jnp.int32)         # [..., m]
        # gather per-subspace codebook rows: [..., m, sub]
        rows = s["codebooks"][jnp.arange(self.m), codes]
        return rows.reshape(*ids.shape, self.dim)


class MGQEEmbedding(DPQEmbedding):
    """Multi-granularity quantization (reference mgqe.py): frequent ids use
    the full code space, infrequent ids a subset — here via a per-id code
    budget mask derived from a frequency split."""

    def __init__(self, num_embeddings: int, dim: int, *, n_codebooks: int = 4,
                 codes: int = 64, hot_fraction: float = 0.1,
                 cold_codes: int = 16, **kw):
        super().__init__(num_embeddings, dim, n_codebooks=n_codebooks,
                         codes=codes)
        self.hot_cut = max(1, int(num_embeddings * hot_fraction))
        self.cold_codes = cold_codes

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        lg = jnp.take(p["logits"], ids, axis=0)
        # cold ids only address the first `cold_codes` codes
        is_hot = (ids < self.hot_cut)[..., None, None]
        code_ok = jnp.arange(self.codes) < self.cold_codes
        lg = jnp.where(is_hot | code_ok, lg, -1e30)
        soft = jax.nn.softmax(lg, axis=-1)
        hard = jax.nn.one_hot(jnp.argmax(lg, axis=-1), self.codes)
        assign = soft + jax.lax.stop_gradient(hard - soft)
        out = jnp.einsum("...mc,mcs->...ms", assign, p["codebooks"])
        return out.reshape(*ids.shape, self.dim), {}

    def _serving_logits(self, p):
        # same cold-id code mask as apply(): without it, argmax over the
        # untrained masked logit entries can emit codes >= cold_codes that the
        # model never used at train time
        is_hot = (jnp.arange(self.n) < self.hot_cut)[:, None, None]
        code_ok = jnp.arange(self.codes) < self.cold_codes
        return jnp.where(is_hot | code_ok, p["logits"], -1e30)


class TensorTrainEmbedding(Module):
    """TT-Rec factorization (reference tt.py): vocab = prod(i_k), dim =
    prod(j_k); cores G_k [r_{k-1}, i_k, j_k, r_k] contracted per lookup."""

    def __init__(self, num_embeddings: int, dim: int, *, ranks: int = 8,
                 factors: int = 3, **kw):
        self.n, self.dim = num_embeddings, dim
        self.i_facs = self._factorize(num_embeddings, factors)
        self.j_facs = self._factorize(dim, factors)
        self.ranks = [1] + [ranks] * (factors - 1) + [1]
        self.w_init = initializers.normal(stddev=0.3)

    @staticmethod
    def _factorize(n: int, k: int) -> list:
        base = max(2, int(round(n ** (1.0 / k))))
        facs = [base] * (k - 1)
        last = (n + int(jnp.prod(jnp.asarray(facs))) - 1) // int(
            jnp.prod(jnp.asarray(facs)))
        return facs + [max(last, 1)]

    def init(self, key):
        ks = jax.random.split(key, len(self.i_facs))
        cores = {}
        for k_i, (i_f, j_f) in enumerate(zip(self.i_facs, self.j_facs)):
            cores[f"core{k_i}"] = self.w_init(
                ks[k_i], (self.ranks[k_i], i_f, j_f, self.ranks[k_i + 1]),
                jnp.float32)
        return {"params": cores, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        orig_shape = ids.shape
        flat = ids.astype(jnp.int32).reshape(-1)
        # id → per-factor indices (mixed radix)
        rem = flat
        out = None
        for k_i, i_f in enumerate(self.i_facs):
            sub = rem % i_f
            rem = rem // i_f
            core = p[f"core{k_i}"][:, sub]           # [r_in, T, j, r_out]
            core = jnp.moveaxis(core, 1, 0)          # [T, r_in, j, r_out]
            if out is None:
                out = core[:, 0]                     # [T, j, r_out]
            else:
                # out [T, J, r_in] x core [T, r_in, j, r_out]
                out = jnp.einsum("tjr,trks->tjks", out, core)
                out = out.reshape(out.shape[0], -1, out.shape[-1])
        rows = out[..., 0][:, :self.dim]             # [T, dim]
        return rows.reshape(*orig_shape, self.dim), {}


class DHEEmbedding(Module):
    """Deep hash embedding (reference dhe.py): k universal hashes → dense
    feature vector → small MLP, no table at all."""

    def __init__(self, num_embeddings: int, dim: int, *, k_hashes: int = 32,
                 hidden: int = 64, **kw):
        self.k = k_hashes
        self.dim = dim
        self.hidden = hidden
        self.w_init = initializers.he_normal()

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"params": {
            "w1": self.w_init(k1, (self.k, self.hidden), jnp.float32),
            "b1": jnp.zeros((self.hidden,)),
            "w2": self.w_init(k2, (self.hidden, self.dim), jnp.float32),
            "b2": jnp.zeros((self.dim,))}, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        feats = jnp.stack(
            [_hash(ids, s, _P1).astype(jnp.float32) / _P1
             for s in range(self.k)], axis=-1)
        feats = (feats - 0.5) * 3.46  # ~unit variance
        h = ops.gelu(feats @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"], {}


class ROBEEmbedding(Module):
    """Random offset block embedding (reference robe.py): rows are chunks of
    one shared weight array addressed by hashed offsets."""

    def __init__(self, num_embeddings: int, dim: int, compress_ratio: float,
                 *, chunk: int = 8, **kw):
        self.size = max(dim, int(num_embeddings * dim * compress_ratio))
        self.dim = dim
        self.chunk = chunk
        self.n_chunks = (dim + chunk - 1) // chunk
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        return {"params": {"array": self.w_init(key, (self.size,),
                                                jnp.float32)}, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        arr = variables["params"]["array"]
        parts = []
        for c in range(self.n_chunks):
            off = _hash(ids, c + 1, max(self.size - self.chunk, 1))
            gather_idx = off[..., None] + jnp.arange(self.chunk)
            parts.append(jnp.take(arr, gather_idx, axis=0))
        rows = jnp.concatenate(parts, axis=-1)[..., :self.dim]
        return rows, {}


class QuantizedEmbedding(Module):
    """int8-storage embedding (reference quantize.py): rows stored quantized
    with a per-row scale; dequant fuses into the gather.  Non-differentiable
    storage — training updates flow through `assign` on the host/PS side, so
    this is the inference/serving form (like the reference's switchinference
    scheduler stage)."""

    def __init__(self, num_embeddings: int, dim: int, *, bits: int = 8, **kw):
        self.n, self.dim = num_embeddings, dim
        self.bits = bits

    def init(self, key):
        w = initializers.normal(stddev=0.01)(key, (self.n, self.dim),
                                             jnp.float32)
        qmax = 2 ** (self.bits - 1) - 1
        scale = jnp.maximum(jnp.max(jnp.abs(w), axis=1), 1e-8) / qmax
        q = jnp.clip(jnp.round(w / scale[:, None]), -qmax - 1,
                     qmax).astype(jnp.int8)
        return {"params": {}, "state": {"q": q, "scale": scale}}

    def apply(self, variables, ids, *, train=False, rng=None):
        s = variables["state"]
        rows = ops.quantize_embedding_lookup(s["q"], s["scale"],
                                             ids.astype(jnp.int32))
        return rows, s

    @staticmethod
    def from_table(table, bits: int = 8):
        qmax = 2 ** (bits - 1) - 1
        scale = jnp.maximum(jnp.max(jnp.abs(table), axis=1), 1e-8) / qmax
        q = jnp.clip(jnp.round(table / scale[:, None]), -qmax - 1,
                     qmax).astype(jnp.int8)
        return q, scale


class ALPTEmbedding(Module):
    """Adaptive low-precision training (reference alpt.py): int8 rows with a
    LEARNED per-row scale; forward dequantizes, backward flows to the scale
    and (via straight-through) the stored rows; stochastic rounding keeps
    the quantized update unbiased."""

    def __init__(self, num_embeddings: int, dim: int, *, bits: int = 8, **kw):
        self.n, self.dim, self.bits = num_embeddings, dim, bits
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        w = self.w_init(key, (self.n, self.dim), jnp.float32)
        return {"params": {"w": w,
                           "log_scale": jnp.full((self.n,), -5.0)},
                "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        rows = jnp.take(p["w"], ids, axis=0)
        scale = jnp.exp(jnp.take(p["log_scale"], ids, axis=0))[..., None]
        qmax = 2 ** (self.bits - 1) - 1
        scaled = rows / scale
        if train and rng is not None:
            noise = jax.random.uniform(rng, scaled.shape) - 0.5
            rounded = jnp.floor(scaled + 0.5 + noise)
        else:
            rounded = jnp.round(scaled)
        rounded = jnp.clip(rounded, -qmax - 1, qmax)
        # straight-through: forward uses quantized value, grad flows to w & scale
        deq = rounded * scale
        return scaled * scale + jax.lax.stop_gradient(deq - scaled * scale), {}


class PrunedEmbedding(Module):
    """DeepLight-style magnitude pruning (reference prune.py): a binary mask
    re-derived from |w| at a sparsity rate that follows a schedule."""

    def __init__(self, num_embeddings: int, dim: int, *, rate: float = 0.9,
                 **kw):
        self.n, self.dim, self.rate = num_embeddings, dim, rate
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        return {"params": {"w": self.w_init(key, (self.n, self.dim),
                                            jnp.float32)},
                "state": {"rate": jnp.asarray(self.rate)}}

    def apply(self, variables, ids, *, train=False, rng=None):
        w = variables["params"]["w"]
        rate = variables["state"]["rate"]
        rows = jnp.take(w, ids.astype(jnp.int32), axis=0)
        thresh = jnp.quantile(jnp.abs(rows), rate)
        return jnp.where(jnp.abs(rows) >= thresh, rows, 0.0), \
            variables["state"]


class PEPEmbedding(Module):
    """Plug-in embedding pruning (reference pep.py): learnable per-element
    soft thresholds g; w_eff = sign(w) * relu(|w| - sigmoid(g))."""

    def __init__(self, num_embeddings: int, dim: int, *,
                 init_threshold: float = -8.0, **kw):
        self.n, self.dim = num_embeddings, dim
        self.init_threshold = init_threshold
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        return {"params": {
            "w": self.w_init(key, (self.n, self.dim), jnp.float32),
            "g": jnp.full((self.n, self.dim), self.init_threshold)},
            "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        w = jnp.take(p["w"], ids, axis=0)
        g = jnp.take(p["g"], ids, axis=0)
        return jnp.sign(w) * jax.nn.relu(jnp.abs(w) - jax.nn.sigmoid(g)), {}


class OptEmbedEmbedding(Module):
    """OptEmbed (reference optembed.py): learnable per-row mask via binary
    step with straight-through gradient (gpu_ops/OptEmbedBinaryStep.py)."""

    def __init__(self, num_embeddings: int, dim: int, **kw):
        self.n, self.dim = num_embeddings, dim
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        # thresholds start low (softplus(-6) ~ 0) so every row begins
        # unmasked and gradients flow; training raises t to prune
        return {"params": {
            "w": self.w_init(key, (self.n, self.dim), jnp.float32),
            "t": jnp.full((self.n,), -6.0)}, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        w = jnp.take(p["w"], ids, axis=0)
        t = jnp.take(p["t"], ids, axis=0)
        score = jnp.linalg.norm(w, axis=-1) - jax.nn.softplus(t)
        hard = (score > 0).astype(w.dtype)
        soft = jax.nn.sigmoid(score * 10.0)
        mask = soft + jax.lax.stop_gradient(hard - soft)
        return w * mask[..., None], {}


class AutoSRHEmbedding(Module):
    """AutoSRH (reference autosrh.py): per-dimension relevance gates learned
    jointly, pruned by gate magnitude at deploy time."""

    def __init__(self, num_embeddings: int, dim: int, **kw):
        self.n, self.dim = num_embeddings, dim
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        return {"params": {
            "w": self.w_init(key, (self.n, self.dim), jnp.float32),
            "alpha": jnp.ones((self.n, self.dim))}, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        w = jnp.take(p["w"], ids, axis=0)
        a = jnp.take(p["alpha"], ids, axis=0)
        return w * a, {}


class MixedDimEmbedding(Module):
    """Mixed-dimension embedding (reference mde.py): frequency tiers get
    different native dims, projected up to `dim`."""

    def __init__(self, num_embeddings: int, dim: int, *,
                 tier_fractions: Sequence[float] = (0.1, 0.9),
                 tier_dims: Sequence[int] = None, **kw):
        self.n, self.dim = num_embeddings, dim
        tier_dims = tier_dims or [dim, max(2, dim // 4)]
        self.tiers = []
        start = 0
        for frac, d in zip(tier_fractions, tier_dims):
            cnt = max(1, int(num_embeddings * frac))
            self.tiers.append((start, min(start + cnt, num_embeddings), d))
            start += cnt
        if start < num_embeddings:  # remainder into last tier
            s, e, d = self.tiers[-1]
            self.tiers[-1] = (s, num_embeddings, d)
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        params = {}
        ks = jax.random.split(key, 2 * len(self.tiers))
        for i, (s, e, d) in enumerate(self.tiers):
            params[f"t{i}"] = self.w_init(ks[2 * i], (e - s, d), jnp.float32)
            if d != self.dim:
                params[f"p{i}"] = self.w_init(ks[2 * i + 1], (d, self.dim),
                                              jnp.float32)
        return {"params": params, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        out = jnp.zeros((*ids.shape, self.dim), jnp.float32)
        for i, (s, e, d) in enumerate(self.tiers):
            in_tier = (ids >= s) & (ids < e)
            local = jnp.clip(ids - s, 0, e - s - 1)
            rows = jnp.take(p[f"t{i}"], local, axis=0)
            if d != self.dim:
                rows = rows @ p[f"p{i}"]
            out = jnp.where(in_tier[..., None], rows, out)
        return out, {}


class AutoDimEmbedding(Module):
    """AutoDim (reference autodim.py): differentiable dim selection — every
    candidate dim has a sub-table + projection; a learned softmax picks."""

    def __init__(self, num_embeddings: int, dim: int, *,
                 candidate_dims: Sequence[int] = None, **kw):
        self.n, self.dim = num_embeddings, dim
        self.cands = list(candidate_dims or [dim, dim // 2, dim // 4])
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        params = {"arch": jnp.zeros((len(self.cands),))}
        ks = jax.random.split(key, 2 * len(self.cands))
        for i, d in enumerate(self.cands):
            params[f"t{i}"] = self.w_init(ks[2 * i], (self.n, d), jnp.float32)
            params[f"p{i}"] = self.w_init(ks[2 * i + 1], (d, self.dim),
                                          jnp.float32)
        return {"params": params, "state": {}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p = variables["params"]
        ids = ids.astype(jnp.int32)
        w = jax.nn.softmax(p["arch"])
        out = 0.0
        for i in range(len(self.cands)):
            out = out + w[i] * (jnp.take(p[f"t{i}"], ids, axis=0) @ p[f"p{i}"])
        return out, {}

    def selected_dim(self, variables) -> int:
        return self.cands[int(jnp.argmax(variables["params"]["arch"]))]


class DedupEmbedding(Module):
    """Dedup (reference dedup.py): an index-indirection array maps ids to
    shared physical rows (e.g. after near-duplicate clustering)."""

    def __init__(self, num_embeddings: int, dim: int, compress_ratio: float,
                 **kw):
        self.n = num_embeddings
        self.phys = max(2, int(num_embeddings * compress_ratio))
        self.dim = dim
        self.w_init = initializers.normal(stddev=0.01)

    def init(self, key):
        return {"params": {"table": self.w_init(key, (self.phys, self.dim),
                                                jnp.float32)},
                "state": {"remap": _hash(jnp.arange(self.n), 7, self.phys)}}

    def set_remap(self, variables, remap):
        variables["state"]["remap"] = jnp.asarray(remap, jnp.int32)
        return variables

    def apply(self, variables, ids, *, train=False, rng=None):
        remap = variables["state"]["remap"]
        phys_ids = jnp.take(remap, ids.astype(jnp.int32), axis=0)
        return jnp.take(variables["params"]["table"], phys_ids, axis=0), \
            variables["state"]


class SparseEmbedding(Module):
    """Sparse-format serving embedding (reference sparse.py: CSR inference
    after pruning).  TPU form: ELL (padded per-row nnz) so lookups stay
    static-shaped — values [N, max_nnz], cols [N, max_nnz] with -1 padding.
    """

    def __init__(self, num_embeddings: int, dim: int, *, max_nnz: int):
        self.n, self.dim, self.max_nnz = num_embeddings, dim, max_nnz

    @staticmethod
    def from_dense(table, max_nnz: int):
        """Convert a (pruned) dense table to ELL state (dense_to_sparse
        analog)."""
        table = jnp.asarray(table)
        n, dim = table.shape
        # top-|max_nnz| magnitudes per row keep the surviving entries
        mag = jnp.abs(table)
        _, cols = jax.lax.top_k(mag, max_nnz)                 # [N, max_nnz]
        vals = jnp.take_along_axis(table, cols, axis=1)
        keep = jnp.take_along_axis(mag, cols, axis=1) > 0
        cols = jnp.where(keep, cols, -1)
        vals = jnp.where(keep, vals, 0.0)
        return {"params": {}, "state": {"values": vals,
                                        "cols": cols.astype(jnp.int32)}}

    def init(self, key):  # serving-only: build via from_dense
        z = jnp.zeros((self.n, self.max_nnz))
        return {"params": {}, "state": {
            "values": z, "cols": jnp.full((self.n, self.max_nnz), -1,
                                          jnp.int32)}}

    def apply(self, variables, ids, *, train=False, rng=None):
        s = variables["state"]
        ids = ids.astype(jnp.int32)
        vals = jnp.take(s["values"], ids, axis=0)             # [..., max_nnz]
        cols = jnp.take(s["cols"], ids, axis=0)
        safe = jnp.where(cols >= 0, cols, 0)
        contrib = jnp.where(cols >= 0, vals, 0.0)
        # scatter the nnz entries into their dense positions
        one_hot = jax.nn.one_hot(safe, self.dim, dtype=vals.dtype)
        out = jnp.einsum("...k,...kd->...d", contrib, one_hot)
        return out, s


class MaskedEmbedding(Module):
    """Finetuning module for the retrain conversions: lookups apply the
    FROZEN sparsity mask in the forward pass, so masked positions produce
    zero output AND zero gradient — the pattern survives any number of
    optimizer steps (the reference's *Retrain modules do the same)."""

    def __init__(self, num_embeddings: int, dim: int):
        self.n, self.dim = num_embeddings, dim

    def init(self, key):  # build via a retrain converter
        return {"params": {"w": jnp.zeros((self.n, self.dim))},
                "state": {"mask": jnp.ones((self.n, self.dim))}}

    def apply(self, variables, ids, *, train=False, rng=None):
        p, s = variables["params"], variables["state"]
        ids = ids.astype(jnp.int32)
        w = jnp.take(p["w"], ids, axis=0)
        m = jnp.take(s["mask"], ids, axis=0)
        return w * m, s


def pep_to_retrain(pep_module: "PEPEmbedding", variables):
    """PEPRetrainEmbedding analog: freeze the learned sparsity pattern.
    Returns MaskedEmbedding variables (finetune through MaskedEmbedding so
    the mask is enforced in forward/backward)."""
    p = variables["params"]
    w, gthr = p["w"], jax.nn.sigmoid(p["g"])
    mask = (jnp.abs(w) > gthr).astype(w.dtype)
    return {"params": {"w": w * mask}, "state": {"mask": mask}}


def autosrh_to_retrain(module: "AutoSRHEmbedding", variables,
                       keep_fraction: float = 0.5):
    """AutoSrhRetrainEmbedding analog: prune dimension gates below the
    keep-fraction quantile.  Returns MaskedEmbedding variables."""
    p = variables["params"]
    a = jnp.abs(p["alpha"])
    thresh = jnp.quantile(a, 1.0 - keep_fraction)
    mask = (a >= thresh).astype(p["w"].dtype)
    # bake the learned gates in: the trained forward is w*alpha, so the
    # retrain weights must start from w*alpha (masked), not raw w
    return {"params": {"w": p["w"] * p["alpha"] * mask},
            "state": {"mask": mask}}


def autodim_to_retrain(module: "AutoDimEmbedding", variables):
    """AutoDimRetrainEmbedding analog: keep only the winning candidate dim's
    table + projection (winner chosen by the module's own selected_dim)."""
    dim = module.selected_dim(variables)
    best = module.cands.index(dim)
    p = variables["params"]
    return {"params": {"t": p[f"t{best}"], "p": p[f"p{best}"]},
            "state": {"dim": dim}}


def optembed_row_pruned(module: "OptEmbedEmbedding", variables):
    """OptEmbeddingAfterRowPruning analog: zero masked-off rows (compact
    remap happens host-side when materializing the smaller table)."""
    p = variables["params"]
    score = jnp.linalg.norm(p["w"], axis=-1) - jax.nn.softplus(p["t"])
    mask = (score > 0).astype(p["w"].dtype)
    return {"params": {"w": p["w"] * mask[:, None]},
            "state": {"row_mask": mask}}


class AdaptiveEmbedding(MixedDimEmbedding):
    """Adaptive embedding (reference adapt.py, Transformer-XL style): alias
    of the tiered mixed-dim scheme with geometric dim decay per tier."""

    def __init__(self, num_embeddings: int, dim: int, *, n_tiers: int = 3,
                 div: int = 4, **kw):
        fracs = []
        dims = []
        rem = 1.0
        for t in range(n_tiers):
            f = 0.1 * (4 ** t)
            f = min(f, rem)
            fracs.append(f)
            dims.append(max(2, dim // (div ** t)))
            rem -= f
        if rem > 0:
            fracs[-1] += rem
        super().__init__(num_embeddings, dim, tier_fractions=fracs,
                         tier_dims=dims)
