"""Per-method training flows for the compression zoo.

Reference: tools/EmbeddingMemoryCompression/methods/scheduler/ — beyond
the stage *machine* (scheduler.py here), the VLDB'24 tool ships per-method
TRAINING RECIPES: AutoDim's bi-level architecture search (autodim.py:13-180,
alternating arch-parameter steps on validation batches with weight steps on
train batches), and OptEmbed's three-stage flow (optembed.py:11-58:
supernet training with a threshold regularizer, evolutionary mask search,
masked retrain).  This module is those flows, TPU-first: each trainer
holds jitted pure steps (weights and arch/threshold parameters split into
separate optimizer trees — the reference splits its `train_op` list the
same way) and plain-python orchestration around them.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class MultiStageFlow:
    """Chain stage trainers, inheriting PARAMETERS but not optimizer state
    (reference multistage.py + optembed.py:12-13: "the parameters are
    inherited from the previous stage; but the optimizer states is new in
    every stage").

    ``stages``: ordered ``(name, fn)`` where ``fn(carry) -> carry``; the
    carry is whatever the stages agree on (typically the variables dict).
    ``run`` executes from ``start_stage`` (reference --stage resume flag).
    """

    def __init__(self, stages: Sequence[Tuple[str, Callable]]):
        if not stages:
            raise ValueError("MultiStageFlow needs at least one stage")
        self.stages = list(stages)
        self.history: List[str] = []

    def run(self, carry, *, start_stage: int = 0):
        self.history = []  # per-run record, not a cross-run accumulator
        for name, fn in self.stages[start_stage:]:
            carry = fn(carry)
            self.history.append(name)
        return carry


class AutoDimBiLevelTrainer:
    """AutoDim's bi-level search (reference autodim.py AutoDimTrainer):
    weights train on TRAIN batches, the architecture softmax trains on
    VALIDATION batches with its own learning rate — the first-order
    (`ignore_second`) DARTS approximation the reference defaults to; the
    second-order term costs an extra fwd+bwd pair per step for a
    correction that rarely changes the winner.

    loss_fn(params, batch) -> scalar must route embeddings through the
    AutoDimEmbedding whose params live under ``params[embed_key]`` with
    the ``arch`` leaf.
    """

    def __init__(self, embed_module, loss_fn, *, embed_key: str = "embed",
                 weight_opt=None, alpha_lr: float = 1e-3):
        from hetu_tpu import optim

        self.module = embed_module
        self.embed_key = embed_key
        self.weight_opt = weight_opt or optim.AdamOptimizer(1e-3)
        self.arch_opt = optim.AdamOptimizer(alpha_lr)
        self._loss_fn = loss_fn
        self._weight_step = jax.jit(self._make_weight_step())
        self._arch_step = jax.jit(self._make_arch_step())

    def _split(self, params):
        arch = params[self.embed_key]["arch"]
        return arch

    def _with_arch(self, params, arch):
        emb = dict(params[self.embed_key])
        emb["arch"] = arch
        out = dict(params)
        out[self.embed_key] = emb
        return out

    def _make_weight_step(self):
        def step(params, wstate, batch):
            arch = jax.lax.stop_gradient(self._split(params))

            def lf(p):
                return self._loss_fn(self._with_arch(p, arch), batch)

            loss, grads = jax.value_and_grad(lf)(params)
            # freeze arch in this half: its grad leaf is zeroed (it moves
            # only on validation batches, below)
            grads[self.embed_key]["arch"] = jnp.zeros_like(arch)
            params, wstate = self.weight_opt.update(grads, wstate, params)
            return params, wstate, loss
        return step

    def _make_arch_step(self):
        def step(params, astate, val_batch):
            def lf(arch):
                return self._loss_fn(self._with_arch(params, arch),
                                     val_batch)

            loss, g = jax.value_and_grad(lf)(self._split(params))
            arch, astate = self.arch_opt.update(g, astate,
                                                self._split(params))
            return self._with_arch(params, arch), astate, loss
        return step

    def init_states(self, params):
        return (self.weight_opt.init_state(params),
                self.arch_opt.init_state(self._split(params)))

    def fit(self, params, train_batches, val_batches, *,
            arch_every: int = 1):
        """Alternate weight/arch steps (reference first_stage_train_step
        interleaving).  Returns (params, train_losses, val_losses)."""
        wstate, astate = self.init_states(params)
        tl, vl = [], []
        vb = iter(val_batches)
        for i, batch in enumerate(train_batches):
            params, wstate, loss = self._weight_step(params, wstate, batch)
            tl.append(float(loss))
            if i % max(arch_every, 1) == 0:
                try:
                    val = next(vb)
                except StopIteration:
                    vb = iter(val_batches)
                    val = next(vb)
                params, astate, vloss = self._arch_step(params, astate, val)
                vl.append(float(vloss))
        return params, tl, vl

    def finalize(self, variables):
        """Winner-take-all retrain conversion (AutoDimRetrainEmbedding):
        keep only the selected candidate's table + projection."""
        from hetu_tpu.embedding_compress.layers import autodim_to_retrain
        return autodim_to_retrain(self.module, variables)


class OptEmbedFlow:
    """OptEmbed's three stages (reference optembed.py):

    1. ``supernet_step`` — train weights + per-row thresholds jointly;
       the loss carries the reference's ``alpha * sum(exp(-threshold))``
       regularizer and the thresholds get their OWN learning rate
       (reference splits threshold_update out of train_op and re-wraps it
       in a separate SGDOptimizer — here the param tree is split into two
       optimizer trees, same effect, no graph surgery).
    2. ``evolutionary_search`` — per-field dim-prefix masks evolve under
       mutation + crossover, ranked by a caller-supplied fitness
       (validation loss of the masked supernet).
    3. ``retrain`` setup via :func:`finalize` — row-pruned weights plus
       the winning field mask, parameters inherited, optimizer fresh.
    """

    def __init__(self, embed_module, loss_fn, *, embed_key: str = "embed",
                 weight_opt=None, thresh_lr: float = 1e-2,
                 alpha: float = 1e-4):
        from hetu_tpu import optim

        self.module = embed_module
        self.embed_key = embed_key
        self.alpha = alpha
        self.weight_opt = weight_opt or optim.AdamOptimizer(1e-3)
        self.thresh_opt = optim.SGDOptimizer(thresh_lr)
        self._loss_fn = loss_fn
        self._supernet_step = jax.jit(self._make_supernet_step())

    def _make_supernet_step(self):
        def step(params, wstate, tstate, batch):
            def lf(p):
                base = self._loss_fn(p, batch)
                reg = self.alpha * jnp.sum(
                    jnp.exp(-p[self.embed_key]["t"]))
                return base + reg

            loss, grads = jax.value_and_grad(lf)(params)
            tgrad = grads[self.embed_key]["t"]
            t = params[self.embed_key]["t"]
            # thresholds ride their own optimizer; zero their leaf in the
            # weight tree so the weight optimizer never touches them
            grads[self.embed_key]["t"] = jnp.zeros_like(tgrad)
            params, wstate = self.weight_opt.update(grads, wstate, params)
            new_t, tstate = self.thresh_opt.update(tgrad, tstate, t)
            emb = dict(params[self.embed_key])
            emb["t"] = new_t
            params = dict(params)
            params[self.embed_key] = emb
            return params, wstate, tstate, loss
        return step

    def train_supernet(self, params, batches):
        wstate = self.weight_opt.init_state(params)
        tstate = self.thresh_opt.init_state(params[self.embed_key]["t"])
        losses = []
        for batch in batches:
            params, wstate, tstate, loss = self._supernet_step(
                params, wstate, tstate, batch)
            losses.append(float(loss))
        return params, losses

    @staticmethod
    def evolutionary_search(fitness_fn, *, n_fields: int, dim: int,
                            population: int = 8, generations: int = 5,
                            parents: int = 4, mutate_prob: float = 0.2,
                            seed: int = 0):
        """Reference OptEmbedEvoTrainer: evolve per-field dim choices.

        A candidate assigns each field a kept-dim prefix in [1, dim];
        ``fitness_fn(cand) -> float`` (LOWER is better, e.g. validation
        loss).  Mutation redraws a field's dim; crossover takes fields
        from two parents.  Returns (best_candidate, best_fitness).
        """
        rng = np.random.default_rng(seed)
        pop = [rng.integers(1, dim + 1, n_fields) for _ in range(population)]
        best, best_fit = None, np.inf
        for _ in range(generations):
            scored = sorted(((float(fitness_fn(c)), c) for c in pop),
                            key=lambda t: t[0])
            if scored[0][0] < best_fit:
                best_fit, best = scored[0][0], scored[0][1].copy()
            keep = [c for _, c in scored[:parents]]
            children = []
            while len(children) < population - len(keep):
                pa, pb = rng.choice(len(keep), 2, replace=False)
                child = np.where(rng.random(n_fields) < 0.5,
                                 keep[pa], keep[pb])
                redraw = rng.random(n_fields) < mutate_prob
                child = np.where(redraw, rng.integers(1, dim + 1, n_fields),
                                 child)
                children.append(child)
            pop = keep + children
        return best, best_fit

    @staticmethod
    def field_mask(cand, dim: int) -> jnp.ndarray:
        """[n_fields, dim] 0/1 mask keeping each field's dim prefix."""
        return (jnp.arange(dim)[None, :] <
                jnp.asarray(cand)[:, None]).astype(jnp.float32)

    def finalize(self, variables, cand=None):
        """Stage-3 retrain variables: row-pruned weights (threshold mask
        baked), plus the evolutionary winner's per-field mask if given."""
        from hetu_tpu.embedding_compress.layers import optembed_row_pruned
        out = optembed_row_pruned(self.module, variables)
        if cand is not None:
            out["state"]["field_dims"] = jnp.asarray(cand)
        return out


class ServingRowCodec:
    """Lossy per-row compression for the SERVING cache's eviction tier.

    The training-time zoo above compresses the TABLE (hashing, TT, masks);
    online serving needs something different — rows evicted from the hot
    f32 tier of :class:`hetu_tpu.serve.recsys.ServingEmbeddingCache` kept
    around cheaply WITH their PS versions, so a re-access within the
    staleness bound decompresses locally instead of re-pulling the row
    (and a degraded cache still has something stale to serve).  Same
    trade as :class:`~hetu_tpu.embedding_compress.layers.QuantizedEmbedding`
    rows: int8 + one f32 scale per row, 4x smaller, ~1e-2 relative error.

    Stateless + vectorized: ``compress``/``decompress`` take/return
    ``[n, dim]`` f32 batches (the cache evicts and promotes per batch).
    """

    bytes_per_value = 1

    def __init__(self, dim: int):
        self.dim = int(dim)

    def compress(self, rows: np.ndarray) -> tuple:
        rows = np.ascontiguousarray(rows, np.float32).reshape(-1, self.dim)
        scale = np.abs(rows).max(axis=1) / 127.0
        q = np.where(scale[:, None] > 0.0,
                     np.round(rows / np.maximum(scale, 1e-30)[:, None]),
                     0.0).astype(np.int8)
        return q, scale.astype(np.float32)

    def decompress(self, blob: tuple) -> np.ndarray:
        q, scale = blob
        return q.astype(np.float32) * scale[:, None]
