"""Shared CTR building blocks: the hidden-MLP tower and the hybrid step.

One source of truth for what WideDeep / DeepFM / DCN all repeat: the deep
tower construction and the PS-hybrid train step (dense params updated
on-device, embedding-row gradients returned for the host push — reference
ParameterServerCommunicate flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hetu_tpu import layers, ops


def mlp_tower(in_dim: int, hidden, out_dim=None) -> layers.Sequential:
    """Linear+Relu stack, optional linear head (shared by the CTR zoo)."""
    mods = []
    prev = in_dim
    for h in hidden:
        mods += [layers.Linear(prev, h), layers.Relu()]
        prev = h
    if out_dim is not None:
        mods.append(layers.Linear(prev, out_dim))
    return layers.Sequential(*mods)


def make_hybrid_step(model, optimizer, n_sparse_inputs: int = 1):
    """Build the jitted hybrid train step for a CTR model whose apply is
    (variables, dense_x, *sparse_rows) -> logit [B].

    Returns step(params, opt_state, model_state, dense_x, *sparse_rows,
    labels) -> (params, opt_state, model_state, loss, logit,
    *sparse_row_grads).
    """

    def step(params, opt_state, model_state, dense_x, *rest):
        sparse_rows = rest[:n_sparse_inputs]
        labels = rest[n_sparse_inputs]

        def loss_fn(params, *sparse_rows):
            logit, new_state = model.apply(
                {"params": params, "state": model_state}, dense_x,
                *sparse_rows, train=True)
            loss = jnp.mean(
                ops.binary_cross_entropy_with_logits(logit, labels))
            return loss, (logit, new_state)

        argnums = tuple(range(1 + n_sparse_inputs))
        (loss, (logit, new_state)), grads = jax.value_and_grad(
            loss_fn, argnums=argnums, has_aux=True)(params, *sparse_rows)
        gp, ge = grads[0], grads[1:]
        params, opt_state = optimizer.update(gp, opt_state, params)
        return (params, opt_state, new_state, loss, logit, *ge)

    return jax.jit(step, donate_argnums=(0, 1))
