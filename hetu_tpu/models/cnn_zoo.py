"""Classic CNN zoo: LeNet and VGG.

Reference: examples/cnn/models/{lenet.py, vgg.py} (+ mlp.py, resnet.py
elsewhere in hetu_tpu.models).
"""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu import layers


def LeNet(num_classes: int = 10, in_channels: int = 1):
    """LeNet-5 for 32x32 inputs (pad MNIST to 32; reference lenet.py)."""
    return layers.Sequential(
        layers.Conv2d(in_channels, 6, 5, padding=2),
        layers.Relu(), layers.MaxPool2d(2, 2),
        layers.Conv2d(6, 16, 5),
        layers.Relu(), layers.MaxPool2d(2, 2),
        layers.Flatten(),
        layers.Linear(16 * 6 * 6, 120), layers.Relu(),
        layers.Linear(120, 84), layers.Relu(),
        layers.Linear(84, num_classes),
    )


_VGG_CFGS = {
    11: (1, 1, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


def VGG(depth: int = 16, num_classes: int = 10, in_channels: int = 3):
    """VGG-11/16/19 with BN for 32x32 inputs (reference vgg.py)."""
    cfg = _VGG_CFGS[depth]
    chans = (64, 128, 256, 512, 512)
    mods = []
    c_in = in_channels
    for n_convs, c_out in zip(cfg, chans):
        for _ in range(n_convs):
            mods += [layers.Conv2d(c_in, c_out, 3, padding=1, bias=False),
                     layers.BatchNorm(c_out), layers.Relu()]
            c_in = c_out
        mods.append(layers.MaxPool2d(2, 2))
    mods += [layers.Flatten(),
             layers.Linear(512, 512), layers.Relu(), layers.DropOut(0.5),
             layers.Linear(512, num_classes)]
    return layers.Sequential(*mods)
