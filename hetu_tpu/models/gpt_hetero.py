"""HeteroGPT: executes a searched per-layer parallelism Plan.

Reference: tools/Galvatron — the runtime half of the planner: each layer
gets its own TP degree / DP type from the searched JSON config
(core/hybrid_parallel_config.py) and activations are redistributed between
differently-parallelized layers (core/redistribute.py).

TPU form: per-layer (non-stacked) parameters so every layer can carry its
own PartitionSpec from a `strategies.search.Plan`; XLA's SPMD partitioner
inserts the activation resharding between layers (the redistribute.py
split/gather pairs) from the sharding mismatch.  `PlanStrategy` adapts a
Plan to the Executor's dist_strategy hook, so the full loop is:

    layers = transformer_layer_specs(...)          # cost IR
    plan = OptCNNSearching(sim, dp).search(layers) # search
    model = HeteroGPT(cfg)
    ex = Executor(model.lm_loss_fn(), opt, mesh=mesh,
                  dist_strategy=PlanStrategy(plan))

Pipeline plans (stage_bounds / meta['pp'] > 1) are NOT executable here —
PlanStrategy covers the intra-stage SPMD layout; pair it with the GPipe
executor for the pipeline dimension.
"""

from __future__ import annotations

from dataclasses import dataclass
import re

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.transformer import TransformerBlock
from hetu_tpu.models.gpt import GPTConfig, GPTModel
from hetu_tpu.parallel.strategies.base import Strategy
from hetu_tpu.parallel.strategies.search import Plan
from hetu_tpu.profiler.simulator import ShardOption


class HeteroGPT(GPTModel):
    """GPT with per-layer parameter trees (plan-shardable).

    Subclasses GPTModel: the loss (lm_loss_fn) is inherited — only the
    parameter layout (per-layer dicts instead of scan-stacked) and the
    layer loop differ.
    """

    def __init__(self, config: GPTConfig, *,
                 layer_remat: "tuple[bool, ...] | None" = None):
        """``layer_remat``: per-transformer-layer activation-checkpoint
        flags, normally taken from a searched Galvatron plan via
        :func:`plan_block_remat` (reference per-layer ckpt flag,
        tools/Galvatron/galvatron/core/hybrid_parallel_config.py:26-110).
        The searcher prices remat per layer; this executes it, so the
        memory the plan certified is the memory the compiled step uses."""
        super().__init__(config)
        if layer_remat is not None and len(layer_remat) != config.num_layers:
            raise ValueError(
                f"layer_remat has {len(layer_remat)} flags for "
                f"{config.num_layers} layers")
        self.layer_remat = layer_remat

    @classmethod
    def from_plan(cls, config: GPTConfig, plan: "Plan") -> "HeteroGPT":
        """The full Galvatron loop in one call: build the model with the
        plan's searched per-layer remat flags applied (pair with
        ``PlanStrategy(plan)`` on the Executor for the sharding half)."""
        return cls(config,
                   layer_remat=plan_block_remat(plan, config.num_layers))

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, c.num_layers + 3)
        params = {
            "tok_emb": self.w_init(ks[0], (c.vocab_size, c.hidden_size)),
            "pos_emb": self.w_init(ks[1], (c.max_position, c.hidden_size)),
            "ln_f_scale": jnp.ones((c.hidden_size,)),
            "ln_f_bias": jnp.zeros((c.hidden_size,)),
        }
        for i in range(c.num_layers):
            params[f"layer{i}"] = self.block.init(ks[2 + i])["params"]
        return {"params": params, "state": {}}

    def hidden_states(self, variables, input_ids, *, train: bool = False,
                      rng=None):
        p = variables["params"]
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(p["tok_emb"], input_ids)
        h = h + p["pos_emb"][None, :s]
        if train and c.dropout_rate > 0:  # same regularization as GPTModel
            h = ops.dropout(h, c.dropout_rate, jax.random.fold_in(rng, 999),
                            train=True)
        h = h.astype(c.dtype)
        for i in range(c.num_layers):
            lrng = None if rng is None else jax.random.fold_in(rng, i)

            def block_fn(lp, hh, lr, _train=train):
                return self.block.apply({"params": lp, "state": {}}, hh,
                                        train=_train, rng=lr)[0]

            if self.layer_remat is not None and self.layer_remat[i]:
                # execute the plan's per-layer ckpt flag: activations of
                # this layer are rematerialized in backward instead of held
                block_fn = jax.checkpoint(block_fn)
            h = block_fn(p[f"layer{i}"], h, lrng)
        return ops.layer_norm(h.astype(jnp.float32), p["ln_f_scale"],
                              p["ln_f_bias"])

    def apply(self, variables, input_ids, *, train: bool = False, rng=None):
        h = self.hidden_states(variables, input_ids, train=train, rng=rng)
        return ops.linear(h, variables["params"]["tok_emb"].T), {}


_LAYER_RE = re.compile(r"\['layer(\d+)'\]")


def plan_block_remat(plan: Plan, num_layers: int) -> "tuple[bool, ...]":
    """Fold a searched plan's per-LayerSpec remat flags into per-block
    flags for :class:`HeteroGPT`.

    The transformer_layer_specs chain is [embed, (attn_i, ffn_i)*, head];
    a block checkpoints when the searcher flagged EITHER of its halves
    (jax.checkpoint granularity is the block — the conservative rounding:
    never less remat than the plan's memory certificate assumed).
    Plans without remat metadata (non-Galvatron searchers) mean no remat.
    """
    flags = plan.meta.get("remat")
    if not flags:
        return tuple(False for _ in range(num_layers))
    body = flags[1:-1]
    if len(body) != 2 * num_layers:
        raise ValueError(
            f"plan has {len(body)} body remat flags for {num_layers} "
            "transformer layers (expected attn+ffn per layer)")
    return tuple(bool(body[2 * i] or body[2 * i + 1])
                 for i in range(num_layers))


def _add_dp_axis(spec: P, ndim: int) -> P:
    """Shard the first unsharded dim over 'dp' (FSDP/ZeRO param slicing).

    Combined with tp: e.g. qkv [H,3H] tp_col P(None,'tp') -> P('dp','tp');
    ffn_out [F,H] tp_row P('tp',None) -> P('tp','dp').  Dims that don't
    divide fall back to replication in Strategy._fit.
    """
    dims = list(spec) + [None] * (ndim - len(spec))
    for i, e in enumerate(dims):
        if e is None:
            dims[i] = "dp"
            return P(*dims)
    return spec  # every dim already sharded


class PlanStrategy(Strategy):
    """Adapt a searched Plan to per-layer PartitionSpecs.

    The Plan's layer_options are matched to HeteroGPT's transformer layers
    in order, skipping non-transformer entries (embed/head LayerSpecs).
    Layers whose option has tp > 1 get Megatron col/row splits; 'dp'
    layers stay replicated (grad-allreduce DP via the sharded batch).

    Per-layer dp_type executes Galvatron's DP-flavor axis
    (core/hybrid_parallel_config.py:26,70,76 / comm_groups.py:58-196):
      'sdp'   — params sharded over the dp mesh axis too (FSDP): XLA SPMD
                inserts the param allgathers and gradient reduce_scatters;
      'zero1' — params replicated but optimizer slots sharded over dp
                (slot_spec below): the slot update runs shard-wise and XLA
                allgathers the updated params.
    embed_sdp mirrors the reference's flag: apply sdp to the (untied
    position/token) embedding tables as well.
    """

    # Megatron split points by param name, shared across model families:
    # GPT blocks expose ffn_in/ffn_out, Llama blocks ffn_gate/ffn_up/
    # ffn_down (SwiGLU: both input mats col-split, down row-split)
    COL = ("qkv_weight", "qkv_bias")
    ROW = ("out_weight",)
    FFN_COL = ("ffn_in", "ffn_gate", "ffn_up")
    FFN_ROW = ("ffn_out", "ffn_down")

    def __init__(self, plan: Plan, *, embed_sdp: bool = False):
        if plan.stage_bounds or plan.meta.get("pp", 1) > 1:
            raise ValueError(
                "plan carries pipeline stages; PlanStrategy executes the "
                "intra-stage SPMD layout only — run the pipeline dimension "
                "with parallel.pipeline.GPipe")
        # the transformer_layer_specs chain is [embed, (attn_i, ffn_i)*,
        # head]; keep attn and ffn tp SEPARATE so the executed layout is
        # exactly what the searcher costed
        body = plan.layer_options[1:-1]
        self.block_opt = {}
        for li in range(len(body) // 2):
            self.block_opt[li] = (body[2 * li], body[2 * li + 1])
        # honor the searcher's dp_type choice for the embed/head LayerSpecs
        # too (the memory budget was certified WITH them): tok_emb is tied
        # to the head here, so either edge option requesting sharding wins
        edge = [plan.layer_options[0], plan.layer_options[-1]]
        self.embed_sdp = embed_sdp or any(
            getattr(o, "dp_type", "dp") == "sdp" for o in edge)
        self.embed_zero1 = any(
            getattr(o, "dp_type", "dp") == "zero1" for o in edge)

    def _layer_opt(self, path):
        m = _LAYER_RE.search(path)
        if not m:
            return None
        attn_opt, ffn_opt = self.block_opt.get(
            int(m.group(1)), (ShardOption("dp"), ShardOption("dp")))
        is_attn = "attn" in path or any(k in path for k in
                                        self.COL + self.ROW)
        return attn_opt if is_attn else ffn_opt

    def _tp_spec(self, path, ndim, tp):
        if tp <= 1:
            return P()
        if any(k in path for k in self.COL + self.FFN_COL):
            return P(*((None,) * (ndim - 1)), "tp")
        if "bias" not in path and any(k in path
                                      for k in self.ROW + self.FFN_ROW):
            if ndim >= 2:
                return P(*((None,) * (ndim - 2)), "tp", None)
        return P()

    # edge (non-transformer) params the embed/head dp_type options govern:
    # tied GPT embeddings and Llama's UNTIED lm_head — the searcher's
    # memory certificate assumes the head shards when its edge says so
    EDGE = ("tok_emb", "pos_emb", "lm_head")

    def param_spec(self, path, leaf):
        ndim = leaf.ndim if hasattr(leaf, "ndim") else len(leaf.shape)
        opt = self._layer_opt(path)
        if opt is None:
            if self.embed_sdp and any(k in path for k in self.EDGE):
                return _add_dp_axis(P(), ndim)
            return P()
        spec = self._tp_spec(path, ndim, opt.tp)
        if opt.dp_type == "sdp":
            spec = _add_dp_axis(spec, ndim)
        return spec

    def slot_spec(self, path, leaf):
        ndim = leaf.ndim if hasattr(leaf, "ndim") else len(leaf.shape)
        opt = self._layer_opt(path)
        if opt is None:
            if (self.embed_sdp or self.embed_zero1) and \
                    any(k in path for k in self.EDGE):
                return _add_dp_axis(P(), ndim)
            return self.param_spec(path, leaf)
        spec = self._tp_spec(path, ndim, opt.tp)
        if opt.dp_type in ("sdp", "zero1"):
            spec = _add_dp_axis(spec, ndim)
        return spec
