"""HeteroGPT: executes a searched per-layer parallelism Plan.

Reference: tools/Galvatron — the runtime half of the planner: each layer
gets its own TP degree / DP type from the searched JSON config
(core/hybrid_parallel_config.py) and activations are redistributed between
differently-parallelized layers (core/redistribute.py).

TPU form: per-layer (non-stacked) parameters so every layer can carry its
own PartitionSpec from a `strategies.search.Plan`; XLA's SPMD partitioner
inserts the activation resharding between layers (the redistribute.py
split/gather pairs) from the sharding mismatch.  `PlanStrategy` adapts a
Plan to the Executor's dist_strategy hook, so the full loop is:

    layers = transformer_layer_specs(...)          # cost IR
    plan = OptCNNSearching(sim, dp).search(layers) # search
    model = HeteroGPT(cfg)
    ex = Executor(model.lm_loss_fn(), opt, mesh=mesh,
                  dist_strategy=PlanStrategy(plan))

Pipeline plans (stage_bounds / meta['pp'] > 1) are NOT executable here —
PlanStrategy covers the intra-stage SPMD layout; pair it with the GPipe
executor for the pipeline dimension.
"""

from __future__ import annotations

from dataclasses import dataclass
import re

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.transformer import TransformerBlock
from hetu_tpu.models.gpt import GPTConfig, GPTModel
from hetu_tpu.parallel.strategies.base import Strategy
from hetu_tpu.parallel.strategies.search import Plan


class HeteroGPT(GPTModel):
    """GPT with per-layer parameter trees (plan-shardable).

    Subclasses GPTModel: the loss (lm_loss_fn) is inherited — only the
    parameter layout (per-layer dicts instead of scan-stacked) and the
    layer loop differ.
    """

    def __init__(self, config: GPTConfig):
        super().__init__(config)

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, c.num_layers + 3)
        params = {
            "tok_emb": self.w_init(ks[0], (c.vocab_size, c.hidden_size)),
            "pos_emb": self.w_init(ks[1], (c.max_position, c.hidden_size)),
            "ln_f_scale": jnp.ones((c.hidden_size,)),
            "ln_f_bias": jnp.zeros((c.hidden_size,)),
        }
        for i in range(c.num_layers):
            params[f"layer{i}"] = self.block.init(ks[2 + i])["params"]
        return {"params": params, "state": {}}

    def apply(self, variables, input_ids, *, train: bool = False, rng=None):
        p = variables["params"]
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(p["tok_emb"], input_ids)
        h = h + p["pos_emb"][None, :s]
        if train and c.dropout_rate > 0:  # same regularization as GPTModel
            h = ops.dropout(h, c.dropout_rate, jax.random.fold_in(rng, 999),
                            train=True)
        h = h.astype(c.dtype)
        for i in range(c.num_layers):
            h, _ = self.block.apply({"params": p[f"layer{i}"], "state": {}},
                                    h, train=train,
                                    rng=None if rng is None else
                                    jax.random.fold_in(rng, i))
        h = ops.layer_norm(h.astype(jnp.float32), p["ln_f_scale"],
                           p["ln_f_bias"])
        return ops.linear(h, p["tok_emb"].T), {}


_LAYER_RE = re.compile(r"\['layer(\d+)'\]")


class PlanStrategy(Strategy):
    """Adapt a searched Plan to per-layer PartitionSpecs.

    The Plan's layer_options are matched to HeteroGPT's transformer layers
    in order, skipping non-transformer entries (embed/head LayerSpecs).
    Layers whose option has tp > 1 get Megatron col/row splits; 'dp'
    layers stay replicated (grad-allreduce DP via the sharded batch).
    """

    COL = ("qkv_weight", "qkv_bias")
    ROW = ("out_weight",)

    def __init__(self, plan: Plan):
        if plan.stage_bounds or plan.meta.get("pp", 1) > 1:
            raise ValueError(
                "plan carries pipeline stages; PlanStrategy executes the "
                "intra-stage SPMD layout only — run the pipeline dimension "
                "with parallel.pipeline.GPipe")
        # the transformer_layer_specs chain is [embed, (attn_i, ffn_i)*,
        # head]; keep attn and ffn tp SEPARATE so the executed layout is
        # exactly what the searcher costed
        body = plan.layer_options[1:-1]
        self.block_tp = {}
        for li in range(len(body) // 2):
            attn, ffn = body[2 * li], body[2 * li + 1]
            self.block_tp[li] = (attn.tp, ffn.tp)

    def param_spec(self, path, leaf):
        m = _LAYER_RE.search(path)
        ndim = leaf.ndim if hasattr(leaf, "ndim") else len(leaf.shape)
        if not m:
            return P()
        attn_tp, ffn_tp = self.block_tp.get(int(m.group(1)), (1, 1))
        is_attn = "attn" in path or any(k in path for k in
                                        self.COL + self.ROW)
        tp = attn_tp if is_attn else ffn_tp
        if tp <= 1:
            return P()
        if any(k in path for k in self.COL) or "ffn_in" in path:
            return P(*((None,) * (ndim - 1)), "tp")
        if "bias" not in path and (any(k in path for k in self.ROW)
                                   or "ffn_out" in path):
            if ndim >= 2:
                return P(*((None,) * (ndim - 2)), "tp", None)
        return P()
