"""MLP (reference: examples/cnn/models/mlp.py)."""

from __future__ import annotations

import jax.numpy as jnp

from hetu_tpu import layers, ops


def MLP(in_features: int = 784, hidden: tuple = (256, 256),
        num_classes: int = 10, dropout: float = 0.0):
    mods = []
    prev = in_features
    for h in hidden:
        mods += [layers.Linear(prev, h), layers.Relu()]
        if dropout:
            mods.append(layers.DropOut(dropout))
        prev = h
    mods.append(layers.Linear(prev, num_classes))
    return layers.Sequential(*mods)
