"""ShardedGPT: the flagship fully-sharded training program.

One explicit-SPMD (shard_map, all axes manual) GPT-MoE that composes every
parallelism axis in a single jitted train step:

  dp — batch sharding, gradient psum (reference: AllReduce DP plane)
  pp — GPipe collective pipelining over the block stack with ppermute
       activation transfer (reference: pipeline_subexecutor / gpipe)
  sp — ring attention over the sequence axis (new capability; SURVEY §2.3)
  tp — Megatron tensor parallel: col-split QKV/FFN-in, row-split
       out-proj/FFN-out with explicit psum (reference:
       distributed_strategies/simple.py:174-283)
  ep — expert parallel MoE FFN with all_to_all dispatch (reference:
       layers/moe_layer.py + _ncclAllToAll)

Why fully manual: XLA's SPMD partitioner cannot infer a pipeline schedule,
and partial-manual shard_map in current JAX rejects auto-sharded residuals —
so the flagship writes every collective explicitly, Megatron-style.  Each
piece is unit-verified against its SPMD/unsharded oracle in
tests/test_sharded_gpt.py.

Constraints: layers %% pp == 0, heads %% tp == 0, seq %% sp == 0,
batch %% (dp * n_microbatches) == 0, experts %% ep == 0, ffn %% tp == 0.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.parallel.ring_attention import _ring_attention_local
from hetu_tpu.ops.moe_ops import (
    layout_transform, make_dispatch_combine, reverse_layout_transform,
    top_k_idx_gate,
)


@dataclass
class ShardedGPTConfig:
    vocab_size: int = 512
    hidden_size: int = 128
    num_layers: int = 4
    num_heads: int = 8
    ffn_size: int = 256
    num_experts: int = 4
    top_k: int = 2
    capacity_factor: float = 2.0
    max_position: int = 128
    n_microbatches: int = 2
    aux_weight: float = 1e-2
    dtype: object = jnp.float32
    vocab_parallel: bool = True   # Megatron vocab-split embedding + CE
    remat: bool = False           # rematerialize blocks (activation memory)


class ShardedGPT:
    def __init__(self, config: ShardedGPTConfig, mesh: Mesh):
        self.c = config
        self.mesh = mesh
        ax = mesh.shape
        self.dp, self.pp, self.sp, self.tp, self.ep = (
            ax.get("dp", 1), ax.get("pp", 1), ax.get("sp", 1),
            ax.get("tp", 1), ax.get("ep", 1))
        c = config
        assert c.num_layers % self.pp == 0
        assert c.num_heads % self.tp == 0
        assert c.ffn_size % self.tp == 0
        assert c.num_experts % self.ep == 0
        self.vocab_parallel = c.vocab_parallel and \
            c.vocab_size % self.tp == 0

    # ---- parameters ----
    def init(self, key):
        c = self.c
        D, F, E, L, V = (c.hidden_size, c.ffn_size, c.num_experts,
                         c.num_layers, c.vocab_size)
        wi = initializers.normal(stddev=0.02)
        hi = initializers.he_normal()
        ks = jax.random.split(key, 8)
        def stack(init_fn, shape, kk):
            return jax.vmap(lambda k: init_fn(k, shape, jnp.float32))(
                jax.random.split(kk, L))
        return {
            "tok_emb": wi(ks[0], (V, D), jnp.float32),
            "pos_emb": wi(ks[1], (c.max_position, D), jnp.float32),
            "blocks": {
                "ln1_scale": jnp.ones((L, D)), "ln1_bias": jnp.zeros((L, D)),
                "qkv_w": stack(wi, (D, 3 * D), ks[2]),
                "qkv_b": jnp.zeros((L, 3 * D)),
                "out_w": stack(wi, (D, D), ks[3]),
                "out_b": jnp.zeros((L, D)),
                "ln2_scale": jnp.ones((L, D)), "ln2_bias": jnp.zeros((L, D)),
                "gate_w": stack(wi, (D, E), ks[4]),
                "w1": stack(hi, (E, D, F), ks[5]),
                "b1": jnp.zeros((L, E, F)),
                "w2": stack(hi, (E, F, D), ks[6]),
                "b2": jnp.zeros((L, E, D)),
            },
            "ln_f_scale": jnp.ones((D,)), "ln_f_bias": jnp.zeros((D,)),
        }

    def param_specs(self):
        pp, tp, ep = "pp", "tp", "ep"
        return {
            # vocab-parallel: embedding rows split over tp (reference
            # MegatronLM vocab-parallel embedding + softmax-CE with partial,
            # distributed_strategies/simple.py:174-283)
            "tok_emb": P("tp") if self.vocab_parallel else P(),
            "pos_emb": P(),
            "blocks": {
                "ln1_scale": P(pp), "ln1_bias": P(pp),
                "qkv_w": P(pp, None, tp), "qkv_b": P(pp, tp),
                "out_w": P(pp, tp, None), "out_b": P(pp),
                "ln2_scale": P(pp), "ln2_bias": P(pp),
                "gate_w": P(pp),
                "w1": P(pp, ep, None, tp), "b1": P(pp, ep, tp),
                "w2": P(pp, ep, tp, None), "b2": P(pp, ep),
            },
            "ln_f_scale": P(), "ln_f_bias": P(),
        }

    def shardings(self):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.param_specs(),
            is_leaf=lambda x: isinstance(x, P))

    def place(self, params):
        return jax.tree_util.tree_map(jax.device_put, params,
                                      self.shardings())

    # ---- local (per-device) computation ----
    def _attention(self, p_l, h):
        """h: [mb, s_loc, D] replicated over tp. Megatron col/row split +
        ring attention over sp."""
        c = self.c
        mb, s_loc, D = h.shape
        H_loc = c.num_heads // self.tp
        hd = D // c.num_heads
        x = ops.layer_norm(h, p_l["ln1_scale"], p_l["ln1_bias"])
        qkv = x.astype(c.dtype) @ p_l["qkv_w"].astype(c.dtype) + p_l["qkv_b"]
        # fused-QKV layout is HEAD-major (H, 3, hd) so the tp column split
        # hands every rank whole (q,k,v) triples for its heads — the (3,H,hd)
        # layout would split "all of Q + half of K" to rank 0
        qkv = qkv.reshape(mb, s_loc, H_loc, 3, hd)
        q, k, v = (jnp.moveaxis(qkv[:, :, :, i], 1, 2) for i in range(3))
        o = _ring_attention_local(q, k, v, axis="sp", causal=True,
                                  scale=hd ** -0.5)
        o = jnp.moveaxis(o, 1, 2).reshape(mb, s_loc, H_loc * hd)
        y = o.astype(c.dtype) @ p_l["out_w"].astype(c.dtype)
        y = lax.psum(y, "tp") + p_l["out_b"]
        return h + y

    def _moe_ffn(self, p_l, h):
        """MoE FFN: a2a over ep, experts' F dim split over tp."""
        c = self.c
        mb, s_loc, D = h.shape
        E, ep = c.num_experts, self.ep
        E_loc = E // ep
        x = ops.layer_norm(h, p_l["ln2_scale"], p_l["ln2_bias"])
        tokens = x.reshape(-1, D)
        t = tokens.shape[0]
        C = max(1, int(c.capacity_factor * t * c.top_k / E))

        logits = tokens.astype(jnp.float32) @ p_l["gate_w"]
        gates, idx = top_k_idx_gate(logits, c.top_k)
        # load-balancing aux (GShard) — statistics over the GLOBAL batch so
        # the sharded loss is identical to the single-device one
        probs = jax.nn.softmax(logits, axis=-1)
        me = lax.pmean(jnp.mean(probs, axis=0), ("dp", "sp"))
        ce = lax.pmean(jnp.mean(jax.nn.one_hot(idx[:, 0], E), axis=0),
                       ("dp", "sp"))
        aux = c.aux_weight * E * jnp.sum(me * ce)

        disp, comb = make_dispatch_combine(gates, idx, E, C)
        xe = layout_transform(tokens, disp)                    # [E, C, D]
        # dispatch: every ep peer sends each expert its tokens
        xe = lax.all_to_all(xe, "ep", split_axis=0, concat_axis=1,
                            tiled=True)                        # [E_loc, ep*C, D]
        dt = c.dtype
        h1 = jnp.einsum("ecd,edf->ecf", xe.astype(dt),
                        p_l["w1"].astype(dt),
                        preferred_element_type=jnp.float32) + p_l["b1"][:, None]
        h1 = ops.gelu(h1)
        ye = jnp.einsum("ecf,efd->ecd", h1.astype(dt),
                        p_l["w2"].astype(dt),
                        preferred_element_type=jnp.float32)
        ye = lax.psum(ye, "tp") + p_l["b2"][:, None]           # F split → psum
        ye = lax.all_to_all(ye, "ep", split_axis=1, concat_axis=0,
                            tiled=True)                        # [E, C, D]
        out = reverse_layout_transform(ye, comb)
        return h + out.reshape(mb, s_loc, D), aux

    def _block(self, p_l, carry):
        h, aux = carry
        h = self._attention(p_l, h)
        h, a = self._moe_ffn(p_l, h)
        return h, aux + a

    def _local_step(self, params, ids, labels):
        """Local program on every device; all mesh axes manual.

        ids, labels: [b_loc, s_loc] (sharded dp x sp).
        Returns replicated scalar (loss, aux).
        """
        c = self.c
        M = c.n_microbatches
        pp_idx = lax.axis_index("pp")
        sp_idx = lax.axis_index("sp")
        n_pp = self.pp

        b_loc, s_loc = ids.shape
        assert b_loc % M == 0, (b_loc, M)
        mb = b_loc // M

        # embeddings (replicated over pp; each (dp,sp) shard embeds its slice)
        pos = sp_idx * s_loc + jnp.arange(s_loc)
        emb = params["tok_emb"]           # [V/tp, D] when vocab-parallel
        if self.vocab_parallel:
            tp_idx = lax.axis_index("tp")
            v_loc = emb.shape[0]
            rel = ids.astype(jnp.int32) - tp_idx * v_loc
            in_rng = (rel >= 0) & (rel < v_loc)
            h = jnp.take(emb, jnp.clip(rel, 0, v_loc - 1), axis=0)
            h = jnp.where(in_rng[..., None], h, 0.0)
            h = lax.psum(h, "tp")         # assemble full embedding
        else:
            h = ops.embedding_lookup(emb, ids)
        h = h + jnp.take(params["pos_emb"], pos, axis=0)[None]
        xs = h.reshape(M, mb, s_loc, c.hidden_size)

        blocks = params["blocks"]  # leaves [L/pp, ...]

        block = self._block
        if c.remat:
            block = jax.checkpoint(block)

        def stage_apply(h_mb):
            def body(carry, p_l):
                h, aux = block(p_l, carry)
                return (h, aux), None
            (h_out, aux), _ = lax.scan(body, (h_mb, jnp.asarray(0.0)), blocks)
            return h_out, aux

        T = M + n_pp - 1
        buf = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)
        aux_total = jnp.asarray(0.0)

        def tick(carry, tt):
            buf, outs, aux_total = carry
            h_in = jnp.where(pp_idx == 0, xs[jnp.clip(tt, 0, M - 1)], buf)
            h_out, aux = stage_apply(h_in)
            perm = [(j, (j + 1) % n_pp) for j in range(n_pp)]
            buf_next = lax.ppermute(h_out, "pp", perm)
            done = tt - (n_pp - 1)
            valid = (done >= 0) & (pp_idx == n_pp - 1)
            odx = jnp.clip(done, 0, M - 1)
            outs = outs.at[odx].set(jnp.where(valid, h_out, outs[odx]))
            in_flight = (tt >= pp_idx) & (tt - pp_idx < M)
            aux_total = aux_total + jnp.where(in_flight, aux, 0.0)
            return (buf_next, outs, aux_total), None

        (buf, outs, aux_total), _ = lax.scan(
            tick, (buf, outs, aux_total), jnp.arange(T))

        # head + loss on the last stage (tied weights)
        hs = outs.reshape(b_loc, s_loc, c.hidden_size).astype(jnp.float32)
        hs = ops.layer_norm(hs, params["ln_f_scale"], params["ln_f_bias"])
        if self.vocab_parallel:
            # vocab-parallel CE: each tp rank scores its vocab slice; the
            # softmax normalizer and target logit assemble via pmax/psum —
            # the [b, s, V] logits never materialize on one chip
            tp_idx = lax.axis_index("tp")
            v_loc = emb.shape[0]
            logits_loc = hs @ emb.T                      # [b, s, V/tp]
            # global max for stability via all_gather (pmax lacks an AD
            # rule); stop_gradient is exact — the max is stability-only
            m_loc = lax.stop_gradient(jnp.max(logits_loc, axis=-1))
            m = jnp.max(lax.all_gather(m_loc, "tp", axis=0), axis=0)
            se = jnp.sum(jnp.exp(logits_loc - m[..., None]), axis=-1)
            lse = jnp.log(lax.psum(se, "tp")) + m
            rell = labels.astype(jnp.int32) - tp_idx * v_loc
            in_rng = (rell >= 0) & (rell < v_loc)
            tgt_loc = jnp.take_along_axis(
                logits_loc, jnp.clip(rell, 0, v_loc - 1)[..., None],
                axis=-1)[..., 0]
            tgt = lax.psum(jnp.where(in_rng, tgt_loc, 0.0), "tp")
            per_tok = jnp.where(labels == -1, 0.0, lse - tgt)
        else:
            logits = hs @ params["tok_emb"].T
            per_tok = ops.softmax_cross_entropy_sparse(logits, labels,
                                                       ignored_index=-1)
        # global sum / global count (NOT mean-of-shard-ratios): keeps the
        # sharded loss bit-comparable to single-device
        num = lax.psum(jnp.sum(per_tok), ("dp", "sp"))
        den = lax.psum(jnp.sum(labels != -1), ("dp", "sp"))
        local_loss = num / jnp.maximum(den, 1)
        loss = jnp.where(pp_idx == n_pp - 1, local_loss, 0.0)
        loss = lax.psum(loss, "pp")          # broadcast from last stage
        # psum over pp sums DISTINCT layer groups (not replicas): no /pp
        aux_mean = lax.pmean(lax.psum(aux_total, "pp") / M, ("dp", "sp"))
        return loss + aux_mean, aux_mean

    # ---- public API ----
    def loss_fn(self):
        specs = self.param_specs()
        data_spec = P("dp", "sp")
        fn = shard_map(self._local_step, mesh=self.mesh,
                       in_specs=(specs, data_spec, data_spec),
                       out_specs=(P(), P()), check_vma=False)
        return fn

    def make_train_step(self, optimizer):
        loss = self.loss_fn()

        def step(params, opt_state, ids, labels):
            (l, aux), grads = jax.value_and_grad(
                lambda p: loss(p, ids, labels), has_aux=True)(params)
            params, opt_state = optimizer.update(grads, opt_state, params)
            return params, opt_state, {"loss": l, "aux_loss": aux}

        return jax.jit(step, donate_argnums=(0, 1))

    def data_sharding(self):
        return NamedSharding(self.mesh, P("dp", "sp"))
