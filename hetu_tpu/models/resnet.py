"""ResNet for CIFAR-scale inputs.

Reference: examples/cnn/models/resnet.py (resnet18/34 with BasicBlock on
CIFAR10) — the BASELINE.json config #1/#2 workload.

TPU notes: NCHW at the API (matching the reference); convs are bias-free with
BN (as in the reference), which XLA fuses into conv epilogues.  bf16-friendly:
pass dtype=jnp.bfloat16 to run the conv/matmul path in bf16 with f32 BN stats.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.base import Module, child_rng
from hetu_tpu.layers.linear import Conv2d, Linear
from hetu_tpu.layers.norm import BatchNorm


class BasicBlock(Module):
    expansion = 1

    def __init__(self, in_planes: int, planes: int, stride: int = 1,
                 dtype=jnp.float32):
        self.conv1 = Conv2d(in_planes, planes, 3, stride=stride, padding=1,
                            bias=False, dtype=dtype)
        self.bn1 = BatchNorm(planes)
        self.conv2 = Conv2d(planes, planes, 3, stride=1, padding=1,
                            bias=False, dtype=dtype)
        self.bn2 = BatchNorm(planes)
        self.downsample = None
        if stride != 1 or in_planes != planes * self.expansion:
            self.downsample = (
                Conv2d(in_planes, planes * self.expansion, 1, stride=stride,
                       bias=False, dtype=dtype),
                BatchNorm(planes * self.expansion))

    def init(self, key):
        ks = jax.random.split(key, 6)
        v = {"conv1": self.conv1.init(ks[0]), "bn1": self.bn1.init(ks[1]),
             "conv2": self.conv2.init(ks[2]), "bn2": self.bn2.init(ks[3])}
        if self.downsample is not None:
            v["ds_conv"] = self.downsample[0].init(ks[4])
            v["ds_bn"] = self.downsample[1].init(ks[5])
        return {"params": {k: x["params"] for k, x in v.items()},
                "state": {k: x["state"] for k, x in v.items()}}

    def apply(self, variables, x, *, train: bool = False, rng=None):
        p, s = variables["params"], variables["state"]
        ns = {}
        def sub(mod, name, h):
            out, st = mod.apply({"params": p[name], "state": s[name]}, h,
                                train=train)
            ns[name] = st
            return out
        out = sub(self.conv1, "conv1", x)
        out = ops.relu(sub(self.bn1, "bn1", out))
        out = sub(self.conv2, "conv2", out)
        out = sub(self.bn2, "bn2", out)
        if self.downsample is not None:
            sc = sub(self.downsample[0], "ds_conv", x)
            sc = sub(self.downsample[1], "ds_bn", sc)
        else:
            sc = x
        return ops.relu(out + sc), ns


class ResNet(Module):
    def __init__(self, block, num_blocks, num_classes: int = 10,
                 dtype=jnp.float32):
        self.dtype = dtype
        self.conv1 = Conv2d(3, 64, 3, stride=1, padding=1, bias=False,
                            dtype=dtype)
        self.bn1 = BatchNorm(64)
        self.in_planes = 64
        self.stages = []
        for planes, n, stride in ((64, num_blocks[0], 1),
                                  (128, num_blocks[1], 2),
                                  (256, num_blocks[2], 2),
                                  (512, num_blocks[3], 2)):
            blocks = []
            for i in range(n):
                blocks.append(block(self.in_planes, planes,
                                    stride if i == 0 else 1, dtype=dtype))
                self.in_planes = planes * block.expansion
            self.stages.append(blocks)
        self.fc = Linear(512 * block.expansion, num_classes, dtype=dtype)

    def init(self, key):
        params, state = {}, {}
        k0, k1, kf, kb = jax.random.split(key, 4)
        for name, mod, kk in (("conv1", self.conv1, k0), ("bn1", self.bn1, k1),
                              ("fc", self.fc, kf)):
            v = mod.init(kk)
            params[name], state[name] = v["params"], v["state"]
        for si, blocks in enumerate(self.stages):
            for bi, b in enumerate(blocks):
                v = b.init(jax.random.fold_in(kb, si * 100 + bi))
                params[f"layer{si}_{bi}"] = v["params"]
                state[f"layer{si}_{bi}"] = v["state"]
        return {"params": params, "state": state}

    def apply(self, variables, x, *, train: bool = False, rng=None):
        p, s = variables["params"], variables["state"]
        ns = {}
        x = x.astype(self.dtype)
        h, st = self.conv1.apply(
            {"params": p["conv1"], "state": s["conv1"]}, x, train=train)
        ns["conv1"] = st
        h, st = self.bn1.apply(
            {"params": p["bn1"], "state": s["bn1"]}, h, train=train)
        ns["bn1"] = st
        h = ops.relu(h)
        for si, blocks in enumerate(self.stages):
            for bi, b in enumerate(blocks):
                name = f"layer{si}_{bi}"
                h, st = b.apply({"params": p[name], "state": s[name]}, h,
                                train=train)
                ns[name] = st
        h = jnp.mean(h, axis=(2, 3))  # global average pool
        logits, _ = self.fc.apply(
            {"params": p["fc"], "state": s["fc"]}, h.astype(jnp.float32))
        ns["fc"] = {}
        return logits, ns

    def loss_fn(self):
        """Standard classification loss_fn for the Executor."""
        def fn(params, model_state, batch, rng, train):
            x, y = batch
            logits, new_state = self.apply(
                {"params": params, "state": model_state}, x, train=train,
                rng=rng)
            loss = jnp.mean(ops.softmax_cross_entropy_sparse(logits, y))
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            return loss, ({"acc": acc}, new_state)
        return fn


def ResNet18(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, dtype)


def ResNet34(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, dtype)
