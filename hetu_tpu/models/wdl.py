"""Wide & Deep for CTR.

Reference: examples/ctr/models/wdl.py (+ the PS/Hybrid launch scripts in
examples/ctr/tests/*.sh) — BASELINE.json config #4 workload.

Hybrid-parallel structure preserved from the reference: the (huge) sparse
embedding tables live on the parameter server (hetu_tpu/ps/PSEmbedding);
this module holds only the DENSE parameters, and its apply takes the pulled
embedding rows as an input so the jitted step returns d(loss)/d(rows) for
the host to push back (hetu_tpu/ps/embedding.py docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hetu_tpu import layers, ops
from hetu_tpu.layers.base import Module


class WideDeep(Module):
    def __init__(self, num_sparse_fields: int, emb_dim: int, dense_dim: int,
                 hidden=(256, 256)):
        from hetu_tpu.models.ctr_common import mlp_tower
        self.num_sparse_fields = num_sparse_fields
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.deep = mlp_tower(num_sparse_fields * emb_dim + dense_dim,
                              hidden, out_dim=1)
        self.wide = layers.Linear(dense_dim, 1)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        d = self.deep.init(k1)
        w = self.wide.init(k2)
        return {"params": {"deep": d["params"], "wide": w["params"]},
                "state": {"deep": d["state"], "wide": w["state"]}}

    def apply(self, variables, dense_x, emb_rows, *, train: bool = False,
              rng=None):
        """dense_x: [B, dense_dim]; emb_rows: [B, fields, emb_dim]."""
        p, s = variables["params"], variables["state"]
        flat = emb_rows.reshape(emb_rows.shape[0], -1)
        deep_in = jnp.concatenate([flat, dense_x], axis=-1)
        deep_out, ds = self.deep.apply({"params": p["deep"],
                                        "state": s["deep"]}, deep_in,
                                       train=train, rng=rng)
        wide_out, _ = self.wide.apply({"params": p["wide"],
                                       "state": s["wide"]}, dense_x)
        logit = (deep_out + wide_out)[:, 0]
        return logit, {"deep": ds, "wide": {}}

    def hybrid_step_fn(self, optimizer):
        """Jitted hybrid train step: updates dense params, returns embedding
        row grads for the PS push (the ParameterServerCommunicate analog;
        shared builder in ctr_common)."""
        from hetu_tpu.models.ctr_common import make_hybrid_step
        return make_hybrid_step(self, optimizer, n_sparse_inputs=1)


class WideDeepDevice(Module):
    """Device-resident Wide&Deep: the embedding table lives in HBM.

    The TPU-idiomatic counterpart of the reference's PS/Hybrid CTR configs
    for tables that FIT on-chip (Criteo-Kaggle's ~33M x 16 f32 is ~2.1 GB
    against 16 GB HBM on v5e): no host tier, the lookup runs the Pallas
    scalar-prefetch gather (``Embedding(impl='auto')``), and the update is
    sparse — row gradients become ``IndexedSlices`` applied only to touched
    rows (the reference's OptimizerOp *_sparse kernels), never a dense
    [V, D] gradient.  The PS classes remain the path for tables bigger
    than HBM.
    """

    def __init__(self, vocab_size: int, num_sparse_fields: int, emb_dim: int,
                 dense_dim: int, hidden=(256, 256), emb_impl: str = "auto"):
        from hetu_tpu import layers
        self.vocab_size = vocab_size
        self.emb = layers.Embedding(vocab_size, emb_dim, impl=emb_impl)
        self.dense_net = WideDeep(num_sparse_fields, emb_dim, dense_dim,
                                  hidden)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        d = self.dense_net.init(k1)
        e = self.emb.init(k2)
        return {"params": {"emb": e["params"], "net": d["params"]},
                "state": {"net": d["state"]}}

    def apply(self, variables, dense_x, sparse_ids, *, train: bool = False,
              rng=None):
        """dense_x [B, dense_dim]; sparse_ids [B, fields] int32 → logit [B]."""
        p, s = variables["params"], variables["state"]
        rows, _ = self.emb.apply({"params": p["emb"], "state": {}},
                                 sparse_ids)
        return self.dense_net.apply({"params": p["net"], "state": s["net"]},
                                    dense_x, rows, train=train, rng=rng)

    def sparse_step_fn(self, optimizer, *, jit: bool = True):
        """Jitted full train step with a SPARSE table update.

        Grads are taken wrt the gathered rows (not the table), converted to
        ``IndexedSlices``, and the optimizer's ``apply_indexed`` rule
        touches only those rows — step cost is O(B·fields·D), independent
        of vocab size.
        """
        from hetu_tpu.ops.embedding import IndexedSlices

        def step(params, opt_state, model_state, dense_x, sparse_ids,
                 labels):
            rows, _ = self.emb.apply(
                {"params": params["emb"], "state": {}}, sparse_ids)

            def loss_fn(net_params, rows):
                logit, new_state = self.dense_net.apply(
                    {"params": net_params, "state": model_state["net"]},
                    dense_x, rows, train=True)
                loss = jnp.mean(
                    ops.binary_cross_entropy_with_logits(logit, labels))
                return loss, (logit, new_state)

            (loss, (logit, new_state)), (g_net, g_rows) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(params["net"], rows)
            d = g_rows.shape[-1]
            g_emb = {"weight": IndexedSlices(
                sparse_ids.reshape(-1), g_rows.reshape(-1, d),
                (self.vocab_size, d))}
            new_params, opt_state = optimizer.update(
                {"emb": g_emb, "net": g_net}, opt_state, params)
            return (new_params, opt_state, {"net": new_state}, loss, logit)

        return jax.jit(step, donate_argnums=(0, 1)) if jit else step

    def masked_step_fn(self, optimizer, *, jit: bool = True):
        """Bucketed-padding train step (SURVEY §7 dynamic shapes).

        Same update as :meth:`sparse_step_fn` but takes ``n_valid``: rows at
        index >= n_valid are padding (dense zeros, ids == -1 per
        data/bucketing.py) — their loss terms are masked out and their id
        rows are dropped by the sparse optimizer (apply_indexed ignores
        negative indices), so a padded batch steps IDENTICALLY to the
        unpadded batch at its true size.  ``n_valid`` is traced (a scalar
        input, not a static arg), so one compiled program serves every
        occupancy of its bucket.
        """
        from hetu_tpu.ops.embedding import IndexedSlices

        def step(params, opt_state, model_state, dense_x, sparse_ids,
                 labels, n_valid):
            B = dense_x.shape[0]
            mask = (jnp.arange(B) < n_valid).astype(jnp.float32)
            safe_ids = jnp.where(sparse_ids >= 0, sparse_ids, 0)
            rows, _ = self.emb.apply(
                {"params": params["emb"], "state": {}}, safe_ids)

            def loss_fn(net_params, rows):
                logit, new_state = self.dense_net.apply(
                    {"params": net_params, "state": model_state["net"]},
                    dense_x, rows, train=True)
                per = ops.binary_cross_entropy_with_logits(logit, labels)
                loss = jnp.sum(per * mask) / jnp.maximum(n_valid, 1)
                return loss, (logit, new_state)

            (loss, (logit, new_state)), (g_net, g_rows) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(params["net"], rows)
            d = g_rows.shape[-1]
            g_emb = {"weight": IndexedSlices(
                sparse_ids.reshape(-1),  # padding keeps -1: dropped rows
                g_rows.reshape(-1, d), (self.vocab_size, d))}
            new_params, opt_state = optimizer.update(
                {"emb": g_emb, "net": g_net}, opt_state, params)
            return (new_params, opt_state, {"net": new_state}, loss, logit)

        return jax.jit(step, donate_argnums=(0, 1)) if jit else step
