"""Wide & Deep for CTR.

Reference: examples/ctr/models/wdl.py (+ the PS/Hybrid launch scripts in
examples/ctr/tests/*.sh) — BASELINE.json config #4 workload.

Hybrid-parallel structure preserved from the reference: the (huge) sparse
embedding tables live on the parameter server (hetu_tpu/ps/PSEmbedding);
this module holds only the DENSE parameters, and its apply takes the pulled
embedding rows as an input so the jitted step returns d(loss)/d(rows) for
the host to push back (hetu_tpu/ps/embedding.py docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hetu_tpu import layers, ops
from hetu_tpu.layers.base import Module


class WideDeep(Module):
    def __init__(self, num_sparse_fields: int, emb_dim: int, dense_dim: int,
                 hidden=(256, 256)):
        from hetu_tpu.models.ctr_common import mlp_tower
        self.num_sparse_fields = num_sparse_fields
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.deep = mlp_tower(num_sparse_fields * emb_dim + dense_dim,
                              hidden, out_dim=1)
        self.wide = layers.Linear(dense_dim, 1)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        d = self.deep.init(k1)
        w = self.wide.init(k2)
        return {"params": {"deep": d["params"], "wide": w["params"]},
                "state": {"deep": d["state"], "wide": w["state"]}}

    def apply(self, variables, dense_x, emb_rows, *, train: bool = False,
              rng=None):
        """dense_x: [B, dense_dim]; emb_rows: [B, fields, emb_dim]."""
        p, s = variables["params"], variables["state"]
        flat = emb_rows.reshape(emb_rows.shape[0], -1)
        deep_in = jnp.concatenate([flat, dense_x], axis=-1)
        deep_out, ds = self.deep.apply({"params": p["deep"],
                                        "state": s["deep"]}, deep_in,
                                       train=train, rng=rng)
        wide_out, _ = self.wide.apply({"params": p["wide"],
                                       "state": s["wide"]}, dense_x)
        logit = (deep_out + wide_out)[:, 0]
        return logit, {"deep": ds, "wide": {}}

    def hybrid_step_fn(self, optimizer):
        """Jitted hybrid train step: updates dense params, returns embedding
        row grads for the PS push (the ParameterServerCommunicate analog;
        shared builder in ctr_common)."""
        from hetu_tpu.models.ctr_common import make_hybrid_step
        return make_hybrid_step(self, optimizer, n_sparse_inputs=1)
