"""MoE transformer LM: every other FFN replaced by an MoE layer.

Reference: examples/moe (HetuMoE scripts, top-1/top-2 gating over 8-16 GPUs)
— here the experts shard over the 'ep' mesh axis and XLA inserts the A2A pair
(BASELINE.json config #5 workload).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.base import Module
from hetu_tpu.layers.attention import MultiHeadAttention
from hetu_tpu.layers.linear import Linear
from hetu_tpu.layers.norm import LayerNorm
from hetu_tpu.layers.moe import Expert, MoELayer, TopKGate


@dataclass
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 512
    num_layers: int = 4
    num_heads: int = 8
    ffn_size: int = 2048
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    max_position: int = 512
    dtype: object = jnp.float32


class MoETransformer(Module):
    def __init__(self, config: MoEConfig, *, mesh=None, ep_axis: str = "ep"):
        c = self.c = config
        self.attn = MultiHeadAttention(c.hidden_size, c.num_heads,
                                       causal=True, dtype=c.dtype)
        self.ln1 = LayerNorm(c.hidden_size)
        self.ln2 = LayerNorm(c.hidden_size)
        self.moe = MoELayer(
            TopKGate(c.hidden_size, c.num_experts, c.top_k),
            Expert(c.num_experts, c.hidden_size, c.ffn_size, dtype=c.dtype),
            capacity_factor=c.capacity_factor, mesh=mesh, ep_axis=ep_axis)
        self.w_init = initializers.normal(stddev=0.02)

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, 3 + c.num_layers * 4)
        params = {
            "tok_emb": self.w_init(ks[0], (c.vocab_size, c.hidden_size)),
            "pos_emb": self.w_init(ks[1], (c.max_position, c.hidden_size)),
        }
        for l in range(c.num_layers):
            base = 2 + l * 4
            params[f"layer{l}"] = {
                "attn": self.attn.init(ks[base])["params"],
                "ln1": self.ln1.init(ks[base + 1])["params"],
                "moe": self.moe.init(ks[base + 2])["params"],
                "ln2": self.ln2.init(ks[base + 3])["params"],
            }
        return {"params": params, "state": {}}

    def apply(self, variables, input_ids, *, train: bool = False, rng=None):
        p = variables["params"]
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(p["tok_emb"], input_ids)
        h = (h + p["pos_emb"][None, :s]).astype(c.dtype)
        total_aux = 0.0
        for l in range(c.num_layers):
            pl = p[f"layer{l}"]
            a, _ = self.attn.apply({"params": pl["attn"], "state": {}},
                                   ops.layer_norm(h, pl["ln1"]["scale"],
                                                  pl["ln1"]["bias"]),
                                   train=train,
                                   rng=None if rng is None else
                                   jax.random.fold_in(rng, l))
            h = h + a
            moe_in = ops.layer_norm(h, pl["ln2"]["scale"], pl["ln2"]["bias"])
            (m, aux), _ = self.moe.apply({"params": pl["moe"], "state": {}},
                                         moe_in, train=train)
            total_aux = total_aux + aux
            h = h + m.astype(c.dtype)
        logits = ops.linear(h.astype(jnp.float32), p["tok_emb"].T)
        return (logits, total_aux), {}

    def lm_loss_fn(self):
        def fn(params, model_state, batch, rng, train):
            ids = batch[0] if isinstance(batch, (tuple, list)) else batch
            (logits, aux), _ = self.apply({"params": params, "state": {}},
                                          ids, train=train, rng=rng)
            lm = jnp.mean(ops.softmax_cross_entropy_sparse(
                logits[:, :-1], ids[:, 1:]))
            return lm + aux, ({"lm_loss": lm, "aux_loss": aux}, model_state)
        return fn

    def param_specs(self, params):
        """EP sharding: expert-stacked weights split on dim 0 over 'ep'."""
        from jax.sharding import PartitionSpec as P

        def spec(path, leaf):
            if "experts" in path:
                return P("ep", *(None,) * (leaf.ndim - 1))
            return P()

        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        return jax.tree_util.tree_unflatten(
            treedef,
            [spec(jax.tree_util.keystr(pa), le) for pa, le in flat])
