"""GPT (decoder-only LM).

Reference: examples/nlp GPT-2 examples + tools/Galvatron gpt models
(hybrid-parallel flagship workload).  Pre-LN causal transformer with tied
LM head; scan-over-layers; Megatron-shardable weights.  This is the flagship
model for the multi-chip dry-run (tp/dp/pp/sp shardings).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.base import Module
from hetu_tpu.layers.transformer import TransformerBlock


@dataclass
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 1024
    dropout_rate: float = 0.1
    dtype: object = jnp.float32
    attention_impl: str = "xla"  # 'flash' = Pallas kernel (TPU)
    remat: bool = False  # recompute each layer in backward: O(L*S*H) residuals
    # instead of O(L*S^2) attention scores — the jax.checkpoint analog of the
    # reference's recompute/checkpoint knobs (Galvatron's ckpt flag)
    remat_policy: str = "full"  # 'full' = save only layer inputs;
    # 'dots' = also save matmul outputs (recompute elementwise only)
    fused_ce: bool = True  # lm_loss via ops.lm_head_cross_entropy: head
    # matmul fused into a chunked exact-LSE CE so [B*S, V] f32 logits never
    # materialize (the unfused path is the reference's
    # Linear→SoftmaxCrossEntropySparse composition)
    ce_row_chunk: int = 2048


class GPTModel(Module):
    def __init__(self, config: GPTConfig):
        self.c = config
        self.block = TransformerBlock(
            config.hidden_size, config.num_heads, config.ffn_size,
            dropout_rate=config.dropout_rate, causal=True, pre_norm=True,
            dtype=config.dtype, attention_impl=config.attention_impl)
        self.w_init = initializers.normal(stddev=0.02)

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, 4)
        block_keys = jax.random.split(ks[0], c.num_layers)
        blocks = jax.vmap(lambda k: self.block.init(k)["params"])(block_keys)
        params = {
            "tok_emb": self.w_init(ks[1], (c.vocab_size, c.hidden_size)),
            "pos_emb": self.w_init(ks[2], (c.max_position, c.hidden_size)),
            "blocks": blocks,
            "ln_f_scale": jnp.ones((c.hidden_size,)),
            "ln_f_bias": jnp.zeros((c.hidden_size,)),
        }
        return {"params": params, "state": {}}

    def hidden_states(self, variables, input_ids, *, train: bool = False,
                      rng=None):
        """Final pre-head hidden states ``[B, S, H]`` (post final LN)."""
        p = variables["params"]
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(p["tok_emb"], input_ids)
        h = h + p["pos_emb"][None, :s]
        if train and c.dropout_rate > 0:
            h = ops.dropout(h, c.dropout_rate, jax.random.fold_in(rng, 999),
                            train=True)
        h = h.astype(c.dtype)

        def layer(carry, xs):
            p_l, k_l = xs
            out, _ = self.block.apply({"params": p_l, "state": {}}, carry,
                                      train=train, rng=k_l)
            return out, None

        if c.remat:
            policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                      if c.remat_policy == "dots" else None)
            layer = jax.checkpoint(layer, policy=policy)
        keys = (jax.random.split(rng, c.num_layers) if rng is not None
                else jnp.zeros((c.num_layers, 2), jnp.uint32))
        h, _ = jax.lax.scan(layer, h, (p["blocks"], keys))
        return ops.layer_norm(h, p["ln_f_scale"], p["ln_f_bias"])

    def apply(self, variables, input_ids, *, train: bool = False, rng=None):
        """Returns (logits [B,S,V], {})."""
        p = variables["params"]
        c = self.c
        h = self.hidden_states(variables, input_ids, train=train, rng=rng)
        # tied LM head in the compute dtype: an f32 matmul would skip the
        # MXU bf16 path; CE upcasts to f32 for the reduction
        logits = ops.linear(h, p["tok_emb"].T.astype(c.dtype))
        return logits, {}

    # ---- serving (hetu_tpu/serve): KV-cache prefill / decode ----

    def prefill_with_cache(self, variables, input_ids, *, last_index=None):
        """Full-prompt forward that also returns every layer's K/V.

        input_ids: [B, S] (right-padded to the serving bucket; pad
        positions produce junk K/V that decode masks/overwrites).
        Returns (logits, k [L, B, S, nh, hd], v [L, B, S, nh, hd]) where
        logits is [B, S, V] — or [B, V] when ``last_index`` (the last real
        prompt position) is given, so serving skips the [S, V] head matmul
        for the S-1 positions whose logits it would throw away.
        """
        p = variables["params"]
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(p["tok_emb"], input_ids)
        h = (h + p["pos_emb"][None, :s]).astype(c.dtype)

        def layer(carry, p_l):
            out, k, v = self.block.prefill_step(
                {"params": p_l, "state": {}}, carry)
            return out, (k, v)

        h, (ks, vs) = jax.lax.scan(layer, h, p["blocks"])
        h = ops.layer_norm(h, p["ln_f_scale"], p["ln_f_bias"])
        if last_index is not None:
            h = jax.lax.dynamic_index_in_dim(h, last_index, axis=1,
                                             keepdims=False)  # [B, H]
        logits = ops.linear(h, p["tok_emb"].T.astype(c.dtype))
        return logits, ks, vs

    def prefill_chunk_with_cache(self, variables, input_ids, k_cache,
                                 v_cache, start, *, last_index=None):
        """Chunked prefill: forward ONE chunk of the prompt against a
        cache already holding everything before it (earlier chunks, or a
        shared prefix adopted from the prefix cache).

        input_ids: [B, S_c] at absolute positions ``start .. start+S_c-1``
        (right-padded within the chunk bucket; pad positions produce junk
        K/V that decode masks/overwrites).  k_cache/v_cache:
        [L, B, T, nh, hd] with positions ``< start`` already written.
        Returns (logits [B, V] at chunk-relative ``last_index``
        (default S_c - 1), new_k, new_v).  With start == 0 and one chunk
        covering the prompt, the numerics match
        :meth:`prefill_with_cache` token-for-token.
        """
        p = variables["params"]
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(p["tok_emb"], input_ids)
        # per-index gather (not dynamic_slice): a final chunk's PAD tail
        # may run past max_position, and slice-start clamping would shift
        # the REAL tokens' positions.  mode="clip" is load-bearing: the
        # default gather fills out-of-range rows with NaN, and a NaN pad
        # K/V row poisons real queries through 0 * NaN in the masked
        # attention product
        pos = jnp.take(p["pos_emb"], start + jnp.arange(s), axis=0,
                       mode="clip")
        h = (h + pos[None]).astype(c.dtype)
        starts = jnp.full((b,), start, jnp.int32)

        def layer(carry, xs):
            p_l, k_l, v_l = xs
            out, k_l, v_l = self.block.prefill_chunk_step(
                {"params": p_l, "state": {}}, carry, k_l, v_l, starts)
            return out, (k_l, v_l)

        h, (k_cache, v_cache) = jax.lax.scan(
            layer, h, (p["blocks"], k_cache, v_cache))
        h = ops.layer_norm(h, p["ln_f_scale"], p["ln_f_bias"])
        idx = s - 1 if last_index is None else last_index
        h = jax.lax.dynamic_index_in_dim(h, idx, axis=1, keepdims=False)
        logits = ops.linear(h, p["tok_emb"].T.astype(c.dtype))
        return logits, k_cache, v_cache

    def decode_with_cache(self, variables, input_ids, k_cache, v_cache,
                          lengths):
        """One decode step for a batch of cached sequences.

        input_ids: [B] int32 newest token per sequence; k_cache/v_cache:
        [L, B, T, nh, hd]; lengths: [B] int32 tokens already cached (the
        new token's position).  Returns (logits [B, V], new_k, new_v).
        """
        p = variables["params"]
        c = self.c
        h = ops.embedding_lookup(p["tok_emb"], input_ids[:, None])
        h = (h + p["pos_emb"][lengths][:, None]).astype(c.dtype)

        def layer(carry, xs):
            p_l, k_l, v_l = xs
            out, k_l, v_l = self.block.decode_step(
                {"params": p_l, "state": {}}, carry, k_l, v_l, lengths)
            return out, (k_l, v_l)

        h, (k_cache, v_cache) = jax.lax.scan(
            layer, h, (p["blocks"], k_cache, v_cache))
        h = ops.layer_norm(h, p["ln_f_scale"], p["ln_f_bias"])
        logits = ops.linear(h[:, 0], p["tok_emb"].T.astype(c.dtype))
        return logits, k_cache, v_cache

    def lm_loss_fn(self):
        """Next-token LM loss; batch = (input_ids,) or (input_ids, labels).

        With ``config.fused_ce`` the head matmul + CE run through
        ``ops.lm_head_cross_entropy`` (chunked exact-LSE; logits never
        materialize); otherwise the reference-shaped unfused composition.
        """
        def fn(params, model_state, batch, rng, train):
            ids = batch[0] if isinstance(batch, (tuple, list)) else batch
            c = self.c
            if c.fused_ce:
                h = self.hidden_states({"params": params, "state": {}}, ids,
                                       train=train, rng=rng)
                loss = ops.lm_head_cross_entropy(
                    h[:, :-1], params["tok_emb"], ids[:, 1:],
                    row_chunk=c.ce_row_chunk)
            else:
                logits, _ = self.apply({"params": params, "state": {}}, ids,
                                       train=train, rng=rng)
                per = ops.softmax_cross_entropy_sparse(
                    logits[:, :-1], ids[:, 1:])
                # normalize by non-ignored rows, matching the fused path
                # (identical when no label is ignored_index, as here)
                n_valid = jnp.sum(ids[:, 1:] != -1)
                loss = jnp.sum(per) / jnp.maximum(n_valid, 1)
            return loss, ({}, model_state)
        return fn


def gpt2_small(**kw) -> GPTModel:
    return GPTModel(GPTConfig(**kw))
