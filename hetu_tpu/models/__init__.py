"""Model zoo — the reference's examples/ reimplemented as framework models.

Reference: examples/cnn (ResNet/VGG/LeNet/MLP), examples/nlp (BERT),
examples/moe, examples/ctr (Wide&Deep etc.), tools/Galvatron (gpt/llama).
"""

from hetu_tpu.models.resnet import BasicBlock, ResNet, ResNet18, ResNet34
from hetu_tpu.models.mlp import MLP
from hetu_tpu.models.bert import BertConfig, BertModel, bert_base, bert_large
from hetu_tpu.models.gpt import GPTConfig, GPTModel, gpt2_small
from hetu_tpu.models.cnn_zoo import LeNet, VGG
from hetu_tpu.models.gcn import GCN
from hetu_tpu.models.wdl import WideDeep
from hetu_tpu.models.gpt_hetero import HeteroGPT, PlanStrategy
from hetu_tpu.models.ctr_zoo import DeepFM, DCN, CrossNet
from hetu_tpu.models.llama import (HeteroLlama, LlamaConfig, LlamaModel,
                                   llama2_7b)
