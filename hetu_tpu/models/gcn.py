"""GCN for node classification.

Reference: examples/gnn (GCN over GraphMix-sampled minibatches) +
gpu_ops/DistGCN_15d.py.  Full-graph training here; the distributed form
shards nodes over 'dp' with psum'd aggregations (see ops/graph_ops.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.base import Module
from hetu_tpu.ops.graph_ops import gcn_conv, gcn_norm


class GCN(Module):
    def __init__(self, in_features: int, hidden: int, num_classes: int,
                 *, dropout_rate: float = 0.5):
        self.dims = (in_features, hidden, num_classes)
        self.dropout_rate = dropout_rate
        self.w_init = initializers.xavier_uniform()

    def init(self, key):
        k1, k2 = jax.random.split(key)
        f, h, c = self.dims
        return {"params": {"w1": self.w_init(k1, (f, h), jnp.float32),
                           "w2": self.w_init(k2, (h, c), jnp.float32)},
                "state": {}}

    def apply(self, variables, x, edge_src, edge_dst, edge_weight, *,
              train: bool = False, rng=None):
        """x: [N, F]; normalized edges from ops.graph_ops.gcn_norm."""
        p = variables["params"]
        n = x.shape[0]
        h = gcn_conv(x, p["w1"], edge_src, edge_dst, edge_weight, n)
        h = ops.relu(h)
        if train and self.dropout_rate > 0:
            h = ops.dropout(h, self.dropout_rate, rng, train=True)
        return gcn_conv(h, p["w2"], edge_src, edge_dst, edge_weight, n), {}

    def loss_fn(self, edge_src, edge_dst, edge_weight):
        """Node-classification loss over a mask (semi-supervised setting)."""
        def fn(params, model_state, batch, rng, train):
            x, labels, mask = batch
            logits, _ = self.apply({"params": params, "state": {}}, x,
                                   edge_src, edge_dst, edge_weight,
                                   train=train, rng=rng)
            per = ops.softmax_cross_entropy_sparse(logits, labels)
            loss = jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1)
            acc = jnp.sum((jnp.argmax(logits, -1) == labels) * mask) / \
                jnp.maximum(jnp.sum(mask), 1)
            return loss, ({"acc": acc}, model_state)
        return fn
