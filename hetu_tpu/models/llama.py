"""Llama-family decoder LM: RMSNorm + SwiGLU + RoPE (+ GQA).

Reference: tools/Galvatron/galvatron/models/llama_hf — the second model
family the reference's hybrid-parallel trainer ships (gpt/llama/baichuan),
proving the planner is not GPT-shaped by accident.  Same role here:
:class:`HeteroLlama` executes a searched per-layer Plan (per-layer TP
degree, dp_type, remat) through the SAME ``PlanStrategy`` as HeteroGPT —
the strategy matches the Megatron split points by name (qkv/out for
attention, gate/up col + down row for SwiGLU).

TPU notes: pre-norm residual blocks scan-stack in :class:`LlamaModel`
(one compiled layer body); RoPE tables are computed once per forward and
hoisted out of the scan by XLA; GQA repeats kv heads with a reshape
(no gather).  The LM head is UNTIED (Llama convention) and runs through
the fused vocab-chunked CE so logits never materialize.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.base import Module


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int | None = None  # < num_heads = GQA; None = MHA
    ffn_size: int = 11008            # SwiGLU intermediate
    max_position: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    dtype: object = jnp.float32
    attention_impl: str = "xla"      # 'flash' = Pallas kernel (TPU)
    remat: bool = False
    fused_ce: bool = True
    ce_row_chunk: int = 2048

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.num_heads % self.num_kv_heads:
            raise ValueError(
                f"num_heads {self.num_heads} must be a multiple of "
                f"num_kv_heads {self.num_kv_heads}")
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"num_heads {self.num_heads} must divide hidden_size "
                f"{self.hidden_size}")


class LlamaBlock(Module):
    """Pre-RMSNorm residual block: RoPE attention + SwiGLU MLP.

    Megatron-shardable layout (what PlanStrategy keys on): ``qkv_weight``
    [H, (nh+2*nkv)*hd] and ``ffn_gate``/``ffn_up`` [H, F] are col-split
    points; ``out_weight`` [H, H] and ``ffn_down`` [F, H] row-split points.
    No biases anywhere (Llama convention).
    """

    def __init__(self, c: LlamaConfig):
        self.c = c
        self.head_dim = c.hidden_size // c.num_heads
        self.w_init = initializers.xavier_uniform()

    def init(self, key):
        c = self.c
        kq, ko, kg, ku, kd = jax.random.split(key, 5)
        hd, nh, nkv = self.head_dim, c.num_heads, c.num_kv_heads
        return {"params": {
            "attn": {
                "qkv_weight": self.w_init(
                    kq, (c.hidden_size, (nh + 2 * nkv) * hd), jnp.float32),
                "out_weight": self.w_init(
                    ko, (c.hidden_size, c.hidden_size), jnp.float32),
            },
            "rms1_scale": jnp.ones((c.hidden_size,)),
            "rms2_scale": jnp.ones((c.hidden_size,)),
            "ffn_gate": self.w_init(kg, (c.hidden_size, c.ffn_size),
                                    jnp.float32),
            "ffn_up": self.w_init(ku, (c.hidden_size, c.ffn_size),
                                  jnp.float32),
            "ffn_down": self.w_init(kd, (c.ffn_size, c.hidden_size),
                                    jnp.float32),
        }, "state": {}}

    def _attention_with_kv(self, p, x, cos, sin):
        """Shared causal-attention body; also returns the chunk's rotated
        un-repeated K/V in cache layout [B, S, nkv, hd] so training
        (:meth:`apply`) and serving prefill stay ONE code path — the
        decode-parity guarantee rides on them never drifting."""
        c = self.c
        b, s, h = x.shape
        nh, nkv = c.num_heads, c.num_kv_heads
        q, k, v = self._qkv(p, x)
        q, k, v = (jnp.moveaxis(t, 1, 2) for t in (q, k, v))  # [B,h,S,D]
        q = ops.apply_rope(q, cos, sin)
        k = ops.apply_rope(k, cos, sin)
        kr, vr = k, v
        if nkv != nh:  # GQA: each kv head serves num_heads/nkv query heads
            rep = nh // nkv
            kr = jnp.repeat(k, rep, axis=1)
            vr = jnp.repeat(v, rep, axis=1)
        if c.attention_impl == "flash":
            from hetu_tpu.ops.pallas_kernels import flash_attention
            out = flash_attention(q, kr, vr, causal=True)
        else:
            out = ops.causal_attention(q, kr, vr)
        out = jnp.moveaxis(out, 1, 2).reshape(b, s, h)
        a = ops.linear(out.astype(c.dtype),
                       p["out_weight"].astype(c.dtype))
        return a, jnp.moveaxis(k, 1, 2), jnp.moveaxis(v, 1, 2)

    def _attention(self, p, x, cos, sin):
        return self._attention_with_kv(p, x, cos, sin)[0]

    def apply(self, variables, x, cos, sin):
        p = variables["params"]
        c = self.c
        a = self._attention(p["attn"],
                            ops.rms_norm(x, p["rms1_scale"], eps=c.rms_eps),
                            cos, sin)
        x = x + a
        return self._mlp(p, x), {}

    def _mlp(self, p, x):
        c = self.c
        hn = ops.rms_norm(x, p["rms2_scale"], eps=c.rms_eps)
        gate = ops.linear(hn, p["ffn_gate"].astype(c.dtype))
        up = ops.linear(hn, p["ffn_up"].astype(c.dtype))
        down = ops.linear(ops.silu(gate) * up,
                          p["ffn_down"].astype(c.dtype))
        return x + down

    # ---- serving (hetu_tpu/serve): KV-cache prefill / decode ----
    # The cache stores ROTATED k (RoPE applied at write time, the standard
    # serving layout) and the nkv un-repeated GQA heads; decode_attention
    # repeats at read time.

    def _qkv(self, pa, x):
        c = self.c
        b, s, _ = x.shape
        hd, nh, nkv = self.head_dim, c.num_heads, c.num_kv_heads
        qkv = ops.linear(x, pa["qkv_weight"].astype(c.dtype))
        q = qkv[..., :nh * hd].reshape(b, s, nh, hd)
        k = qkv[..., nh * hd:(nh + nkv) * hd].reshape(b, s, nkv, hd)
        v = qkv[..., (nh + nkv) * hd:].reshape(b, s, nkv, hd)
        return q, k, v

    def prefill_step(self, variables, x, cos, sin):
        """cos/sin: [S, hd/2] chunk tables (prefill starts at position 0).
        x [B,S,H] → (out [B,S,H], k [B,S,nkv,hd] rotated, v [B,S,nkv,hd]).
        """
        p = variables["params"]
        a, k, v = self._attention_with_kv(
            p["attn"], ops.rms_norm(x, p["rms1_scale"], eps=self.c.rms_eps),
            cos, sin)
        return self._mlp(p, x + a), k, v

    def prefill_chunk_step(self, variables, x, k_cache, v_cache, starts,
                           cos, sin):
        """Chunked prefill; cos/sin are FULL tables [T_max, hd/2] gathered
        at each token's absolute position (``starts[b] + i``).
        x [B,S_c,H]; caches [B,T,nkv,hd] holding everything before the
        chunk.  Returns (out, new_k, new_v)."""
        p = variables["params"]
        c = self.c
        b, s, _ = x.shape
        hn = ops.rms_norm(x, p["rms1_scale"], eps=c.rms_eps)
        q, k, v = self._qkv(p["attn"], hn)
        q = ops.apply_rope_at(jnp.moveaxis(q, 1, 2), cos, sin, starts)
        k = ops.apply_rope_at(jnp.moveaxis(k, 1, 2), cos, sin, starts)
        k_cache, v_cache = ops.cache_update(
            k_cache, v_cache, jnp.moveaxis(k, 1, 2), v, starts)
        out = ops.chunk_attention(q, k_cache, v_cache, starts)
        out = jnp.moveaxis(out, 1, 2).reshape(b, s, c.hidden_size)
        a = ops.linear(out.astype(c.dtype),
                       p["attn"]["out_weight"].astype(c.dtype))
        return self._mlp(p, x + a), k_cache, v_cache

    def decode_step(self, variables, x, k_cache, v_cache, lengths,
                    cos, sin):
        """One-token decode; cos/sin are FULL tables [T_max, hd/2] gathered
        at each sequence's position.  x [B,1,H]; caches [B,T,nkv,hd];
        lengths [B] = tokens already cached.  Returns (out, new_k, new_v).
        """
        p = variables["params"]
        c = self.c
        b = x.shape[0]
        hn = ops.rms_norm(x, p["rms1_scale"], eps=c.rms_eps)
        q, k, v = self._qkv(p["attn"], hn)
        q = ops.apply_rope_at(jnp.moveaxis(q, 1, 2), cos, sin, lengths)
        k = ops.apply_rope_at(jnp.moveaxis(k, 1, 2), cos, sin, lengths)
        k_cache, v_cache = ops.cache_update(
            k_cache, v_cache, jnp.moveaxis(k, 1, 2), v, lengths)
        out = ops.decode_attention(q, k_cache, v_cache, lengths)
        out = jnp.moveaxis(out, 1, 2).reshape(b, 1, c.hidden_size)
        a = ops.linear(out.astype(c.dtype),
                       p["attn"]["out_weight"].astype(c.dtype))
        return self._mlp(p, x + a), k_cache, v_cache


class LlamaModel(Module):
    """Scan-stacked Llama (homogeneous layers, one compiled body)."""

    def __init__(self, config: LlamaConfig):
        self.c = config
        self.block = LlamaBlock(config)
        self.w_init = initializers.normal(stddev=0.02)

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, 3)
        block_keys = jax.random.split(ks[0], c.num_layers)
        blocks = jax.vmap(lambda k: self.block.init(k)["params"])(block_keys)
        return {"params": {
            "tok_emb": self.w_init(ks[1], (c.vocab_size, c.hidden_size)),
            "lm_head": self.w_init(ks[2], (c.vocab_size, c.hidden_size)),
            "blocks": blocks,
            "rms_f_scale": jnp.ones((c.hidden_size,)),
        }, "state": {}}

    def _tables(self, s):
        c = self.c
        return ops.rope_tables(s, c.hidden_size // c.num_heads,
                               theta=c.rope_theta)

    def hidden_states(self, variables, input_ids, *, train: bool = False,
                      rng=None):
        p = variables["params"]
        c = self.c
        h = ops.embedding_lookup(p["tok_emb"], input_ids).astype(c.dtype)
        cos, sin = self._tables(input_ids.shape[1])

        def layer(carry, p_l):
            out, _ = self.block.apply({"params": p_l, "state": {}}, carry,
                                      cos, sin)
            return out, None

        if c.remat:
            layer = jax.checkpoint(layer)
        h, _ = jax.lax.scan(layer, h, p["blocks"])
        return ops.rms_norm(h, p["rms_f_scale"], eps=c.rms_eps)

    def apply(self, variables, input_ids, *, train: bool = False, rng=None):
        h = self.hidden_states(variables, input_ids, train=train, rng=rng)
        logits = ops.linear(
            h, variables["params"]["lm_head"].T.astype(self.c.dtype))
        return logits, {}

    # ---- serving (hetu_tpu/serve): KV-cache prefill / decode ----

    def prefill_with_cache(self, variables, input_ids, *, last_index=None):
        """Full-prompt forward returning per-layer rotated K/V.

        input_ids: [B, S] → (logits, k [L, B, S, nkv, hd],
        v [L, B, S, nkv, hd]); logits is [B, S, V], or [B, V] when
        ``last_index`` names the last real prompt position (serving skips
        the head matmul for the padded tail)."""
        p = variables["params"]
        c = self.c
        h = ops.embedding_lookup(p["tok_emb"], input_ids).astype(c.dtype)
        cos, sin = self._tables(input_ids.shape[1])

        def layer(carry, p_l):
            out, k, v = self.block.prefill_step(
                {"params": p_l, "state": {}}, carry, cos, sin)
            return out, (k, v)

        h, (ks, vs) = jax.lax.scan(layer, h, p["blocks"])
        h = ops.rms_norm(h, p["rms_f_scale"], eps=c.rms_eps)
        if last_index is not None:
            h = jax.lax.dynamic_index_in_dim(h, last_index, axis=1,
                                             keepdims=False)  # [B, H]
        logits = ops.linear(h, p["lm_head"].T.astype(c.dtype))
        return logits, ks, vs

    def prefill_chunk_with_cache(self, variables, input_ids, k_cache,
                                 v_cache, start, *, last_index=None):
        """Chunked prefill (see GPTModel.prefill_chunk_with_cache):
        input_ids [B, S_c] at absolute positions ``start..start+S_c-1``,
        caches [L, B, T, nkv, hd] with positions < start written.
        Returns (logits [B, V] at chunk-relative ``last_index``, new_k,
        new_v)."""
        p = variables["params"]
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(p["tok_emb"], input_ids).astype(c.dtype)
        # full tables, gathered per token at its absolute position
        cos, sin = self._tables(c.max_position)
        starts = jnp.full((b,), start, jnp.int32)

        def layer(carry, xs):
            p_l, k_l, v_l = xs
            out, k_l, v_l = self.block.prefill_chunk_step(
                {"params": p_l, "state": {}}, carry, k_l, v_l, starts,
                cos, sin)
            return out, (k_l, v_l)

        h, (k_cache, v_cache) = jax.lax.scan(
            layer, h, (p["blocks"], k_cache, v_cache))
        h = ops.rms_norm(h, p["rms_f_scale"], eps=c.rms_eps)
        idx = s - 1 if last_index is None else last_index
        h = jax.lax.dynamic_index_in_dim(h, idx, axis=1, keepdims=False)
        logits = ops.linear(h, p["lm_head"].T.astype(c.dtype))
        return logits, k_cache, v_cache

    def decode_with_cache(self, variables, input_ids, k_cache, v_cache,
                          lengths):
        """One decode step; input_ids [B], caches [L, B, T, nkv, hd],
        lengths [B].  Returns (logits [B, V], new_k, new_v)."""
        p = variables["params"]
        c = self.c
        h = ops.embedding_lookup(
            p["tok_emb"], input_ids[:, None]).astype(c.dtype)
        # full tables, gathered per sequence at its own position
        cos, sin = self._tables(c.max_position)

        def layer(carry, xs):
            p_l, k_l, v_l = xs
            out, k_l, v_l = self.block.decode_step(
                {"params": p_l, "state": {}}, carry, k_l, v_l, lengths,
                cos, sin)
            return out, (k_l, v_l)

        h, (k_cache, v_cache) = jax.lax.scan(
            layer, h, (p["blocks"], k_cache, v_cache))
        h = ops.rms_norm(h, p["rms_f_scale"], eps=c.rms_eps)
        logits = ops.linear(h[:, 0], p["lm_head"].T.astype(c.dtype))
        return logits, k_cache, v_cache

    def lm_loss_fn(self):
        """Next-token loss; batch = (input_ids,).  Fused CE against the
        UNTIED lm_head (ops.lm_head_cross_entropy takes any [V, H])."""
        def fn(params, model_state, batch, rng, train):
            ids = batch[0] if isinstance(batch, (tuple, list)) else batch
            c = self.c
            if c.fused_ce:
                h = self.hidden_states({"params": params, "state": {}}, ids,
                                       train=train, rng=rng)
                loss = ops.lm_head_cross_entropy(
                    h[:, :-1], params["lm_head"], ids[:, 1:],
                    row_chunk=c.ce_row_chunk)
            else:
                logits, _ = self.apply({"params": params, "state": {}}, ids,
                                       train=train, rng=rng)
                per = ops.softmax_cross_entropy_sparse(
                    logits[:, :-1], ids[:, 1:])
                n_valid = jnp.sum(ids[:, 1:] != -1)
                loss = jnp.sum(per) / jnp.maximum(n_valid, 1)
            return loss, ({}, model_state)
        return fn


class HeteroLlama(LlamaModel):
    """Llama with per-layer parameter trees, executing a searched Plan.

    The Galvatron loop for the second family (reference
    tools/Galvatron/galvatron/models/llama_hf):

        layers = llama_layer_specs(...)                # cost IR
        plan = GalvatronSearching(sim, ...).search(layers)
        model = HeteroLlama.from_plan(cfg, plan)       # per-layer remat
        ex = Executor(model.lm_loss_fn(), opt, mesh=mesh,
                      dist_strategy=PlanStrategy(plan))  # per-layer tp/dp
    """

    def __init__(self, config: LlamaConfig, *,
                 layer_remat: "tuple[bool, ...] | None" = None):
        super().__init__(config)
        if layer_remat is not None and len(layer_remat) != config.num_layers:
            raise ValueError(
                f"layer_remat has {len(layer_remat)} flags for "
                f"{config.num_layers} layers")
        self.layer_remat = layer_remat

    @classmethod
    def from_plan(cls, config: LlamaConfig, plan) -> "HeteroLlama":
        from hetu_tpu.models.gpt_hetero import plan_block_remat
        return cls(config,
                   layer_remat=plan_block_remat(plan, config.num_layers))

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, c.num_layers + 3)
        params = {
            "tok_emb": self.w_init(ks[0], (c.vocab_size, c.hidden_size)),
            "lm_head": self.w_init(ks[1], (c.vocab_size, c.hidden_size)),
            "rms_f_scale": jnp.ones((c.hidden_size,)),
        }
        for i in range(c.num_layers):
            params[f"layer{i}"] = self.block.init(ks[2 + i])["params"]
        return {"params": params, "state": {}}

    def hidden_states(self, variables, input_ids, *, train: bool = False,
                      rng=None):
        p = variables["params"]
        c = self.c
        h = ops.embedding_lookup(p["tok_emb"], input_ids).astype(c.dtype)
        cos, sin = self._tables(input_ids.shape[1])
        for i in range(c.num_layers):
            def block_fn(lp, hh):
                return self.block.apply({"params": lp, "state": {}}, hh,
                                        cos, sin)[0]
            if self.layer_remat is not None and self.layer_remat[i]:
                block_fn = jax.checkpoint(block_fn)
            h = block_fn(p[f"layer{i}"], h)
        return ops.rms_norm(h, p["rms_f_scale"], eps=c.rms_eps)


def llama2_7b(**kw) -> LlamaModel:
    return LlamaModel(LlamaConfig(**kw))
