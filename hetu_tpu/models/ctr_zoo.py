"""CTR model zoo beyond Wide&Deep: DeepFM, DCN, and Deep Crossing.

Reference: examples/ctr/models/{deepfm_criteo.py, dcn_criteo.py,
dc_criteo.py} (alongside wdl.py →
hetu_tpu/models/wdl.py).  Same hybrid contract as WideDeep: the huge sparse
embeddings live on the PS plane and arrive as pulled rows; these modules
hold only dense parameters and return d(loss)/d(rows) for the host push.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hetu_tpu import layers, ops
from hetu_tpu.layers.base import Module


class DeepFM(Module):
    """FM second-order interactions + deep MLP (reference deepfm.py).

    Inputs: dense_x [B, dense_dim]; emb_rows [B, fields, emb_dim] (the FM
    latent vectors, PS-pulled); fm_linear_rows [B, fields, 1] (first-order
    weights per feature id — a dim-1 PS table, like the reference's
    separate linear embedding).
    """

    def __init__(self, num_sparse_fields: int, emb_dim: int, dense_dim: int,
                 hidden=(256, 256)):
        from hetu_tpu.models.ctr_common import mlp_tower
        self.fields = num_sparse_fields
        self.emb_dim = emb_dim
        self.deep = mlp_tower(num_sparse_fields * emb_dim + dense_dim,
                              hidden, out_dim=1)
        self.dense_linear = layers.Linear(dense_dim, 1)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        d = self.deep.init(k1)
        l = self.dense_linear.init(k2)
        return {"params": {"deep": d["params"], "lin": l["params"]},
                "state": {"deep": d["state"], "lin": {}}}

    def apply(self, variables, dense_x, emb_rows, fm_linear_rows, *,
              train: bool = False, rng=None):
        p, s = variables["params"], variables["state"]
        # FM 2nd order: 0.5 * (sum v)^2 - sum v^2, summed over emb dim
        sum_v = jnp.sum(emb_rows, axis=1)
        fm2 = 0.5 * jnp.sum(sum_v * sum_v
                            - jnp.sum(emb_rows * emb_rows, axis=1), axis=-1)
        fm1 = jnp.sum(fm_linear_rows[..., 0], axis=1)
        deep_in = jnp.concatenate(
            [emb_rows.reshape(emb_rows.shape[0], -1), dense_x], axis=-1)
        deep_out, ds = self.deep.apply(
            {"params": p["deep"], "state": s["deep"]}, deep_in, train=train,
            rng=rng)
        lin_out, _ = self.dense_linear.apply(
            {"params": p["lin"], "state": {}}, dense_x)
        logit = fm1 + fm2 + deep_out[:, 0] + lin_out[:, 0]
        return logit, {"deep": ds, "lin": {}}

    def hybrid_step_fn(self, optimizer):
        """Dense update + (emb_grads, fm_linear_grads) for the PS push."""
        from hetu_tpu.models.ctr_common import make_hybrid_step
        return make_hybrid_step(self, optimizer, n_sparse_inputs=2)


class ResidualUnit(Module):
    """Deep Crossing residual unit (reference dc_criteo.py:8-27):
    y = relu(x + W2 relu(W1 x + b1) + b2)."""

    def __init__(self, dim: int, hidden: int):
        self.dim, self.hidden = dim, hidden

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"params": {
            "w1": jax.random.normal(k1, (self.dim, self.hidden)) * 0.1,
            "b1": jnp.zeros((self.hidden,)),
            "w2": jax.random.normal(k2, (self.hidden, self.dim)) * 0.1,
            "b2": jnp.zeros((self.dim,))}, "state": {}}

    def apply(self, variables, x, *, train: bool = False, rng=None):
        p = variables["params"]
        h = ops.relu(x @ p["w1"] + p["b1"])
        return ops.relu(x + h @ p["w2"] + p["b2"]), {}


class DeepCrossing(Module):
    """Deep Crossing (reference dc_criteo.py): a stack of residual units
    over the concatenated [embeddings, dense] features, linear head."""

    def __init__(self, num_sparse_fields: int, emb_dim: int, dense_dim: int,
                 hidden: int = 64, n_units: int = 3):
        self.in_dim = num_sparse_fields * emb_dim + dense_dim
        self.units = [ResidualUnit(self.in_dim, hidden)
                      for _ in range(n_units)]
        self.head = layers.Linear(self.in_dim, 1)

    def init(self, key):
        ks = jax.random.split(key, len(self.units) + 1)
        return {"params": {
            **{f"unit{i}": u.init(k)["params"]
               for i, (u, k) in enumerate(zip(self.units, ks))},
            "head": self.head.init(ks[-1])["params"]}, "state": {}}

    def apply(self, variables, dense_x, emb_rows, *, train: bool = False,
              rng=None):
        p = variables["params"]
        x = jnp.concatenate(
            [emb_rows.reshape(emb_rows.shape[0], -1), dense_x], axis=-1)
        for i, u in enumerate(self.units):
            x, _ = u.apply({"params": p[f"unit{i}"], "state": {}}, x)
        logit, _ = self.head.apply({"params": p["head"], "state": {}}, x)
        return logit[:, 0], {}

    def hybrid_step_fn(self, optimizer):
        from hetu_tpu.models.ctr_common import make_hybrid_step
        return make_hybrid_step(self, optimizer, n_sparse_inputs=1)


class CrossNet(Module):
    """DCN cross layers: x_{l+1} = x0 * (w^T x_l) + b + x_l."""

    def __init__(self, dim: int, n_layers: int = 3):
        self.dim, self.n = dim, n_layers

    def init(self, key):
        ks = jax.random.split(key, self.n)
        return {"params": {
            "w": jnp.stack([jax.random.normal(k, (self.dim,)) * 0.01
                            for k in ks]),
            "b": jnp.zeros((self.n, self.dim))}, "state": {}}

    def apply(self, variables, x0, *, train: bool = False, rng=None):
        p = variables["params"]
        x = x0
        for l in range(self.n):
            xw = jnp.einsum("bd,d->b", x, p["w"][l])[:, None]
            x = x0 * xw + p["b"][l] + x
        return x, {}


class DCN(Module):
    """Deep & Cross Network (reference dcn.py): cross net + deep MLP on the
    concatenated [embeddings, dense] features."""

    def __init__(self, num_sparse_fields: int, emb_dim: int, dense_dim: int,
                 hidden=(256, 256), n_cross: int = 3):
        from hetu_tpu.models.ctr_common import mlp_tower
        self.in_dim = num_sparse_fields * emb_dim + dense_dim
        self.cross = CrossNet(self.in_dim, n_cross)
        self.deep = mlp_tower(self.in_dim, hidden)
        self.head = layers.Linear(hidden[-1] + self.in_dim, 1)

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        c = self.cross.init(k1)
        d = self.deep.init(k2)
        h = self.head.init(k3)
        return {"params": {"cross": c["params"], "deep": d["params"],
                           "head": h["params"]},
                "state": {"deep": d["state"]}}

    def apply(self, variables, dense_x, emb_rows, *, train: bool = False,
              rng=None):
        p, s = variables["params"], variables["state"]
        x0 = jnp.concatenate(
            [emb_rows.reshape(emb_rows.shape[0], -1), dense_x], axis=-1)
        xc, _ = self.cross.apply({"params": p["cross"], "state": {}}, x0)
        xd, ds = self.deep.apply({"params": p["deep"], "state": s["deep"]},
                                 x0, train=train, rng=rng)
        logit, _ = self.head.apply(
            {"params": p["head"], "state": {}},
            jnp.concatenate([xc, xd], axis=-1))
        return logit[:, 0], {"deep": ds}

    def hybrid_step_fn(self, optimizer):
        from hetu_tpu.models.ctr_common import make_hybrid_step
        return make_hybrid_step(self, optimizer, n_sparse_inputs=1)
