"""BERT.

Reference: examples/nlp/bert (hetu BERT-base pretraining, BASELINE.json
config #3).  Encoder-only transformer with token/position/segment embeddings,
post-LN blocks, MLM + NSP heads.

TPU notes: the whole model is one jit region; blocks run under lax.scan over
stacked per-layer params ("scan-over-layers") so compile time stays flat with
depth and XLA pipelines layer collectives.  Weights are Megatron-shardable
(see parallel/strategies/megatron.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from hetu_tpu import init as initializers
from hetu_tpu import ops
from hetu_tpu.layers.base import Module
from hetu_tpu.layers.transformer import TransformerBlock


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: object = jnp.float32
    attention_impl: str = "xla"  # 'flash' = Pallas kernel (TPU); only
    # applies when no attention_mask is passed (masked calls warn + use xla)


class BertModel(Module):
    def __init__(self, config: BertConfig):
        self.c = config
        self.block = TransformerBlock(
            config.hidden_size, config.num_heads, config.ffn_size,
            dropout_rate=config.dropout_rate, causal=False, pre_norm=False,
            dtype=config.dtype, attention_impl=config.attention_impl)
        self.w_init = initializers.truncated_normal(stddev=0.02)

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, 8)
        block_keys = jax.random.split(ks[0], c.num_layers)
        # stacked per-layer params for scan-over-layers
        blocks = jax.vmap(lambda k: self.block.init(k)["params"])(block_keys)
        params = {
            "tok_emb": self.w_init(ks[1], (c.vocab_size, c.hidden_size)),
            "pos_emb": self.w_init(ks[2], (c.max_position, c.hidden_size)),
            "seg_emb": self.w_init(ks[3], (c.type_vocab_size, c.hidden_size)),
            "emb_ln_scale": jnp.ones((c.hidden_size,)),
            "emb_ln_bias": jnp.zeros((c.hidden_size,)),
            "blocks": blocks,
            "pooler_w": self.w_init(ks[4], (c.hidden_size, c.hidden_size)),
            "pooler_b": jnp.zeros((c.hidden_size,)),
            # MLM head (tied decoder uses tok_emb.T) + NSP head
            "mlm_dense_w": self.w_init(ks[5], (c.hidden_size, c.hidden_size)),
            "mlm_dense_b": jnp.zeros((c.hidden_size,)),
            "mlm_ln_scale": jnp.ones((c.hidden_size,)),
            "mlm_ln_bias": jnp.zeros((c.hidden_size,)),
            "mlm_bias": jnp.zeros((c.vocab_size,)),
            "nsp_w": self.w_init(ks[6], (c.hidden_size, 2)),
            "nsp_b": jnp.zeros((2,)),
        }
        return {"params": params, "state": {}}

    def encode(self, params, input_ids, token_type_ids=None,
               attention_mask=None, *, train=False, rng=None):
        c = self.c
        b, s = input_ids.shape
        h = ops.embedding_lookup(params["tok_emb"], input_ids)
        h = h + params["pos_emb"][None, :s]
        if token_type_ids is not None:
            h = h + ops.embedding_lookup(params["seg_emb"], token_type_ids)
        h = ops.layer_norm(h, params["emb_ln_scale"], params["emb_ln_bias"])
        if train and c.dropout_rate > 0:
            h = ops.dropout(h, c.dropout_rate, jax.random.fold_in(rng, 999),
                            train=True)
        h = h.astype(c.dtype)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :]  # [B,1,1,S]

        def layer(carry, xs):
            p_l, k_l = xs
            out, _ = self.block.apply({"params": p_l, "state": {}}, carry,
                                      mask=mask, train=train, rng=k_l)
            return out, None

        keys = (jax.random.split(rng, c.num_layers) if rng is not None
                else jnp.zeros((c.num_layers, 2), jnp.uint32))
        h, _ = jax.lax.scan(layer, h, (params["blocks"], keys))
        return h.astype(jnp.float32)

    def apply(self, variables, input_ids, token_type_ids=None,
              attention_mask=None, *, train: bool = False, rng=None):
        """Returns (sequence_output [B,S,H], pooled [B,H])."""
        p = variables["params"]
        seq = self.encode(p, input_ids, token_type_ids, attention_mask,
                          train=train, rng=rng)
        pooled = ops.tanh(ops.linear(seq[:, 0], p["pooler_w"], p["pooler_b"]))
        return (seq, pooled), {}

    def mlm_logits(self, params, seq):
        h = ops.gelu(ops.linear(seq, params["mlm_dense_w"],
                                params["mlm_dense_b"]))
        h = ops.layer_norm(h, params["mlm_ln_scale"], params["mlm_ln_bias"])
        return ops.linear(h, params["tok_emb"].T, params["mlm_bias"])

    def pretrain_loss_fn(self):
        """MLM + NSP loss (reference: examples/nlp/bert pretraining scripts).

        batch = (input_ids, token_type_ids, attention_mask, mlm_labels
                 [-1 = unmasked], nsp_labels)
        """
        def fn(params, model_state, batch, rng, train):
            input_ids, tok_type, attn_mask, mlm_labels, nsp_labels = batch
            seq = self.encode(params, input_ids, tok_type, attn_mask,
                              train=train, rng=rng)
            logits = self.mlm_logits(params, seq)
            per_tok = ops.softmax_cross_entropy_sparse(logits, mlm_labels,
                                                       ignored_index=-1)
            denom = jnp.maximum(jnp.sum(mlm_labels != -1), 1)
            mlm_loss = jnp.sum(per_tok) / denom
            pooled = ops.tanh(ops.linear(seq[:, 0], params["pooler_w"],
                                         params["pooler_b"]))
            nsp_logits = ops.linear(pooled, params["nsp_w"], params["nsp_b"])
            nsp_loss = jnp.mean(
                ops.softmax_cross_entropy_sparse(nsp_logits, nsp_labels))
            loss = mlm_loss + nsp_loss
            return loss, ({"mlm_loss": mlm_loss, "nsp_loss": nsp_loss},
                          model_state)
        return fn


def bert_base(**kw) -> BertModel:
    return BertModel(BertConfig(**kw))


def bert_large(**kw) -> BertModel:
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_layers", 24)
    kw.setdefault("num_heads", 16)
    kw.setdefault("ffn_size", 4096)
    return BertModel(BertConfig(**kw))
