"""Fault-tolerant training supervisor over ``train.executor.Executor``.

Closes the recovery contracts the lower layers explicitly punt to the
caller:

* ``ps/van.py`` (PartitionedPSTable docstring): a killed PS shard that
  restarts blank is transparently re-created with FRESH-INIT weights and
  ``recovered`` increments — "the caller decides whether to re-push
  weights".  :class:`PSShardGuard` is that caller: it snapshots the table
  on the checkpoint cadence and replays the recovered shard's rows via
  ``sparse_set``, so a resurrected shard carries learned embeddings.
* ``train/checkpoint.py``: atomic single-file save/load, but no retention
  policy and no corrupt-file fallback.  :class:`CheckpointManager` adds
  keep-K, a CRC32 sidecar, and newest-valid-wins restore.
* ``train/executor.py``: the ``train_guarded`` subexecutor skips nonfinite
  updates in-graph; :class:`Supervisor` counts the skips and aborts after
  N consecutive.

The supervisor's per-step loop is: injected faults (optional chaos
harness) → shard-guard poll/repair → batch fetch (retried) → guarded
train step → post-step hook (retried; skipped on a nonfinite step so
poisoned gradients never reach the PS) → cadence checkpoint → preemption
check.
Retries use exponential backoff with seeded jitter and a transient-error
predicate — van/PS transport failures and injected faults retry; real
bugs raise immediately.

SIGTERM (preemption) is handled cooperatively: the handler only sets a
flag; at the END of the in-flight step the supervisor checkpoints
(params + optimizer + RNG seed/seqnum, plus PS snapshots) and returns with
``preempted=True``.  A later ``run()`` with the same ``ckpt_dir`` resumes
at the exact step with the exact RNG state.
"""

from __future__ import annotations

import signal
import threading
import time
import zlib
from collections import defaultdict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

import numpy as np

from hetu_tpu.telemetry import trace
from hetu_tpu.train import checkpoint as ckpt
from hetu_tpu.train.checkpoint import CheckpointCorruptError


class NonFiniteAbort(RuntimeError):
    """Too many consecutive nonfinite (NaN/Inf) steps — the run is
    diverged, not unlucky; aborting beats silently skipping forever.

    ``state``/``step`` carry the last-finite training state (the guarded
    step never let nonfinite values in), because the caller's own state
    object was donated to the jitted step and is gone; with a
    ``ckpt_dir`` the supervisor also checkpoints it before raising."""

    def __init__(self, msg: str, *, state=None, step: int = -1):
        super().__init__(msg)
        self.state = state
        self.step = step


def default_is_transient(exc: BaseException) -> bool:
    """Errors worth retrying: transport-level van/PS failures (a dead shard
    mid-restart, a dropped connection, an injected fault) and flaky-data
    errors.  Everything else — shape errors, OOM, real bugs — is not."""
    from hetu_tpu.resilience.faults import TransientDataError
    if isinstance(exc, (ConnectionError, TimeoutError, TransientDataError)):
        return True  # TransientFault subclasses ConnectionError
    # the native layer surfaces every failed wire op as
    # RuntimeError("hetu_ps <op> failed with rc=..."); during a shard
    # restart these clear once the heartbeat re-resolves the endpoint
    # (asserted end-to-end, with real SIGKILLed shard processes and a
    # same-port AND new-port restart, in tests/test_van_heartbeat.py)
    return isinstance(exc, RuntimeError) and "hetu_ps" in str(exc)


# ---------------------------------------------------------------------------
# checkpoint retention
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Atomic keep-K checkpoint directory with CRC sidecars.

    ``save`` publishes ``ckpt-<step>.npz`` (checkpoint.save is atomic:
    tmp + fsync + os.replace) plus a ``.crc`` sidecar holding
    ``crc32 nbytes`` of the published file, then prunes to the newest
    ``keep``.  ``restore`` walks newest→oldest, skipping any candidate
    whose CRC mismatches or whose load raises
    :class:`~hetu_tpu.train.checkpoint.CheckpointCorruptError` — a
    preemption mid-save or bit rot costs at most one checkpoint interval,
    never the run.
    """

    def __init__(self, directory, *, keep: int = 3, prefix: str = "ckpt"):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = int(keep)
        self.prefix = prefix
        self.skipped: list[str] = []  # corrupt candidates seen by restore
        self.last_restored: Optional[str] = None  # path restore() used

    def _path(self, step: int) -> Path:
        return self.dir / f"{self.prefix}-{int(step):08d}.npz"

    def steps(self) -> list[int]:
        out = []
        for p in self.dir.glob(f"{self.prefix}-*.npz"):
            try:
                out.append(int(p.stem.split("-")[-1]))
            except ValueError:
                continue
        return sorted(out)

    @staticmethod
    def _crc_file(path: Path) -> tuple[int, int]:
        """Streamed (crc32, nbytes) — never the whole archive in RAM."""
        crc = 0
        n = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    return crc, n
                crc = zlib.crc32(chunk, crc)
                n += len(chunk)

    def save(self, state, step: int, *, extra: Optional[dict] = None) -> Path:
        path = self._path(step)
        ckpt.save(path, state, extra=extra)
        crc, n = self._crc_file(path)
        crc_tmp = path.with_suffix(".crc.tmp")
        crc_tmp.write_text(f"{crc:08x} {n}\n")
        crc_tmp.replace(path.with_suffix(".crc"))
        self._prune()
        return path

    def _prune(self) -> None:
        for s in self.steps()[:-self.keep]:
            self._path(s).unlink(missing_ok=True)
            self._path(s).with_suffix(".crc").unlink(missing_ok=True)

    def _crc_ok(self, path: Path) -> bool:
        side = path.with_suffix(".crc")
        if not side.exists():
            return True  # no sidecar: can't judge here; load() still checks
        try:
            want_crc, want_n = side.read_text().split()
            crc, n = self._crc_file(path)
            return n == int(want_n) and crc == int(want_crc, 16)
        except (OSError, ValueError):
            return False

    def restore(self, template, *, restore_rng: bool = True):
        """Newest valid checkpoint → ``(state, step)``; None if none.

        Only CORRUPTION falls back to an older checkpoint; a checkpoint
        that loads but doesn't fit (wrong architecture, newer format)
        raises — silently restarting from fresh weights because the
        template changed is never what the caller meant."""
        for step in reversed(self.steps()):
            path = self._path(step)
            if not self._crc_ok(path):
                self.skipped.append(str(path))
                continue
            try:
                state = ckpt.load(path, template, restore_rng=restore_rng)
            except CheckpointCorruptError:
                self.skipped.append(str(path))
                continue
            self.last_restored = str(path)
            return state, step
        return None


# ---------------------------------------------------------------------------
# PS shard snapshot / repair
# ---------------------------------------------------------------------------

class PSShardGuard:
    """Snapshot + repair for one ``PartitionedPSTable``.

    ``snapshot()`` (called on the supervisor's checkpoint cadence) pulls
    each LIVE shard's row range into worker memory (and optionally persists
    it, so a preempted-and-resumed worker can still repair).  ``poll()``
    watches ``table.alive``/``table.recovered``: when a shard that died
    comes back and the group re-created it blank (``recovered``
    incremented), the guard replays that shard's snapshot rows via
    ``sparse_set`` — only the recovered shard is touched, live shards never
    rewind.

    Durable optimizer slots: when the table exposes ``slots_get`` /
    ``slots_set`` (the csrc ``ps_table_slots_*`` ops over the van/group),
    snapshots ALSO capture each live shard's server-side optimizer state —
    s1 (velocity / adagrad accumulator / adam m), s2 (adam v), and the
    per-row adam step — and repair replays them after the weights, so a
    resurrected shard resumes with its REAL accumulators, bitwise, not
    fresh zeros.  ``slots=False`` opts out (weights-only, the pre-slot
    behavior).

    Limits (see README "Fault tolerance"): repair restores weights AND
    slots as of the last snapshot — updates since the snapshot are lost;
    the checkpoint cadence bounds the loss.  An alive-flicker without a
    blank re-create (``recovered`` unchanged) is left alone.
    """

    def __init__(self, table, *, snapshot_path=None, name: str = "pstable",
                 slots: bool = True):
        self.table = table
        self.name = name
        self.snapshot_path = Path(snapshot_path) if snapshot_path else None
        self.slots = bool(slots) and hasattr(table, "slots_get") \
            and hasattr(table, "slots_set")
        self._snap = None              # [rows, dim] f32, lazily allocated
        self._snap_s1 = None           # [rows, dim] f32 optimizer slot 1
        self._snap_s2 = None           # [rows, dim] f32 optimizer slot 2
        self._snap_step = None         # [rows] u64 per-row adam step
        self._have: set[int] = set()   # shard idx with valid snapshot rows
        self._have_slots: set[int] = set()  # shard idx with slot snapshot
        self._pending: set[int] = set()  # shards seen dead, awaiting repair
        self._seen_recovered = int(table.recovered)
        self.repairs = 0
        if self.snapshot_path is not None and self.snapshot_path.exists():
            z = np.load(self.snapshot_path)
            self._snap = z["values"]
            self._have = {int(i) for i in z["have"]}
            if "s1" in z.files:  # pre-slot snapshot files stay loadable
                self._snap_s1 = z["s1"]
                self._snap_s2 = z["s2"]
                self._snap_step = z["step"]
                self._have_slots = {int(i) for i in z["have_slots"]}

    def shard_rows(self, i: int) -> np.ndarray:
        starts = self.table.shard_starts
        hi = (starts[i + 1] if i + 1 < self.table.n_servers
              else self.table.rows)
        return np.arange(starts[i], hi, dtype=np.int64)

    def snapshot(self) -> int:
        """Snapshot every live shard; returns how many shards captured.
        Dead shards keep their previous snapshot rows (that is the data the
        repair will need) and are queued for repair."""
        if self._snap is None:
            self._snap = np.zeros((self.table.rows, self.table.dim),
                                  np.float32)
        if self.slots and self._snap_s1 is None:
            self._snap_s1 = np.zeros_like(self._snap)
            self._snap_s2 = np.zeros_like(self._snap)
            self._snap_step = np.zeros(self.table.rows, np.uint64)
        captured = 0
        alive = self.table.alive
        for i, a in enumerate(alive):
            if not a:
                self._pending.add(i)
                continue
            rows = self.shard_rows(i)
            try:
                # pull into locals, commit only after EVERY read succeeds:
                # a shard dying between the weight pull and the slot pull
                # must not leave new weights paired with the previous
                # snapshot's accumulators (a torn pair that never
                # coexisted would be replayed on repair)
                vals = self.table.sparse_pull(rows)
                if self.slots:
                    s1, s2, st = self.table.slots_get(rows)
            except (RuntimeError, ConnectionError, TimeoutError):
                self._pending.add(i)  # died between the mask and the pull
                continue
            self._snap[rows] = vals
            if self.slots:
                self._snap_s1[rows] = s1
                self._snap_s2[rows] = s2
                self._snap_step[rows] = st
                self._have_slots.add(i)
            self._have.add(i)
            captured += 1
        if self.snapshot_path is not None and captured:
            tmp = self.snapshot_path.with_name(self.snapshot_path.name
                                               + ".tmp")
            arrays = {"values": self._snap,
                      "have": np.asarray(sorted(self._have), np.int64)}
            if self.slots:
                arrays.update(
                    s1=self._snap_s1, s2=self._snap_s2,
                    step=self._snap_step,
                    have_slots=np.asarray(sorted(self._have_slots),
                                          np.int64))
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            tmp.replace(self.snapshot_path)
        return captured

    def poll(self) -> int:
        """Detect died→alive shards; replay snapshots into any the group
        re-created blank.  Returns repairs performed now.

        Attribution: ``recovered`` is one GLOBAL counter, so each blank
        re-create must be CLAIMED by exactly one pending shard — a bump
        observed across a shard's own probe is attributed to that shard;
        an unclaimed earlier bump (a training op touched the resurrected
        shard between polls) is claimed by the first pending shard that
        probes clean.  An alive-flicker whose incarnation never changed
        claims nothing and is left alone; only when a flickered and a
        re-created shard race the SAME poll and the flickered one probes
        first can a spurious rewind (bounded by the snapshot cadence)
        still happen."""
        t = self.table
        alive = t.alive
        for i, a in enumerate(alive):
            if not a:
                self._pending.add(i)
        done = 0
        seen = self._seen_recovered  # re-creates already claimed
        for i in sorted(self._pending):
            if not alive[i]:
                continue
            rows = self.shard_rows(i)
            rec_before = int(t.recovered)
            try:
                # the probe forces the group's lazy shard re-create (a
                # blank restarted server answers 'no table' until then)
                t.sparse_pull(rows[:1])
            except (RuntimeError, ConnectionError, TimeoutError):
                continue  # still coming up — next poll
            rec_after = int(t.recovered)
            if rec_after > rec_before:
                recreated = True           # this probe triggered it
                seen += rec_after - rec_before
            elif rec_before > seen:
                recreated = True           # claim one unattributed bump
                seen += 1
            else:
                recreated = False          # flicker: data intact
            if recreated and i in self._have:
                t.sparse_set(rows, self._snap[rows])
                if self.slots and i in self._have_slots:
                    # AFTER the weights: sparse_set leaves slots untouched,
                    # so the restored accumulators land bitwise-exact
                    t.slots_set(rows, self._snap_s1[rows],
                                self._snap_s2[rows], self._snap_step[rows])
                done += 1
                self.repairs += 1
            self._pending.discard(i)
        if self._pending:
            self._seen_recovered = max(seen, self._seen_recovered)
        else:
            # nothing left to claim a bump: fold fully forward so a
            # death+restart that happened entirely between polls (never
            # observed dead, re-created by a training op, unrepairable
            # anyway) can't misattribute to a future flicker
            self._seen_recovered = max(self._seen_recovered,
                                       int(t.recovered))
        return done


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

@dataclass
class SupervisorReport:
    """What a ``run()`` did: the final state, where it stopped, whether a
    preemption cut it short, and the resilience counters."""

    state: Any
    step: int
    preempted: bool
    counters: dict = field(default_factory=dict)
    last_metrics: dict = field(default_factory=dict)


class Supervisor:
    """Wraps an :class:`~hetu_tpu.train.executor.Executor` with checkpoint
    retention, per-step retry, a nonfinite guard, PS shard repair, and
    cooperative preemption.  See the module docstring for the loop shape.

    ``run(state, batch_fn, steps)`` drives ``batch_fn(step_index)`` →
    ``executor.run('train_guarded', ...)`` until ``state.step == steps``;
    ``post_step(step, state, metrics, batch)`` (optional) carries hybrid
    PS work (e.g. embedding-gradient pushes) inside the retry envelope.
    """

    def __init__(self, executor, *, ckpt_dir=None, ckpt_every: int = 0,
                 keep: int = 3, retries: int = 8,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 2.0,
                 backoff_jitter: float = 0.25, seed: int = 0,
                 nonfinite_limit: int = 3, injector=None, guards=(),
                 logger=None, is_transient: Optional[Callable] = None,
                 preempt_signals=(signal.SIGTERM,)):
        self.executor = executor
        self.manager = (CheckpointManager(ckpt_dir, keep=keep)
                        if ckpt_dir is not None else None)
        self.ckpt_every = int(ckpt_every)
        self.retries = int(retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_jitter = float(backoff_jitter)
        self.nonfinite_limit = int(nonfinite_limit)
        self.injector = injector
        self.guards = list(guards)
        self.logger = logger
        self.preempt_signals = tuple(preempt_signals)
        self._is_transient = is_transient or default_is_transient
        self._jitter_rng = np.random.default_rng(seed)
        self.counters: dict = defaultdict(int)
        self._preempt = threading.Event()

    # ---- retry envelope ----
    def _with_retries(self, fn, what: str):
        attempt = 0
        t_first_fail = 0.0  # tracing: first failure → eventual success
        while True:
            try:
                out = fn()
                if attempt:
                    # only a retried envelope leaves a recovery span — the
                    # zero-retry common case records nothing
                    trace.complete("recovery.retry", t_first_fail,
                                   {"what": what, "attempts": attempt})
                return out
            except Exception as e:
                if not self._is_transient(e) or attempt >= self.retries:
                    raise
                if attempt == 0:
                    t_first_fail = trace.now_us()
                delay = min(self.backoff_base_s * (2.0 ** attempt),
                            self.backoff_max_s)
                delay *= 1.0 + self.backoff_jitter * float(
                    self._jitter_rng.random())
                self.counters["retries"] += 1
                self.counters[f"retries_{what}"] += 1
                self._log_inc("retries")
                trace.instant("supervisor.retry",
                              {"what": what, "attempt": attempt,
                               "error": type(e).__name__,
                               "delay_s": round(delay, 4)})
                time.sleep(delay)
                attempt += 1

    def _log_inc(self, name: str, n: int = 1) -> None:
        if self.logger is not None and hasattr(self.logger, "inc"):
            self.logger.inc(name, n)

    # ---- preemption ----
    def _on_signal(self, signum, frame) -> None:
        # only set a flag: the in-flight step finishes, then we checkpoint
        self._preempt.set()
        self.counters["preempt_signals"] += 1

    # ---- subclass hooks (ElasticSupervisor overrides) ----
    def _maybe_resize(self, state, step_i: int):
        """Membership hook, called at the top of every step AFTER injected
        faults land: the base supervisor's mesh is fixed for the life of
        the run, so this is the identity.  ElasticSupervisor overrides it
        to reform the mesh and redistribute state."""
        return state

    def _ckpt_extra(self) -> Optional[dict]:
        """Extra JSON recorded in every checkpoint header (None = none).
        ElasticSupervisor records the live DP width here."""
        return None

    # ---- checkpoint + snapshots ----
    def _checkpoint(self, state, step: int, *,
                    reason: str = "cadence") -> None:
        t0 = time.perf_counter()
        with trace.span("supervisor.checkpoint") as sp:
            sp.set("step", int(step))
            sp.set("reason", reason)
            if self.manager is not None:
                with trace.span("supervisor.checkpoint_write"):
                    self.manager.save(state, step, extra=self._ckpt_extra())
            for g in self.guards:
                try:
                    with trace.span("supervisor.shard_snapshot"):
                        g.snapshot()
                    self.counters["shard_snapshots"] += 1
                except (RuntimeError, ConnectionError, TimeoutError):
                    self.counters["shard_snapshot_errors"] += 1
        dt = time.perf_counter() - t0
        self.counters["checkpoints"] += 1
        self.counters["checkpoint_latency_s_last"] = dt
        self._log_inc("checkpoints")
        if self.logger is not None:
            self.logger.log({"checkpoint_latency_s": dt}, step=step)

    # ---- the loop ----
    def run(self, state, batch_fn: Callable[[int], Any], steps: int, *,
            post_step: Optional[Callable] = None,
            resume: bool = True) -> SupervisorReport:
        self._preempt.clear()  # a prior run's preemption must not leak in
        if self.injector is not None:
            batch_fn = self.injector.wrap_batch_fn(batch_fn)
            self.injector.install()

        step_i = int(np.asarray(state.step))
        if resume and self.manager is not None:
            got = self.manager.restore(state)
            if self.manager.skipped:
                # recorded even when NOTHING restored: "found checkpoints
                # and rejected every one" must never be silent
                self.counters["corrupt_checkpoints_skipped"] = \
                    len(self.manager.skipped)
                self._log_inc("corrupt_checkpoints_skipped",
                              len(self.manager.skipped))
            if got is not None:
                state, step_i = got
                self.counters["resumed_from_step"] = step_i

        old_handlers = {}
        try:
            for sg in self.preempt_signals:
                old_handlers[sg] = signal.signal(sg, self._on_signal)
        except ValueError:
            old_handlers = {}  # not the main thread: injector-driven
            # preemption still works via an externally-installed handler

        nonfinite_run = 0
        preempted = False
        metrics: dict = {}
        try:
            while step_i < int(steps):
                if self.injector is not None:
                    self.injector.on_step(step_i)
                state = self._maybe_resize(state, step_i)
                for g in self.guards:
                    t_poll = trace.now_us()
                    repaired = self._with_retries(g.poll, "guard")
                    if repaired:
                        # retroactive span: only a poll that actually
                        # repaired a shard is a recovery worth a track slot
                        trace.complete("recovery.shard_repair", t_poll,
                                       {"repaired": repaired,
                                        "step": step_i})
                        self.counters["shard_repairs"] += repaired
                        self._log_inc("shard_repairs", repaired)
                with trace.span("train.data_wait"):
                    batch = self._with_retries(lambda: batch_fn(step_i),
                                               "data")
                if self.injector is not None:
                    batch = self.injector.corrupt_batch(step_i, batch)
                state, metrics = self.executor.run("train_guarded", state,
                                                   batch)
                nonfinite = int(np.asarray(metrics.get("nonfinite", 0)))
                if nonfinite:
                    nonfinite_run += 1
                    self.counters["nonfinite_steps_skipped"] += 1
                    self._log_inc("nonfinite_steps_skipped")
                    trace.instant("recovery.nonfinite_skip",
                                  {"step": step_i, "run": nonfinite_run})
                    if nonfinite_run >= self.nonfinite_limit:
                        # the caller's own state object was donated to the
                        # jitted step — preserve the last-finite state
                        # (checkpoint if we can, always on the exception)
                        if self.manager is not None:
                            self._checkpoint(state, step_i,
                                             reason="nonfinite")
                        raise NonFiniteAbort(
                            f"{nonfinite_run} consecutive nonfinite steps "
                            f"ending at step {step_i} — loss diverged or "
                            "data is poisoned; aborting (exception .state "
                            "holds the last finite values)",
                            state=state, step=step_i)
                else:
                    nonfinite_run = 0
                    if post_step is not None:
                        self._with_retries(
                            lambda: post_step(step_i, state, metrics,
                                              batch), "post_step")
                step_i += 1
                self.counters["steps"] += 1
                if (self.ckpt_every and step_i % self.ckpt_every == 0
                        and step_i < int(steps)):
                    self._checkpoint(state, step_i)
                if self._preempt.is_set():
                    self._checkpoint(state, step_i, reason="preempt")
                    preempted = True
                    break
        finally:
            for sg, h in old_handlers.items():
                signal.signal(sg, h)
            if self.injector is not None:
                self.injector.uninstall()
                for k, v in self.injector.counters.items():
                    self.counters[k] = v
            if self.logger is not None:
                snap = {k: float(v) for k, v in self.counters.items()}
                self.logger.log(snap, step=step_i)
        if not preempted and self.ckpt_every and self.manager is not None:
            # final: resume == completed
            self._checkpoint(state, step_i, reason="final")
        return SupervisorReport(state=state, step=step_i,
                                preempted=preempted,
                                counters=dict(self.counters),
                                last_metrics=metrics)
