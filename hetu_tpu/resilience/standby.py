"""Self-promoting standby controllers: takeover without an operator.

PR 12 made every controller killable, but takeover stayed
OPERATOR-invoked — somebody had to notice the dead controller and call
``takeover()``.  This module closes that residual: a
:class:`StandbyController` attaches to the fleet's blackboard, watches
the CONTROLLER row's beat exactly the way members do, and when the
beat stays silent past the lease bound it promotes ITSELF — a
single-shot van-side CAS on the controller row's incarnation field
decides the race, so of N standbys watching one fleet exactly one
wins (the losers observe the winner's incarnation in the CAS response
and exit FENCED, touching nothing).  The winner then invokes the
plane's existing ``takeover()`` classmethod
(:class:`~hetu_tpu.serve.crosshost.CrossProcessServingPool` /
``MultiControllerElasticSupervisor`` / ``MPMDPipelineSupervisor``),
which claims the fence one higher again and adopts the fleet — the
standby adds only the WATCHING and the CAS-decided right to act.

Why the pre-claim is single-shot where ``claim_controller``'s CAS
loop retries: a retrying loser would out-claim the winner mid-takeover
(two controllers adopting one fleet); a standby that LOSES the claim
must stand down, not escalate.  The pre-claim writes ``beat=1`` under
the new incarnation, so members' silence clocks restart immediately —
the fleet knows a successor exists before the (slower) adoption
finishes.

The ``standby_main`` harness runs this as its own process (markers:
``READY`` → ``WATCHING`` → ``PROMOTED``/``FENCED`` → ``ALLDONE``),
with a crash-durable span stream like every other fleet process.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Optional

import numpy as np

from hetu_tpu.ps import membership as _mb
from hetu_tpu.telemetry import trace


class StandbyController:
    """Watch a fleet's controller lease; self-promote on silence.

    ``plane`` names which ``takeover()`` to invoke: ``"serving"``,
    ``"elastic"``, or ``"mpmd"``.  ``takeover_kwargs`` are passed
    through (``workdir``/``port`` ride separately).  ``table=``
    injects a pre-built blackboard surface (tests); otherwise the
    standby attaches over the van — replicated when ``van_spec`` names
    a durable-tier pair, so the standby survives a van failover too.
    """

    def __init__(self, *, workdir=None, port: int = 0, plane: str =
                 "serving", membership_table: int = 0, n_slots: int = 0,
                 lease_bound_s: float = 2.0, poll_s: float = 0.1,
                 van_spec: Optional[dict] = None,
                 takeover_kwargs: Optional[dict] = None,
                 table=None, name: str = "standby"):
        if plane not in ("serving", "elastic", "mpmd"):
            raise ValueError(f"unknown control plane {plane!r}")
        self.workdir = workdir
        self.port = int(port)
        self.plane = plane
        self.n_slots = int(n_slots)
        self.lease_bound_s = float(lease_bound_s)
        self.poll_s = float(poll_s)
        self.takeover_kwargs = dict(takeover_kwargs or {})
        self.name = name
        self._replica = None
        if van_spec:
            from hetu_tpu.ps.replica import VanReplica
            self._replica = VanReplica.from_spec(van_spec)
        self._table = table if table is not None else \
            _mb.attach_blackboard("127.0.0.1", self.port,
                                  table_id=int(membership_table),
                                  n_slots=self.n_slots,
                                  replica=self._replica)
        self._own_table = table is None
        # observed controller lease: incarnation, beat, and when the
        # beat last ADVANCED (monotonic) — the silence clock
        self.ctrl_inc = 0
        self.ctrl_beat = -1
        self._advance = time.monotonic()
        self._stop = threading.Event()
        # outcome: None while watching; "promoted" (this standby won
        # and ran the takeover — `adopted` holds the result), or
        # "fenced" (another claimant won the CAS — stood down)
        self.outcome: Optional[str] = None
        self.adopted = None
        from hetu_tpu.telemetry import default_registry as reg
        self._m_promoted = reg.counter(
            "standby.promotions",
            help="standby self-promotions that WON the controller CAS")
        self._m_fenced = reg.counter(
            "standby.claims_lost",
            help="standby claims lost to a concurrent winner (stood "
                 "down FENCED)")

    # ---- observation ----
    def observe(self) -> bool:
        """One read of the controller row; returns True when the beat
        advanced (or a new incarnation appeared)."""
        row = _mb.control_rpc(
            lambda: self._table.sparse_pull([self.n_slots + 1]),
            op="standby_watch", link=f"{self.name}->van",
            deadline_s=2.0)[0]
        inc, beat = int(row[_mb.R_CINC]), int(row[_mb.R_CBEAT])
        advanced = False
        if inc > self.ctrl_inc:
            self.ctrl_inc, self.ctrl_beat = inc, beat
            advanced = True
        elif inc == self.ctrl_inc and beat != self.ctrl_beat:
            self.ctrl_beat = beat
            advanced = True
        if advanced:
            self._advance = time.monotonic()
        return advanced

    def silent(self) -> bool:
        """True when a controller has been observed and its beat has
        not advanced for the lease bound.  A fleet whose controller
        NEVER beat (died before this standby attached) goes silent on
        the same clock — the bound starts at attach."""
        return time.monotonic() - self._advance > self.lease_bound_s

    # ---- the claim ----
    def try_claim(self) -> bool:
        """ONE CAS attempt at ``observed + 1``.  True = this standby
        owns the right to take over; False = a concurrent claimant won
        (``ctrl_inc`` now carries the winner's incarnation).  Never
        retries a loss — a standby that lost must stand down."""
        observed = self.ctrl_inc
        desired = np.zeros(_mb.MEMBER_DIM, np.float32)
        desired[_mb.R_CINC] = observed + 1
        desired[_mb.R_CBEAT] = 1
        desired[_mb.R_CEPOCH] = 0
        desired[_mb.R_CPID] = os.getpid() % (1 << 24)
        try:
            swapped, actual = _mb.control_rpc(
                lambda: self._table.row_cas(
                    self.n_slots + 1, _mb.R_CINC, float(observed),
                    desired),
                op="standby_claim", link=f"{self.name}->van",
                deadline_s=5.0)
        except (NotImplementedError, AttributeError):
            # old van without OP_ROW_CAS: a single-shot claim cannot be
            # made tie-proof — refuse to self-promote rather than risk
            # two winners (the operator path still works)
            raise RuntimeError(
                "standby self-promotion needs a CAS-capable van "
                "(OP_ROW_CAS); claim refused on this server")
        if swapped:
            self.ctrl_inc = observed + 1
            self.ctrl_beat = 1
            self._m_promoted.inc()
            return True
        self.ctrl_inc = int(actual[_mb.R_CINC])
        self.ctrl_beat = int(actual[_mb.R_CBEAT])
        self._advance = time.monotonic()
        self._m_fenced.inc()
        return False

    def _bridge_beats(self, stop: threading.Event) -> None:
        """Between winning the claim and the takeover's own service
        beating the row, the promoted incarnation must not look SILENT
        — a second standby whose clock expired just behind ours would
        otherwise claim on top of a takeover already in flight (a
        legitimate sequential claim, but a pointless fleet steal).
        Beat via CAS so the write lands ONLY while the row still holds
        our incarnation: the moment the takeover's claim bumps it, the
        CAS fails and the bridge stops — it can never clobber the
        successor."""
        beat = self.ctrl_beat
        mine = self.ctrl_inc
        while not stop.is_set():
            beat = (beat + 1) % (1 << 20)
            desired = np.zeros(_mb.MEMBER_DIM, np.float32)
            desired[_mb.R_CINC] = mine
            desired[_mb.R_CBEAT] = beat
            desired[_mb.R_CPID] = os.getpid() % (1 << 24)
            try:
                swapped, _ = self._table.row_cas(
                    self.n_slots + 1, _mb.R_CINC, float(mine), desired)
            except Exception:
                swapped = True  # transient wire: keep bridging
            if not swapped:
                return  # the takeover owns the row now
            stop.wait(0.1)

    def _invoke_takeover(self):
        kw = dict(self.takeover_kwargs)
        if self.plane == "serving":
            from hetu_tpu.serve.crosshost import CrossProcessServingPool
            return CrossProcessServingPool.takeover(
                workdir=self.workdir, port=self.port, **kw)
        if self.plane == "elastic":
            from hetu_tpu.resilience.multicontroller import (
                MultiControllerElasticSupervisor,
            )
            return MultiControllerElasticSupervisor.takeover(
                workdir=self.workdir, port=self.port, **kw)
        from hetu_tpu.parallel.mpmd_elastic import MPMDPipelineSupervisor
        return MPMDPipelineSupervisor.takeover(
            workdir=self.workdir, port=self.port, **kw)

    # ---- the loop ----
    def run_once(self) -> Optional[str]:
        """One watch step: observe, and when the lease is silent run
        the claim.  Returns the outcome once decided."""
        try:
            self.observe()
        except Exception:
            # an unreadable blackboard is NOT controller silence — the
            # van may be failing over under us; freeze the clock (the
            # next successful read restarts it) rather than promote on
            # blindness
            self._advance = time.monotonic()
            return None
        if not self.silent():
            return None
        t0 = trace.now_us()
        if self.try_claim():
            self.outcome = "promoted"
            trace.complete("standby.promote", t0,
                           {"incarnation": self.ctrl_inc,
                            "plane": self.plane}, cat="ctrl")
            bridge_stop = threading.Event()
            bridge = threading.Thread(target=self._bridge_beats,
                                      args=(bridge_stop,), daemon=True)
            bridge.start()
            try:
                self.adopted = self._invoke_takeover()
            finally:
                bridge_stop.set()
        else:
            self.outcome = "fenced"
        return self.outcome

    def watch(self, timeout_s: float = 600.0) -> str:
        """Block until promoted or fenced (or the budget lapses —
        outcome ``"timeout"``)."""
        deadline = time.monotonic() + float(timeout_s)
        while not self._stop.is_set() and time.monotonic() < deadline:
            out = self.run_once()
            if out is not None:
                return out
            time.sleep(self.poll_s)
        return self.outcome or "timeout"

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        if self._own_table:
            try:
                self._table.close()
            except Exception:
                pass


def standby_main(config_path: str) -> int:
    """Entry point for a spawned STANDBY process.  Config names the
    blackboard (membership_table/n_slots/port — or a workdir whose
    member spawn configs carry them), the plane, and the lease bound.
    Markers: ``READY`` (armed) → ``PROMOTED``/``FENCED`` →
    ``ALLDONE`` (serving plane: every adopted request resolved)."""
    cfg = json.loads(open(config_path).read())
    workdir = cfg["workdir"]
    # the durable tier's flight recorder covers the standby too: the
    # promotion evidence must survive even if THIS process is killed
    # right after acting (satellite of the observability plane)
    trace.open_process_stream(workdir, f"standby_p{os.getpid()}")
    spec = dict(cfg)
    if "membership_table" not in spec:
        # serving plane: every control-plane id is in the member spawn
        # configs on disk, same discovery the takeover classmethod uses
        from pathlib import Path

        from hetu_tpu.serve.crosshost import MemberSpec
        cfgs = sorted(Path(workdir).glob("member_*.json"),
                      key=lambda p: p.stat().st_mtime)
        ms = MemberSpec.from_json(cfgs[-1].read_text())
        spec["membership_table"] = ms.membership_table
        spec["n_slots"] = ms.n_slots
        spec.setdefault("van", ms.van or None)
    sb = StandbyController(
        workdir=workdir, port=int(cfg["port"]),
        plane=cfg.get("plane", "serving"),
        membership_table=int(spec["membership_table"]),
        n_slots=int(spec["n_slots"]),
        lease_bound_s=float(cfg.get("lease_bound_s", 2.0)),
        poll_s=float(cfg.get("poll_s", 0.1)),
        van_spec=spec.get("van"),
        takeover_kwargs=cfg.get("takeover_kwargs"))
    print("READY", flush=True)
    try:
        out = sb.watch(timeout_s=float(cfg.get("watch_timeout_s",
                                               600.0)))
    except Exception:
        traceback.print_exc()
        print("FENCED", flush=True)  # never won: stood down
        sb.close()
        return 3
    if out != "promoted":
        print("FENCED" if out == "fenced" else "TIMEOUT", flush=True)
        sb.close()
        return 3 if out == "fenced" else 2
    print("PROMOTED", flush=True)
    rc = 0
    try:
        if sb.plane == "serving" and sb.adopted is not None:
            results = sb.adopted.wait_adopted(
                timeout_s=float(cfg.get("resolve_timeout_s", 120.0)))
            # one rid → status map covering BOTH sources of truth: the
            # ledger's pre-kill resolutions and the adoptions resolved
            # under this incarnation (the loss-accounting surface)
            statuses = dict(sb.adopted.takeover_report.get("resolved",
                                                           {}))
            statuses.update({str(k): v.get("status")
                             for k, v in results.items()})
            print("RESOLVED", json.dumps(statuses), flush=True)
        print("ALLDONE", flush=True)
        if sb.plane == "serving" and sb.adopted is not None:
            # keep serving (and beating the controller row) for the
            # configured hold — the promoted incarnation must not go
            # silent the moment the adoption resolves, or a trailing
            # standby would claim a fleet that just changed hands
            hold = float(cfg.get("hold_s", 0.0))
            t_end = time.monotonic() + hold
            while time.monotonic() < t_end and not sb.adopted.fenced:
                time.sleep(0.05)
    except Exception:
        traceback.print_exc()
        rc = 1
    finally:
        if sb.adopted is not None:
            try:
                sb.adopted.close()
            except Exception:
                traceback.print_exc()
        sb.close()
    return rc


if __name__ == "__main__":
    import sys
    sys.exit(standby_main(sys.argv[1]))
