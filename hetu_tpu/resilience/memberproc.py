"""Shared control-plane loop for cross-process member processes.

Both lockstep training planes — the dp multi-controller worker
(:mod:`hetu_tpu.resilience.multicontroller`) and the MPMD pipeline
stage (:mod:`hetu_tpu.parallel.mpmd_elastic`) — speak the same member
protocol: heartbeat into a blackboard row on a cadence, honor the
control row's netem slow-link fields, ack PREPARE epochs with frozen
progress, and wait out generation-counted van barriers while
re-checking the control row so a membership move voids the in-flight
step.  This module is the ONE copy of that protocol; the step BODY
(what a member computes between barriers) stays with each plane.

A member class mixes in :class:`ControlPlaneMember`, calls
``_init_control_plane`` after its blackboard join is constructed, and
uses ``_epoch_barriers`` / ``_await_barrier`` / ``_check_epoch`` in its
run loop.  The shared methods read these attributes: ``member``
(:class:`~hetu_tpu.ps.membership.MembershipClient`), ``committed``,
``epoch``, ``acked``, ``_work_ms``, and a spec-like object with
``hb_ms``, ``port``, ``barrier_base``, ``barrier_wait_s``.
"""

from __future__ import annotations

import threading
import time


class EpochChanged(Exception):
    """The controller published a new membership epoch (or a PREPARE
    freeze) mid-step: the in-flight step is void (never logged/
    committed) and re-runs after the member adopts the new epoch."""


class ControlPlaneMember:
    """Mixin: heartbeat thread, slow-link honoring, epoch-scoped
    barrier pair, and movement-aware barrier waits."""

    def _init_control_plane(self, *, van, netem_local: str,
                            my_slot: int) -> None:
        self._van = van
        self._my_slot = int(my_slot)
        self.committed = -1
        self.epoch = 0
        self.acked = 0
        self._bars = None      # (key, sync_barrier, commit_barrier)
        # replicated durable tier: resolve the per-process VanReplica
        # (same cached instance the tables/membership already share) so
        # barriers can re-key and re-dial across a primary promotion
        self._replica = None
        self._van_voided = None   # epoch whose step a promotion voided
        van_spec = getattr(self.spec, "van", None)
        if van_spec:
            from hetu_tpu.ps.replica import VanReplica
            self._replica = VanReplica.from_spec(van_spec)
        # the scalar WORK time reported in the heartbeat's load field —
        # work time only, barrier/mailbox waits excluded: a fast member
        # parked on a slow peer must not itself read as slow
        self._work_ms = 0.0
        # the injected slow link (control row C_SLOW_*): a NetEm
        # latency policy on this member's van ops — the fault is a slow
        # WIRE, not a sleep in the math, so detection sees exactly what
        # a congested DCN link would produce
        from hetu_tpu.ps.netem import NetEm
        self.netem = NetEm(local=netem_local, peer="van")
        self.netem.install()
        self._slow_ms_active = 0
        self._stop = threading.Event()

    def _start_beat(self) -> None:
        self._beat = threading.Thread(target=self._beat_loop,
                                      daemon=True)
        self._beat.start()

    def _beat_loop(self) -> None:
        period = max(self.spec.hb_ms, 10) / 1000.0
        while not self._stop.wait(period):
            try:
                self._sync_row()
            except Exception:
                time.sleep(period)  # silence IS the loss signal

    def _sync_row(self) -> None:
        self.member.heartbeat(committed=float(self.committed),
                              epoch_ack=float(self.acked),
                              load=float(self._work_ms))

    def _apply_slow(self, slow_slot: int, slow_ms: int) -> None:
        """Honor the control row's straggler-injection fields: install
        (or clear) a symmetric latency policy on this member's van
        link.  Idempotent per published value."""
        from hetu_tpu.ps.netem import LinkPolicy
        want = int(slow_ms) if (int(slow_slot) == self._my_slot and
                                int(slow_ms) > 0) else 0
        if want == self._slow_ms_active:
            return
        if want:
            self.netem.set_link(LinkPolicy(latency_s=want / 1000.0),
                                direction="both")
        else:
            self.netem.clear()
        self._slow_ms_active = want

    def _van_endpoint(self):
        """Where barriers dial: the replica pair's CURRENT primary, or
        the spec's fixed port when the plane runs unreplicated."""
        if self._replica is not None:
            host, port = self._replica.primary
            return host, port
        return "127.0.0.1", self.spec.port

    def _van_gen(self) -> int:
        """The van-generation band of barrier ids: 0 before any
        failover (incarnation 1 — ids are unchanged from the
        unreplicated plane), +1 per promotion.  A promoted van has NONE
        of the old van's arrival-generation state, so re-arriving at
        the OLD ids would resume someone else's generation counter;
        re-keying by ``(van_gen, epoch, phase)`` makes every member
        arrive at FRESH ids on the new primary instead — idempotently,
        because the voided step re-runs in full."""
        if self._replica is not None:
            return max(self._replica.incarnation - 1, 0)
        return 0

    def _barrier(self, phase: int, width: int):
        bid = (self.spec.barrier_base + self._van_gen() * (1 << 21)
               + 2 * self.epoch + phase)
        host, port = self._van_endpoint()
        return self._van.RemoteBarrier(host, port, bid, width)

    def _epoch_barriers(self, width: int):
        """The (sync, commit) barrier pair for the CURRENT (van_gen,
        epoch), cached — barrier ids and widths only change with the
        epoch or a van promotion, and opening two fresh van connections
        per STEP would put hundreds of connect/close cycles per second
        on the hot path."""
        key = (self._van_gen(), self.epoch)
        if self._bars is None or self._bars[0] != key:
            self._close_barriers()
            self._bars = (key, self._barrier(0, width),
                          self._barrier(1, width))
        return self._bars[1], self._bars[2]

    def _close_barriers(self) -> None:
        if self._bars is not None:
            for bar in self._bars[1:]:
                try:
                    bar.close()
                except Exception:
                    pass
            self._bars = None

    def _park_if_headless(self) -> bool:
        """Freeze at this step boundary while the CONTROLLER is silent
        (its blackboard beat stopped for ``spec.ctrl_lease_s``): a
        headless fleet must not race a takeover's re-freeze mid-step.
        Parking is pure waiting — the beat thread keeps heartbeating
        and the member's own lease stays live.

        Unparking is incarnation-aware: beats from the SAME incarnation
        mean the controller never died (a GC pause, a slow poll) —
        nothing was missed, continue immediately.  Beats from a NEW
        incarnation mean a takeover is in progress: hold until its
        republish (a new epoch, or a PREPARE) lands, so the takeover's
        freeze can never interleave with a half-run step — the one
        ordering that would turn a clean controller death into an
        at-least-once gradient (weight-byte-identity cannot absorb a
        post-push discard).  Returns True when a park happened (the
        caller re-reads the control row and continues).  Disabled when
        ``spec.ctrl_lease_s`` is 0/absent."""
        bound = float(getattr(self.spec, "ctrl_lease_s", 0.0) or 0.0)
        if bound <= 0.0 or not self.member.controller_silent(bound):
            return False
        self.parks = getattr(self, "parks", 0) + 1
        parked_inc = self.member.ctrl_inc
        parked_epoch = self.epoch
        while not self._stop.is_set():
            try:
                ctl = self.member.read_control()
            except Exception:
                ctl = None  # an unreachable van parks too; the beat
                # thread keeps trying — silence is judged on beats
            if ctl is None or self.member.controller_silent(bound):
                self._stop.wait(0.05)
                continue
            if self.member.ctrl_inc == parked_inc:
                break  # the same controller resumed: no takeover, no
                # republish coming — just continue
            if ctl[0] != parked_epoch or ctl[4] != 0:
                break  # the takeover's republish landed: the next
                # control read freezes/acks it at this boundary
            self._stop.wait(0.02)
        return True

    def _hold_for_republish(self, e: int, phase: int) -> bool:
        """True while the member should idle at its loop top after a
        promotion-driven step void: the controller (which learns of the
        promotion through its own replica callback) republishes a fresh
        epoch, and only THAT epoch's re-run is safe to log.  The hold
        never blocks a PREPARE — the loop's phase branch runs first, so
        the republish's ack path stays live."""
        if self._van_voided is None:
            return False
        if phase != 0 or e != self._van_voided:
            self._van_voided = None
            return False
        return True

    def _check_epoch(self) -> None:
        """Raise :class:`EpochChanged` when the controller moved the
        membership (new epoch OR a prepare freeze) — the in-flight step
        is then void."""
        e, _, _, _, phase, _, _ = self.member.read_control()
        if e != self.epoch or phase != 0:
            raise EpochChanged

    def _await_barrier(self, bar) -> None:
        """Wait out one lockstep barrier, re-checking the control row
        between short waits.  The generation-counted van barrier
        withdraws timed-out arrivals, so lockstep cannot release
        short-handed.  Transport failures run the replica failover
        dance: once the primary changes the in-flight step is void
        (:class:`EpochChanged`) and the re-run arrives at the re-keyed
        barrier ids on the promoted van."""
        faults = 0
        while True:
            try:
                bar.wait(timeout_s=self.spec.barrier_wait_s)
                return
            except TimeoutError:
                self._check_epoch()
            except (ConnectionError, RuntimeError) as e:
                faults += 1
                self._wire_fault(e, faults=faults)

    def _wire_fault(self, e: BaseException, *, faults: int = 1) -> None:
        """A van op (barrier wait, mailbox, table) failed transport-
        wise mid-step.  With a replicated durable tier, run the
        failover dance; once the primary changed, drop the stale
        barrier handles and void the step.  Without a replica (or for
        a non-wire error) the failure propagates — the van is the
        single point of failure it always was."""
        from hetu_tpu.ps.replica import _is_wire_error
        wire = _is_wire_error(e) or (isinstance(e, RuntimeError)
                                     and "rc=" in str(e))
        if self._replica is None or not wire:
            raise e
        if self._replica.failover(e):
            self._close_barriers()
            # hold the re-run until the controller republishes: a
            # re-run at the OLD epoch would write a same-epoch
            # duplicate of the voided step's consumed record (the dp
            # plane's complete-cover evidence tolerates crash residue
            # ACROSS epochs, not same-epoch duplicates)
            self._van_voided = self.epoch
            raise EpochChanged from e
        if faults > 120:
            raise e  # the van is alive and the op persistently fails:
            # this is not a failover, surface the real error
        # not promoted yet (detection grace window): give the dance a
        # beat, then re-check the control row — a controller-driven
        # move can land while the pair is still deciding.  An
        # unreachable van parks the check too; the next wait retries.
        time.sleep(0.05)
        try:
            self._check_epoch()
        except EpochChanged:
            raise
        except Exception:
            pass

    def _close_control_plane(self) -> None:
        self._close_barriers()
        self.member.close()
        self.netem.uninstall()


def drive_controller_harness(poll, progress, done, *,
                             deadline_s: float,
                             on_progress=None) -> int:
    """The ONE copy of the spawned-controller chaos-harness drive loop
    (the controller half of this module's member protocol).  Both
    training planes' ``--controller`` entry points delegate here, so
    the marker contract the chaos tests key on cannot drift between
    them: ``READY`` once the caller's supervisor is built (the spawn
    handshake), ``STEP <p>`` per ``progress()`` change, ``DEADLINE``
    (rc 2) when
    the fleet never finishes inside ``deadline_s`` — an ``ALLDONE``
    there would mask the hang as completion — ``ALLDONE`` then hold
    (the harness kills us, or we get fenced), and ``FENCED`` (rc 3)
    on :class:`~hetu_tpu.ps.membership.ControllerFenced` WITHOUT any
    fleet teardown: a fenced zombie's close() would kill member
    processes the new incarnation now owns.

    ``on_progress(p)`` is the per-plane edge hook (e.g. the elastic
    harness's publish-PREPARE-then-hang mode); it may never return.
    """
    from hetu_tpu.ps import membership as _mb
    print("READY", flush=True)
    deadline = time.monotonic() + float(deadline_s)
    last = object()
    finished = False
    try:
        while time.monotonic() < deadline:
            poll()
            p = progress()
            if p != last:
                last = p
                print(f"STEP {p}", flush=True)
            if on_progress is not None:
                on_progress(p)
            if done():
                finished = True
                break
            time.sleep(0.03)
        if not finished:
            print("DEADLINE", flush=True)
            return 2
        print("ALLDONE", flush=True)
        while True:
            poll()
            time.sleep(0.05)
    except _mb.ControllerFenced:
        print("FENCED", flush=True)
        return 3
