"""Elastic mesh resharding: survive permanent DP-worker loss (and rejoin)
by reforming the mesh at the surviving width instead of aborting.

PR 2's :class:`~hetu_tpu.resilience.supervisor.Supervisor` retries, guards,
and checkpoints — but the device mesh is fixed for the life of the run, so
a PERMANENTLY lost data-parallel worker still kills it.  On preemptible
TPU fleets permanent loss is the common case, and
checkpoint-restart-at-the-same-size is not an answer: arxiv 2004.13336
shows a replica-count change is fundamentally a RESHARDING of (optimizer)
state, and arxiv 2412.14374 motivates mesh membership as a runtime input.

:class:`ElasticSupervisor` closes that gap.  Per step, BEFORE the guard
polls and the batch fetch, it drains membership events — injected
``worker_loss``/``worker_join`` chaos faults (authoritative) and
:class:`MembershipMonitor` promotions of repeated PSShardGuard/van
failures — and, when the alive set changed, runs the resharding step:

1. snapshot the live :class:`~hetu_tpu.train.executor.TrainState`
   host-side (params, optimizer state, step counter, RNG — ``np.asarray``
   per leaf, so nothing references the old mesh's buffers);
2. reform the mesh at the surviving width
   (:func:`~hetu_tpu.parallel.mesh.elastic_mesh` — survivors keep their
   exact devices, only the lost/joined worker's placement changes);
3. re-place the state under the new mesh with ``jax.device_put`` and
   point the executor at it (``Executor.set_mesh`` drops every compiled
   step — shardings are baked at trace time, so the next ``run()``
   re-jits at the new width);
4. re-partition the data: with an :class:`ElasticBatchSchedule` the
   GLOBAL batch sequence is a pure function of (seed, step) — a resize
   only changes how each global batch is sliced over survivors, so a
   4→3→4 run consumes byte-identical global batches in the same order as
   a run that never resized.  In ``fixed_per_worker`` mode (global batch
   = per-worker batch × width) the gradient is instead rescaled by
   nominal/current width (``Executor.set_grad_scale``) so a
   sum-over-nominal-batch loss keeps its scale across the shrink.

Checkpoints record the live DP width (``extra['dp_width']``) and restore
at a DIFFERENT width — leaves are global arrays, so restore re-places them
under whatever mesh the membership says (train/checkpoint.py's
width-portability contract).

Determinism: membership events come from the seeded
:class:`~hetu_tpu.resilience.faults.FaultSchedule` (``to_json`` is
byte-stable), the batch schedule is seeded and width-invariant, and the
RNG rides the TrainState — an elastic chaos run replays exactly.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np

from hetu_tpu.parallel.mesh import (
    AXIS_DP, MeshConfig, elastic_mesh, host_to_device, replicated,
)
from hetu_tpu.resilience.supervisor import Supervisor
from hetu_tpu.telemetry import trace


class ElasticReshardError(RuntimeError):
    """The mesh cannot be reformed at the requested membership — every
    worker lost, a join for a worker that is present, or a global batch
    that does not divide by the surviving width."""


@dataclass
class ResizeEvent:
    """One completed resize, for reports/benches: detect→resharded wall
    time ``downtime_s`` EXCLUDES the next step's re-jit (the bench times
    detect → resharded → next completed step around the run loop)."""

    step: int
    kind: str                   # "shrink" | "grow"
    worker: int
    width: int                  # width AFTER the resize
    downtime_s: float
    alive: tuple = field(default_factory=tuple)


class MembershipMonitor:
    """Promotes failure evidence into resize decisions.

    Two input planes, mirroring how loss actually shows up:

    * :meth:`inject` — an AUTHORITATIVE membership event (the chaos
      harness's seeded ``worker_loss``/``worker_join``, or a cluster
      scheduler's notification): decided immediately.
    * :meth:`report_failure` / :meth:`report_ok` — circumstantial
      evidence (a PSShardGuard shard staying dead, van retries exhausting
      against one worker's endpoint).  ``fail_threshold`` CONSECUTIVE
      failure reports with no intervening ok promote to a loss decision —
      one flaky poll never reshapes the fleet.

    The monitor tracks the alive set itself so double-loss / double-join
    are rejected here, once, instead of in every caller.
    """

    def __init__(self, nominal_dp: int, *, fail_threshold: int = 3):
        if nominal_dp < 1:
            raise ValueError("nominal_dp must be >= 1")
        self.nominal_dp = int(nominal_dp)
        self.fail_threshold = int(fail_threshold)
        self.alive: set[int] = set(range(self.nominal_dp))
        self._fails: dict[int, int] = defaultdict(int)
        self._decisions: deque = deque()

    def inject(self, kind: str, worker: int) -> None:
        worker = int(worker)
        if kind == "loss":
            if worker in self.alive:
                self.alive.discard(worker)
                self._decisions.append(("loss", worker))
        elif kind == "join":
            if not 0 <= worker < self.nominal_dp:
                raise ElasticReshardError(
                    f"worker {worker} outside the nominal fleet "
                    f"[0, {self.nominal_dp})")
            if worker not in self.alive:
                self.alive.add(worker)
                self._fails.pop(worker, None)
                self._decisions.append(("join", worker))
        else:
            raise ValueError(f"unknown membership event kind {kind!r}")

    def report_failure(self, worker: int) -> None:
        worker = int(worker)
        if worker not in self.alive:
            return  # already decided lost
        self._fails[worker] += 1
        if self._fails[worker] >= self.fail_threshold:
            self.inject("loss", worker)

    def report_ok(self, worker: int) -> None:
        self._fails.pop(int(worker), None)

    def pop_decisions(self) -> list:
        out = list(self._decisions)
        self._decisions.clear()
        return out


class ElasticSupervisor(Supervisor):
    """:class:`Supervisor` whose mesh membership is a runtime input.

    Usage::

        config = MeshConfig(dp=4)
        ex = Executor(loss_fn, opt)            # mesh installed by the sup
        schedule = ElasticBatchSchedule((X, Y), global_batch, seed=0)
        sup = ElasticSupervisor(ex, config=config, schedule=schedule,
                                injector=FaultInjector(faults), ...)
        rep = sup.run(state, lambda i: dict_batch(schedule.global_batch(i)),
                      steps)

    ``data_mode``:

    * ``"fixed_global_batch"`` (default): every step consumes the same
      global batch whatever the width (use :class:`ElasticBatchSchedule`);
      the global batch must divide by every reachable width — validated
      at construction against 1..nominal_dp when a schedule is given,
      else at each resize.
    * ``"fixed_per_worker"``: the global batch is per-worker × width, so
      a shrink feeds fewer examples per step; gradients are rescaled by
      nominal/current width so a loss summed over the nominal global
      batch keeps its scale (a mean-loss run may prefer scale 1 — pass
      ``rescale_grads=False``).

    PSShardGuard/van failure promotion: ``shard_workers`` maps a guard's
    PS shard index to the DP worker hosting it; a shard that stays dead
    ``monitor.fail_threshold`` consecutive polls promotes that worker's
    loss.  Without the map, only injected events and explicit
    ``monitor.report_failure`` calls reshape the fleet.
    """

    def __init__(self, executor, *, config: MeshConfig,
                 devices: Optional[Sequence] = None,
                 schedule=None, data_mode: str = "fixed_global_batch",
                 rescale_grads: bool = True,
                 monitor: Optional[MembershipMonitor] = None,
                 fail_threshold: int = 3,
                 shard_workers: Optional[dict] = None,
                 min_width: int = 1, **kw):
        super().__init__(executor, **kw)
        if data_mode not in ("fixed_global_batch", "fixed_per_worker"):
            raise ValueError(f"unknown data_mode {data_mode!r}")
        self.config = config
        self.devices = (np.asarray(devices) if devices is not None
                        else np.asarray(jax.devices()))
        self.schedule = schedule
        self.data_mode = data_mode
        self.rescale_grads = bool(rescale_grads)
        self.min_width = int(min_width)
        self.monitor = monitor or MembershipMonitor(
            config.dp, fail_threshold=fail_threshold)
        self.shard_workers = dict(shard_workers or {})
        self._guard_dead_polls: dict[int, int] = defaultdict(int)
        self.resizes: list[ResizeEvent] = []
        if schedule is not None and data_mode == "fixed_global_batch":
            for w in range(max(self.min_width, 1), config.dp + 1):
                schedule.check_width(w)
        # install the nominal mesh (or adopt a caller-installed one at the
        # nominal width) so step 0 already runs under elastic management
        if executor.mesh is None:
            executor.set_mesh(elastic_mesh(config, sorted(self.monitor.alive),
                                           devices=self.devices))
        self.counters["elastic_width"] = len(self.monitor.alive)

    # ---- membership → resharding ----
    @property
    def width(self) -> int:
        return len(self.monitor.alive)

    def rank_of(self, worker: int) -> int:
        """Worker's slot in the CURRENT mesh (its dp coordinate) — the
        rank survivors use for ``ElasticBatchSchedule.local_slice``."""
        alive = sorted(self.monitor.alive)
        if worker not in alive:
            raise ElasticReshardError(f"worker {worker} is not alive")
        return alive.index(worker)

    def _promote_guard_failures(self) -> None:
        """PSShardGuard evidence: a shard pending repair for another poll
        is one failure strike against the worker hosting it; a shard no
        longer pending clears its worker's strikes."""
        if not self.shard_workers:
            return
        pending = set()
        for g in self.guards:
            pending |= set(getattr(g, "_pending", ()))
        for shard, worker in self.shard_workers.items():
            if shard in pending:
                self.monitor.report_failure(worker)
            else:
                self.monitor.report_ok(worker)

    def _maybe_resize(self, state, step_i: int):
        if self.injector is not None and \
                hasattr(self.injector, "pop_worker_events"):
            for kind, worker in self.injector.pop_worker_events():
                self.monitor.inject(kind, worker)
        self._promote_guard_failures()
        decisions = self.monitor.pop_decisions()
        if not decisions:
            return state
        # ONE reshard for the whole batch: monitor.alive already reflects
        # every drained decision, so a loss+join landing on the same step
        # costs one snapshot/re-place/re-jit, not one per event.  Each
        # decision still gets its own ResizeEvent (the membership deltas),
        # all stamped with the post-batch width and sharing the downtime.
        t0 = time.perf_counter()
        with trace.span("elastic.reshard") as sp:
            sp.set("step", int(step_i))
            sp.set("width", self.width)
            sp.set("decisions",
                   [f"{k}:{w}" for k, w in decisions])
            state = self._reshard(state)
        dt = time.perf_counter() - t0
        self.counters["resizes"] += 1
        self.counters["elastic_width"] = self.width
        self.counters["resize_downtime_s_last"] = dt
        self._log_inc("resizes")
        if self.logger is not None:
            self.logger.log({"elastic_width": self.width,
                             "resize_downtime_s": dt}, step=step_i)
        for kind, worker in decisions:
            ev = ResizeEvent(
                step=step_i, kind="shrink" if kind == "loss" else "grow",
                worker=int(worker), width=self.width, downtime_s=dt,
                alive=tuple(sorted(self.monitor.alive)))
            self.resizes.append(ev)
            self.counters[f"resizes_{ev.kind}"] += 1
        return state

    def _reshard(self, state):
        """Snapshot host-side → reform mesh → re-place → re-jit."""
        alive = sorted(self.monitor.alive)
        if len(alive) < max(self.min_width, 1):
            raise ElasticReshardError(
                f"only {len(alive)} of {self.config.dp} workers alive "
                f"(min_width={self.min_width}); cannot reform the mesh")
        width = len(alive)
        if self.schedule is not None and \
                self.data_mode == "fixed_global_batch":
            self.schedule.check_width(width)
        # host-side snapshot: every leaf leaves the old mesh's buffers
        # before the new placement (params, optimizer slots, step, RNG).
        # np.array(copy=True) is load-bearing: np.asarray(jax_cpu_array)
        # is a zero-copy VIEW of the device buffer.  The re-place goes
        # through host_to_device, which guards the CPU
        # zero-copy-adoption + donation hazard (see parallel/mesh.py).
        with trace.span("elastic.snapshot"):
            host = jax.tree_util.tree_map(lambda a: np.array(a, copy=True),
                                          state)
        with trace.span("elastic.remesh") as sp:
            sp.set("width", width)
            mesh = elastic_mesh(self.config, alive, devices=self.devices)
            # set_mesh drops every compiled step: the NEXT run() pays the
            # re-jit (its train.compile instant + step span show the cost)
            self.executor.set_mesh(mesh)
            if self.data_mode == "fixed_per_worker" and self.rescale_grads:
                self.executor.set_grad_scale(self.config.dp / width)
        with trace.span("elastic.replace"):
            sharding = replicated(mesh)
            return jax.tree_util.tree_map(
                lambda a: host_to_device(a, sharding), host)

    # ---- checkpoints carry the width ----
    def _ckpt_extra(self) -> dict:
        return {"dp_width": self.width,
                "alive": sorted(self.monitor.alive),
                "nominal_dp": self.config.dp}

    def run(self, state, batch_fn, steps, **kw):
        if batch_fn is None and self.schedule is not None:
            batch_fn = self.schedule.global_batch
        # place the caller's state (the restore TEMPLATE too: checkpoint
        # leaves re-place to the template's shardings) under the CURRENT
        # mesh — a width-3 checkpoint restoring into a width-4 run lands
        # replicated over the width-4 mesh, not wherever the template's
        # buffers happened to live.  host_to_device: the caller may hand
        # numpy leaves, and the donated train step must never free a
        # numpy-owned buffer (see parallel/mesh.py)
        if self.executor.mesh is not None:
            sharding = replicated(self.executor.mesh)
            state = jax.tree_util.tree_map(
                lambda a: host_to_device(a, sharding), state)
        rep = super().run(state, batch_fn, steps, **kw)
        rep.counters.setdefault("elastic_width", self.width)
        return rep
