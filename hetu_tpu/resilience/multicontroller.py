"""Multi-controller elastic training: each dp worker is its own process.

:class:`~hetu_tpu.resilience.elastic.ElasticSupervisor` reshapes ONE
process's mesh; this module is the cross-process promotion the ROADMAP
names (arXiv 2412.14374's multi-controller coordination over DCN, with
arXiv 2004.13336's width-as-resharding contract): N worker PROCESSES
coordinate through the van — weights and optimizer state live on a PS
table (which is what makes membership change cheap: resharding moves no
parameter bytes, only the DATA partition), membership crosses the
:mod:`hetu_tpu.ps.membership` blackboard, and steps synchronize on van
barriers.

The determinism contract is PR 3's, now literal across processes: the
global batch sequence is a pure function of ``(seed, step)``
(:class:`~hetu_tpu.data.dataloader.ElasticBatchSchedule`), and a resize
only re-slices each global batch over the survivors.  Every worker
appends a CONSUMED record (step, epoch, width, rank, slice CRC) to its
log right after its gradient push — the record is evidence the slice's
bytes entered training.  :func:`check_complete_cover` then asserts the
cross-run invariant: every step in order carries a COMPLETE cover (one
width, every rank, each slice CRC equal to the width-invariant
schedule's bytes) at the step's LATEST epoch — so the committed batch
sequence is byte-identical to a never-resized run.  A step a worker
died inside may additionally carry partial records from the aborted
epoch (torn state at SIGKILL is unknowable; the epoch that re-ran the
step is the committed one): gradient application across a crash step is
AT-LEAST-ONCE (benign — the PS-side SGD is linear, a re-pushed slice is
a second small step, not corruption), while batch-sequence consumption
is exactly-once.

Per step, per worker::

    sync barrier(epoch)  →  pull weights  →  grad on local_slice(step,
    rank, width)  →  push grad  →  commit barrier(epoch)  →  write the
    commit to the blackboard  →  log + step+1

Barrier ids encode ``(epoch, phase)``: a worker that timed out (peer
suspended/killed) re-reads the control row — if the controller moved the
membership, the in-flight step is DISCARDED (never logged) and re-runs
at the new width from ``resume_step``; otherwise it simply re-waits.
The generation-counted van barrier withdraws timed-out arrivals, so
lockstep cannot release short-handed.

Epoch transitions are TWO-PHASE, because ``resume_step`` must be exact:
the fleet keeps committing steps while the controller deliberates, so a
resume computed from racing progress reports would re-run (or skip) a
global batch.  The controller first publishes the new epoch with
``phase=PREPARE``: every worker stops at its next step boundary and
acks with its frozen committed step; only when every present worker has
acked does the controller publish ``phase=0`` with ``resume_step =
max(frozen committed) + 1`` — commits are barrier-atomic, so the frozen
values agree and the resume is exact for survivors and rejoiners alike.

The CONTROLLER process (:class:`MultiControllerElasticSupervisor`) owns
no training math at all: it spawns workers, watches leases, and
publishes membership epochs — worker SIGKILL → lease expiry →
``elastic.reshard`` span (ends when every survivor acked the new epoch)
→ survivors reshard; a replacement process joins with a fresh
incarnation and is re-admitted and re-placed the same way.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import time
import traceback
import zlib
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

from hetu_tpu.ps import membership as _mb
from hetu_tpu.resilience.memberproc import (
    ControlPlaneMember, EpochChanged as _EpochChanged,
    drive_controller_harness,
)
from hetu_tpu.telemetry import trace

WEIGHTS_TABLE_ID = 0x57454947          # 'WEIG'
BARRIER_BASE = 0x42415252              # 'BARR'


@dataclass
class WorkerSpec:
    """Everything a worker process needs — JSON into the spawn config.
    The dataset is REGENERATED from ``data_seed`` in every process
    (deterministic), so no training bytes ever cross the spawn
    boundary; only the PS table does."""

    port: int
    slot: int
    n_slots: int
    steps: int
    global_batch: int
    features: int = 8
    out_dim: int = 4
    n_samples: int = 256
    data_seed: int = 0
    lr: float = 0.05
    hb_ms: int = 80
    membership_table: int = _mb.TRAIN_MEMBERSHIP_TABLE
    weights_table: int = WEIGHTS_TABLE_ID
    barrier_base: int = BARRIER_BASE
    barrier_wait_s: float = 0.5
    # per-step throttle: a CPU-box fleet steps this tiny model at
    # 50-100 steps/s, far faster than any lease window — chaos tests
    # (and the bench's detect/recover timing) pace the fleet so faults
    # land INSIDE a run, not after it finished
    step_sleep_s: float = 0.0
    # park when the CONTROLLER's blackboard beat is silent this long
    # (0 disables): a headless fleet freezes at its next step boundary
    # and resumes on the first beat from ANY controller incarnation —
    # the member half of fenced control-plane takeover
    ctrl_lease_s: float = 0.0
    # rank-ordered gradient application: workers STAGE their gradients
    # (idempotent sparse_set into per-rank rows of `staging_table`),
    # barrier, then rank 0 applies them to the weights IN RANK ORDER
    # over one connection — f32 addition is not associative, so
    # arrival-order pushes reproduce same-seed runs only to ~1e-3;
    # rank order makes clean same-seed dp runs BITWISE identical
    # (the byte-identity level the MPMD plane already has)
    ordered_grads: bool = False
    staging_table: int = 0
    log_path: str = ""
    # replicated durable tier: a ReplicaSpec dict — non-empty means the
    # worker's blackboard + weights/staging tables dual-write over the
    # primary+backup van pair and re-resolve on primary death
    van: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "WorkerSpec":
        return cls(**json.loads(s))


def make_dataset(spec: WorkerSpec):
    """Seeded synthetic regression problem, identical in every process:
    ``Y = X @ W_true`` plus small noise."""
    rng = np.random.default_rng(spec.data_seed)
    X = rng.standard_normal((spec.n_samples, spec.features),
                            dtype=np.float32)
    w_true = rng.standard_normal((spec.features, spec.out_dim),
                                 dtype=np.float32)
    Y = X @ w_true + 0.01 * rng.standard_normal(
        (spec.n_samples, spec.out_dim), dtype=np.float32)
    return X, Y


def make_schedule(spec: WorkerSpec):
    from hetu_tpu.data.dataloader import ElasticBatchSchedule
    X, Y = make_dataset(spec)
    return ElasticBatchSchedule((X, Y), spec.global_batch,
                                seed=spec.data_seed)


def slice_crc(arrays) -> int:
    crc = 0
    for a in arrays:
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    return crc


# ---------------------------------------------------------------------------
# worker process
# ---------------------------------------------------------------------------

class WorkerProcess(ControlPlaneMember):
    """One dp worker: its own controller over its own slice (numpy math
    — the data plane here is the VAN, not the accelerator; the jax
    executor path stays with the in-process supervisors).  The member
    control plane (beats, slow-link honoring, epoch barriers) is the
    shared :class:`~hetu_tpu.resilience.memberproc.ControlPlaneMember`;
    this class owns the step body and the consumed-batch log."""

    def __init__(self, spec: WorkerSpec):
        from hetu_tpu.ps import van
        self.spec = spec
        self.schedule = make_schedule(spec)
        from hetu_tpu.ps.replica import open_table
        self.member = _mb.MembershipClient(
            "127.0.0.1", spec.port, table_id=spec.membership_table,
            slot=spec.slot, n_slots=spec.n_slots,
            replica=spec.van or None)
        self.table = open_table(
            spec.van, "127.0.0.1", spec.port, spec.features,
            spec.out_dim, table_id=spec.weights_table, create=False)
        self._staging = None
        if spec.ordered_grads and spec.staging_table:
            self._staging = open_table(
                spec.van, "127.0.0.1", spec.port,
                spec.n_slots * spec.features, spec.out_dim,
                table_id=spec.staging_table, create=False)
        self._sbar = None  # (epoch, stage barrier) — ordered_grads only
        self._init_control_plane(van=van, netem_local=f"w{spec.slot}",
                                 my_slot=spec.slot)
        # straggler plane: per-phase wall timing, logged per step
        self.phase_ms: dict = {}
        self._log = open(spec.log_path or
                         f"worker_{spec.slot}.jsonl", "a")
        self.member.join(committed=-1.0)
        self._start_beat()

    def run(self) -> None:
        spec = self.spec
        step = 0
        while not self._stop.is_set():
            e, width, mask, resume, phase, slow_slot, slow_ms = \
                self.member.read_control()
            self._apply_slow(slow_slot, slow_ms)
            if self._park_if_headless():
                continue  # controller silent: frozen at this boundary
                # until a (possibly new-incarnation) controller beats
            if e == 0:
                if self._stop.wait(0.05):
                    break
                continue
            if phase != 0:
                # PREPARE: freeze at this step boundary and ack with the
                # frozen progress (the controller computes the exact
                # resume from these rows)
                if self.acked < e:
                    self.acked = e
                    try:
                        self._sync_row()
                    except Exception:
                        pass  # the beat thread resends the ack in hb_ms
                if self._stop.wait(0.02):
                    break
                continue
            if self._hold_for_republish(e, phase):
                # a van promotion voided the in-flight step: wait for
                # the controller's republish before re-running it
                if self._stop.wait(0.02):
                    break
                continue
            if e != self.epoch:
                # the resume is EXACT (computed from frozen acks), so
                # adopting it never re-runs or skips a committed step
                self.epoch = e
                self.acked = max(self.acked, e)
                step = resume
            slots = _mb.MembershipService.slots_of(mask)
            if spec.slot not in slots:
                # excluded (evicted straggler): keep probing the van
                # link and report the probe time as load — the
                # probation loop's evidence that the link healed.  The
                # probe is a timed pull + zero-push pair, i.e. a real
                # step's WIRE cost (a zero gradient is a no-op on the
                # weights), so an injected slow link keeps the probe
                # honestly slow until it actually heals.  Probed every
                # loop iteration (faster than the beat cadence) so each
                # beat the controller counts carries a FRESH sample —
                # a throttled probe would let one lucky measurement be
                # double-counted toward re-admission.
                try:
                    t0 = time.perf_counter()
                    w = self.table.dense_pull()
                    self.table.dense_push(np.zeros_like(w))
                    self._work_ms = (time.perf_counter() - t0) * 1e3
                except Exception:
                    pass  # an unreachable van is the beat's problem
                if self._stop.wait(0.05):
                    break
                continue
            rank = slots.index(spec.slot)
            if step >= spec.steps:
                break
            bar_sync, bar_commit = self._epoch_barriers(width)
            pushed = False  # did THIS attempt's gradient land on the
            # tier?  read by the failover handler below: a step voided
            # after its push re-pushes on re-run — that duplicate was
            # invisible before PR 19 counted it
            try:
                t0 = time.perf_counter()
                self._await_barrier(bar_sync)
                t1 = time.perf_counter()
                Xb, Yb = self.schedule.local_slice(step, rank, width)
                w = self.table.dense_pull()
                t2 = time.perf_counter()
                err = Xb @ w - Yb
                # d/dw of mean_{GLOBAL batch} ||Xw - Y||^2: each
                # worker pushes its slice's share; the PS-side SGD is
                # linear, so N sequential pushes apply exactly the
                # summed global-mean gradient
                grad = (2.0 / spec.global_batch) * (Xb.T @ err)
                t3 = time.perf_counter()
                if self._staging is not None:
                    self._push_ordered(grad, rank, width)
                else:
                    self.table.dense_push(grad)
                pushed = True
                t4 = time.perf_counter()
                # the WORK phases only (pull/grad/push) feed the
                # heartbeat's load field: barrier waits are time spent
                # on PEERS, and charging them here would make every
                # healthy worker in a fleet with one straggler read as
                # a straggler itself
                self._work_ms = (t4 - t1) * 1e3
                # the consumption record lands BEFORE the commit
                # barrier: the push already happened, so if this
                # process is SIGKILLed parked in the barrier (whose
                # server-side arrival can still release the peers —
                # the ghost-arrival window), the evidence that its
                # slice entered training is on disk.  A record whose
                # step later re-runs at a new epoch is crash residue
                # check_complete_cover knowingly tolerates.
                self._log.write(json.dumps(
                    {"step": step, "epoch": self.epoch,
                     "width": width, "rank": rank,
                     "crc": slice_crc((Xb, Yb)),
                     "loss": float(np.mean(err * err)),
                     "ms": {"bar_sync": round((t1 - t0) * 1e3, 3),
                            "pull": round((t2 - t1) * 1e3, 3),
                            "grad": round((t3 - t2) * 1e3, 3),
                            "push": round((t4 - t3) * 1e3, 3)}}) + "\n")
                self._log.flush()
                self._await_barrier(bar_commit)
                self.phase_ms = {
                    "bar_sync": (t1 - t0) * 1e3, "pull": (t2 - t1) * 1e3,
                    "grad": (t3 - t2) * 1e3, "push": (t4 - t3) * 1e3,
                    "bar_commit": (time.perf_counter() - t4) * 1e3}
            except _EpochChanged:
                continue  # step discarded, re-run at the new width
            except Exception as e:
                # a table op mid-step hit the durable-tier failover
                # (VanFailover after the dance, or a raw wire error the
                # dance can absorb): void the step exactly like an
                # epoch change — the re-run re-pulls and re-pushes on
                # the promoted primary at re-keyed barrier ids.  The
                # re-push is the plane's documented at-least-once
                # (check_complete_cover tolerance); byte-identity under
                # van chaos lives with the idempotent MPMD plane.
                if pushed:
                    # the gradient landed, then the step voided: the
                    # re-run WILL push it again.  Count the duplicate
                    # where it happens — ``ps.dp_repush_duplicates``
                    # rides fleet_metrics() so an operator can bound
                    # how non-idempotent a chaotic run actually was.
                    from hetu_tpu.telemetry import default_registry
                    default_registry.counter(
                        "ps.dp_repush_duplicates").inc()
                try:
                    self._wire_fault(e)
                except _EpochChanged:
                    pass
                continue
            # COMMITTED: every worker of this epoch passed the commit
            # barrier; the blackboard row is written BEFORE proceeding,
            # so a prepare freeze always reads current progress
            self.committed = step
            try:
                self._sync_row()
            except Exception:
                pass  # the beat thread re-writes it within hb_ms
            step += 1
            if spec.step_sleep_s > 0:
                self._stop.wait(spec.step_sleep_s)
        self.close()

    def _stage_barrier(self, width: int):
        """The ordered-apply barrier for the current epoch, cached like
        ``_epoch_barriers`` — in a DISJOINT id band (the epoch pair
        occupies ``base + 2*epoch + phase``, so a third phase would
        collide with the next epoch's sync barrier).  Re-keyed by the
        van generation and dialed at the current primary, exactly like
        the epoch pair — a promoted van has no arrival state to
        resume."""
        key = (self._van_gen(), self.epoch)
        if self._sbar is None or self._sbar[0] != key:
            if self._sbar is not None:
                try:
                    self._sbar[1].close()
                except Exception:
                    pass
            bid = (self.spec.barrier_base + self._van_gen() * (1 << 21)
                   + (1 << 20) + self.epoch)
            host, port = self._van_endpoint()
            self._sbar = (key, self._van.RemoteBarrier(host, port, bid,
                                                       width))
        return self._sbar[1]

    def _push_ordered(self, grad, rank: int, width: int) -> None:
        """Rank-ordered gradient application: stage this rank's gradient
        (idempotent ``sparse_set`` into its staging rows — a crash-step
        re-run overwrites, never double-stages), barrier until every
        rank of the epoch staged, then rank 0 pulls all staged slices
        and applies them to the weights IN RANK ORDER over its single
        connection (the van serves one connection's requests in order).
        The commit barrier that follows in the step body fences the
        applies before anyone's next pull.  Determinism: the PS-side
        SGD now always sums the same f32 values in the same order, so
        clean same-seed runs produce bitwise-identical weights.  Crash
        semantics are unchanged (at-least-once across a discarded
        epoch, tolerated exactly like a re-pushed slice)."""
        f = self.spec.features
        rows = np.arange(rank * f, (rank + 1) * f, dtype=np.int64)
        self._staging.sparse_set(rows, grad.astype(np.float32))
        self._await_barrier(self._stage_barrier(width))
        if rank == 0:
            idx = np.arange(width * f, dtype=np.int64)
            staged = self._staging.sparse_pull(idx)
            for r in range(width):
                self.table.dense_push(staged[r * f:(r + 1) * f])

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._sync_row()
            self.member.leave()
        except Exception:
            pass
        self._log.close()
        self.table.close()
        if self._staging is not None:
            try:
                self._staging.close()
            except Exception:
                pass
        if self._sbar is not None:
            try:
                self._sbar[1].close()
            except Exception:
                pass
        self._close_control_plane()


def worker_main(config_path: str) -> int:
    spec = WorkerSpec.from_json(open(config_path).read())
    # crash-durable span stream in the run workdir (the spawn config's
    # directory IS the workdir): the elastic worker's flight recorder
    trace.open_process_stream(Path(config_path).resolve().parent,
                              f"worker_s{spec.slot}_p{os.getpid()}")
    worker = WorkerProcess(spec)
    print("READY", spec.slot, flush=True)
    worker.run()
    return 0


# ---------------------------------------------------------------------------
# consumed-batch verification (the byte-identity evidence)
# ---------------------------------------------------------------------------

def merge_consumed_logs(paths) -> dict:
    """Merge worker logs → ``{step: [(epoch, width, rank, crc), ...]}``."""
    out: dict = {}
    for p in paths:
        p = Path(p)
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            if not line.strip():
                continue
            rec = json.loads(line)
            out.setdefault(int(rec["step"]), []).append(
                (int(rec["epoch"]), int(rec["width"]), int(rec["rank"]),
                 int(rec["crc"])))
    return out


def check_complete_cover(consumed: dict, schedule, steps: int) -> None:
    """Assert the merged logs prove the run consumed byte-identical
    global batches vs a never-resized run, for every step in
    ``[0, steps)``:

    * the step's LATEST epoch carries a COMPLETE cover — one width,
      every rank ``0..width-1`` exactly once, each slice's CRC equal to
      the width-invariant schedule's bytes (``local_slice`` partitions
      the SAME ``global_batch(step)`` at every width, so a complete
      cover at ANY width is the same global bytes);
    * records from EARLIER epochs of the same step are crash residue —
      a worker SIGKILLed between its gradient push and the commit
      barrier (gradient at-least-once, tolerated) — and must still be a
      valid partial slicing (CRCs match, no duplicate rank per width).

    Raises AssertionError naming the first violation."""
    for step in range(int(steps)):
        recs = consumed.get(step)
        assert recs, f"step {step} was never consumed by any worker"
        last_epoch = max(e for e, _, _, _ in recs)
        cover = [(w, r) for e, w, r, _ in recs if e == last_epoch]
        widths = {w for w, _ in cover}
        assert len(widths) == 1, \
            (f"step {step}: epoch {last_epoch} records carry several "
             f"widths {sorted(widths)}")
        width = widths.pop()
        ranks = sorted(r for _, r in cover)
        assert ranks == list(range(width)), \
            (f"step {step}: epoch {last_epoch} ranks {ranks} do not "
             f"cover width {width}")
        seen = set()
        for e, w, r, crc in recs:
            assert (e, w, r) not in seen, \
                f"step {step}: duplicate record for epoch {e} rank {r}/{w}"
            seen.add((e, w, r))
            want = slice_crc(schedule.local_slice(step, r, w))
            assert crc == want, \
                (f"step {step} rank {r}/{w}: consumed slice CRC "
                 f"{crc:#x} != schedule's {want:#x}")


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

@dataclass
class ReshardRecord:
    """One published membership epoch, for reports/benches."""

    epoch: int
    kind: str          # "shrink" | "grow"
    slot: int
    width: int
    resume_step: int
    downtime_s: float
    alive: tuple = field(default_factory=tuple)


class MultiControllerElasticSupervisor:
    """Membership authority over N worker PROCESSES.

    Owns the van, the weights table (where the model actually lives —
    the property that makes a worker process stateless-but-for-data),
    the blackboard, and the lease state machine.  It publishes decided
    membership epochs; workers do everything else.  ``procs`` holds the
    live ``Popen`` handles the ``worker_proc_kill`` chaos fault targets.
    """

    def __init__(self, n_workers: int, *, workdir, steps: int,
                 global_batch: int, features: int = 8, out_dim: int = 4,
                 n_samples: int = 256, data_seed: int = 0,
                 lr: float = 0.05, hb_ms: int = 80,
                 lease_s: float = 0.6, suspect_grace_s: float = 0.4,
                 deaf_ack_s: Optional[float] = None,
                 min_width: int = 1, port: int = 0,
                 own_van: bool = True,
                 step_sleep_s: float = 0.0,
                 ctrl_lease_s: float = 0.0,
                 injector=None, spawn_timeout_s: float = 120.0,
                 straggler_factor: float = 4.0,
                 straggler_policy: str = "wait",
                 straggler_evict_after: int = 3,
                 straggler_slow_ms: int = 120,
                 straggler_readmit_after: int = 3,
                 ordered_grads: bool = False,
                 van_spec: Optional[dict] = None,
                 _takeover_spec: Optional[WorkerSpec] = None):
        from hetu_tpu.ps import van
        if n_workers < 1:
            raise ValueError("need at least one worker")
        for w in range(max(min_width, 1), n_workers + 1):
            if global_batch % w:
                raise ValueError(
                    f"global batch {global_batch} must divide by every "
                    f"reachable width (fails at {w})")
        self._van = van
        self._own_van = bool(own_van)
        if not van_spec and _takeover_spec is not None:
            # the durable-tier pair is recorded in the spawn configs on
            # disk, like every other control-plane id
            van_spec = getattr(_takeover_spec, "van", None) or None
        # replicated durable tier: weights/staging/blackboard tables
        # dual-write over a primary+backup van pair; a primary SIGKILL
        # is a retried transient at every op site (VanFailover), so the
        # PS-resident model survives the van process itself
        self._replica = None
        self._van_spec = dict(van_spec) if van_spec else {}
        if self._van_spec:
            if own_van:
                raise ValueError(
                    "a replicated durable tier is external by "
                    "definition: pass own_van=False with van_spec")
            from hetu_tpu.ps.replica import VanReplica
            self._replica = VanReplica.from_spec(
                self._van_spec, bootstrap=_takeover_spec is None)
            if _takeover_spec is not None:
                self._replica.refresh()  # unconditional: a stale
                # cached view must not adopt the dead primary
            port = self._replica.primary[1]
            # a van promotion republishes a fresh epoch from poll():
            # members that detected the failover themselves converge on
            # the re-keyed barriers anyway; the republish gives any
            # still-parked member a control-row edge to re-read, and
            # records the event as a reshard
            self._van_failover_pending = False
            self._replica.register(
                lambda _rep: setattr(self, "_van_failover_pending",
                                     True))
        if own_van:
            self.port = van.serve(port)
        else:
            # attach to an EXTERNAL van process (the durable tier the
            # ROADMAP's controller-failover story needs: a controller
            # crash must not take the blackboard and the model with it)
            if not port:
                raise ValueError("own_van=False needs the running "
                                 "van's port")
            self.port = int(port)
        self.workdir = Path(workdir)
        self.steps = int(steps)
        self.n_workers = int(n_workers)
        self.min_width = int(min_width)
        self.injector = injector
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._incarnations = 0
        self.epoch = 0
        self.resume_step = 0
        self.resizes: list = []
        self.log_paths: list = []
        # straggler plane (the slow-vs-dead split the lease machine
        # cannot make: a straggler's beats FLOW, its work time grows).
        # Detection: reported work_ms > straggler_factor x the median
        # of its peers'.  Policy "wait" = record + tolerate (the
        # barriers already pace the fleet at the straggler's speed);
        # "evict" = after `straggler_evict_after` slow COMMITTED steps,
        # reshard around it (a shrink epoch excluding the slot — batch
        # byte-identity preserved by the same complete-cover machinery
        # as any other shrink).
        if straggler_policy not in ("wait", "evict"):
            raise ValueError(f"unknown straggler_policy "
                             f"{straggler_policy!r}: wait|evict")
        self.straggler_factor = float(straggler_factor)
        self.straggler_policy = straggler_policy
        self.straggler_evict_after = int(straggler_evict_after)
        self.straggler_slow_ms = int(straggler_slow_ms)
        # auto re-admission probation: an evicted-but-alive slot keeps
        # probing its van link (the worker times a weights pull while
        # excluded and reports it as load), and after this many
        # consecutive healthy probed beats the controller lifts the
        # eviction (readmit_straggler).  0 disables — eviction then
        # stays operator-lifted only.
        self.straggler_readmit_after = int(straggler_readmit_after)
        self._evicted: set = set()
        self._probation: dict = {}         # slot -> {"beat", "ok"}
        self.procs: list = [None] * n_workers
        self._member_pids: dict = {}    # takeover-adopted pids (no Popen)
        self._fired_through = 0
        from hetu_tpu.resilience.straggler import SupervisorStragglerPlane
        if _takeover_spec is not None:
            # ---- takeover: adopt a running fleet whose controller
            # died.  Everything the old controller held in RAM is
            # re-derived from what survives on the van: the control row
            # (epoch / mask / resume / a half-open PREPARE), the lease
            # rows (who is alive, frozen committed progress), and the
            # spawn configs on disk (every table id).  The fleet is
            # parked (ctrl_lease_s) or frozen (phase=1); the republish
            # below un-parks it with an EXACT resume.
            self.spec = WorkerSpec(**{**asdict(_takeover_spec),
                                      "slot": -1, "log_path": ""})
            # the whole attach sequence is guarded: a blackboard/claim
            # failure after the weights table connected must close it,
            # not leak the van connection for the process's life
            try:
                from hetu_tpu.ps.replica import open_table
                self.table = open_table(
                    self._replica, "127.0.0.1", self.port,
                    int(features), int(out_dim),
                    table_id=self.spec.weights_table, create=False)
                self._bb = _mb.attach_blackboard(
                    "127.0.0.1", self.port,
                    table_id=self.spec.membership_table,
                    n_slots=n_workers, replica=self._replica)
                self.svc = _mb.MembershipService(
                    self._bb, n_workers, lease_s=lease_s,
                    suspect_grace_s=suspect_grace_s,
                    deaf_ack_s=deaf_ack_s)
                self._stragglers = SupervisorStragglerPlane(
                    self.svc, factor=self.straggler_factor,
                    subject="worker", policy=straggler_policy,
                    evict_after=self.straggler_evict_after,
                    slow_ms=self.straggler_slow_ms)
                self.log_paths = sorted(
                    str(p) for p in self.workdir.glob("worker_*_*.jsonl")
                    # the workers' telemetry span streams live in the
                    # same workdir and match the stem — they are NOT
                    # consumed-batch logs
                    if not p.name.endswith(".trace.jsonl"))
                self._incarnations = len(
                    list(self.workdir.glob("worker_*_*.json")))
                self._adopt()
            except Exception:
                self.close()
                raise
            return
        # ---- normal bring-up ----
        # fresh table/barrier ids per supervisor: the native table and
        # barrier registries outlive van.stop(), so fixed ids would leak
        # state between two fleets built in one process (tests, benches)
        weights_table = _mb.fresh_table_id()
        membership_table = _mb.fresh_table_id()
        staging_table = _mb.fresh_table_id() if ordered_grads else 0
        barrier_base = BARRIER_BASE + (_mb.fresh_table_id() << 8)
        self.spec = WorkerSpec(
            port=self.port, slot=-1, n_slots=n_workers, steps=self.steps,
            global_batch=int(global_batch), features=int(features),
            out_dim=int(out_dim), n_samples=int(n_samples),
            data_seed=int(data_seed), lr=float(lr), hb_ms=int(hb_ms),
            membership_table=membership_table,
            weights_table=weights_table, barrier_base=barrier_base,
            step_sleep_s=float(step_sleep_s),
            ctrl_lease_s=float(ctrl_lease_s),
            ordered_grads=bool(ordered_grads),
            staging_table=staging_table, van=self._van_spec)
        # everything after van.serve is guarded: a table/blackboard/
        # spawn failure must stop the in-process van server (and close
        # what was created) instead of leaking it for the process's life
        try:
            from hetu_tpu.ps.replica import open_table
            self.table = open_table(
                self._replica, "127.0.0.1", self.port, int(features),
                int(out_dim), table_id=weights_table, create=True,
                init="zeros", optimizer="sgd", lr=float(lr))
            if ordered_grads:
                # gradient staging area: one block of `features` rows
                # per rank, lr=0 SGD so sparse_set writes verbatim (the
                # blackboard convention) — workers stage here and rank 0
                # applies to the weights table in rank order
                self._staging = open_table(
                    self._replica, "127.0.0.1", self.port,
                    n_workers * int(features), int(out_dim),
                    table_id=staging_table, create=True,
                    init="zeros", optimizer="sgd", lr=0.0)
            self._bb = _mb.create_blackboard(
                "127.0.0.1", self.port,
                table_id=membership_table, n_slots=n_workers,
                replica=self._replica)
            self.svc = _mb.MembershipService(
                self._bb, n_workers, lease_s=lease_s,
                suspect_grace_s=suspect_grace_s, deaf_ack_s=deaf_ack_s)
            self._stragglers = SupervisorStragglerPlane(
                self.svc, factor=self.straggler_factor, subject="worker",
                policy=straggler_policy,
                evict_after=self.straggler_evict_after,
                slow_ms=self.straggler_slow_ms)
            for slot in range(n_workers):
                self._spawn(slot)
            self._wait_joined(range(n_workers))
        except Exception:
            self.close()
            raise
        # epoch numbering starts at 1: a zeroed control row must not
        # read as a published membership
        self._publish(kind=None)

    @classmethod
    def takeover(cls, *, workdir, port, lease_s: float = 0.6,
                 suspect_grace_s: float = 0.4,
                 deaf_ack_s: Optional[float] = None, min_width: int = 1,
                 spawn_timeout_s: float = 120.0, injector=None,
                 **straggler_kw) -> "MultiControllerElasticSupervisor":
        """Become the fleet's NEW controller after the old one died:
        re-derive the supervisor from the worker spawn configs under
        ``workdir`` and the still-running van at ``port``, claim the
        controller row with a higher incarnation, and republish the
        frozen membership with an exact resume (a two-phase re-freeze)
        under a ``ctrl.takeover`` span.  The killed-mid-PREPARE case is
        covered by construction: the fresh epoch supersedes the
        half-open one and collects fresh frozen acks."""
        cfgs = sorted(Path(workdir).glob("worker_*_*.json"),
                      key=lambda p: p.stat().st_mtime)
        if not cfgs:
            raise FileNotFoundError(
                f"no worker spawn configs under {workdir}")
        spec = WorkerSpec.from_json(cfgs[-1].read_text())
        return cls(spec.n_slots, workdir=workdir, steps=spec.steps,
                   global_batch=spec.global_batch,
                   features=spec.features, out_dim=spec.out_dim,
                   n_samples=spec.n_samples, data_seed=spec.data_seed,
                   lr=spec.lr, hb_ms=spec.hb_ms, lease_s=lease_s,
                   suspect_grace_s=suspect_grace_s,
                   deaf_ack_s=deaf_ack_s, min_width=min_width,
                   port=port, own_van=False,
                   step_sleep_s=spec.step_sleep_s,
                   ctrl_lease_s=spec.ctrl_lease_s, injector=injector,
                   spawn_timeout_s=spawn_timeout_s,
                   _takeover_spec=spec, **straggler_kw)

    def _adopt(self) -> None:
        """Adopt the fleet: republish the frozen epoch under the new
        incarnation.  Every piece of the old controller's RAM is
        re-derived — epoch and resume from the control row, the evicted
        set from (alive lease rows) minus (published mask), the
        committed high-water from the frozen progress rows."""
        ctrl = self.svc.read_control_row()
        self.epoch = int(ctrl["epoch"])
        self.resume_step = int(ctrl["resume_step"])
        # carry the predecessor's straggler injection forward: the
        # takeover republish must not silently heal an injected slow
        # link (the same rule every epoch transition honors)
        self.svc.adopt_slow(ctrl["slow_slot"], ctrl["slow_ms"])
        # learn who is beating before judging anything
        self.svc.wait_present(self._spawn_timeout_s)
        # worker pids off the lease rows: these processes are the DEAD
        # controller's children — the pid is the only handle
        # close()/spawn_replacement have on them
        self._member_pids.update(self.svc.member_pids())
        if self.epoch > 0:
            mask_slots = set(_mb.MembershipService.slots_of(
                int(ctrl["alive_mask"])))
            self._evicted = {s for s in self.svc.present_slots()
                             if s not in mask_slots}
        with trace.span("ctrl.takeover", cat="ctrl") as sp:
            sp.set("plane", "elastic")
            sp.set("incarnation", self.svc.ctrl_incarnation)
            sp.set("epoch_adopted", self.epoch)
            sp.set("phase_at_death", int(ctrl["phase"]))
            if self._present():
                # the two-phase re-freeze: exact resume from fresh
                # frozen acks — this is also what finishes an epoch the
                # old controller died inside (phase=1 half-open)
                t0 = time.perf_counter()
                self._publish(kind="takeover", t0=t0)
            sp.set("epoch", self.epoch)
            sp.set("resume_step", self.resume_step)
        self.takeover_report = {
            "incarnation": self.svc.ctrl_incarnation,
            "epoch": self.epoch, "resume_step": self.resume_step,
            "evicted": sorted(self._evicted),
            "present": sorted(self.svc.present_slots()),
        }

    # ---- spawning ----
    def _spawn(self, slot: int) -> None:
        from hetu_tpu.resilience.shardproc import spawn_module
        self._incarnations += 1
        tag = f"worker_{slot}_{self._incarnations}"
        if self._replica is not None:
            # spawn configs carry the CURRENT pair membership: after a
            # failover + re-silver the original endpoints may both be
            # dead, and a fresh process has no other rendezvous
            self.spec = WorkerSpec(**{**asdict(self.spec),
                                      "van": self._replica.current_spec()})
        spec = WorkerSpec(**{**asdict(self.spec), "slot": int(slot),
                             "log_path": str(self.workdir /
                                             f"{tag}.jsonl")})
        cfg = self.workdir / f"{tag}.json"
        cfg.write_text(spec.to_json())
        self.log_paths.append(spec.log_path)
        # workers are numpy+van only — force them onto CPU so a fleet on
        # an accelerator box never has N processes fighting for the chip
        self.procs[slot] = spawn_module(
            self.workdir, tag, "hetu_tpu.resilience.multicontroller",
            [str(cfg)], extra_env={"JAX_PLATFORMS": "cpu"},
            timeout_s=self._spawn_timeout_s)

    def _wait_joined(self, slots, timeout_s: Optional[float] = None):
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self._spawn_timeout_s)
        want = set(int(s) for s in slots)
        while time.monotonic() < deadline:
            self.svc.poll()
            if want <= set(self.svc.present_slots()):
                return
            time.sleep(0.05)
        raise TimeoutError(f"workers {sorted(want)} did not join in time")

    # ---- membership → epochs ----
    def _publish(self, *, kind: Optional[str], slot: int = -1,
                 t0: Optional[float] = None) -> None:
        """Move the fleet to a new membership epoch.

        Two-phase when the fleet is live (``kind`` set): publish
        ``phase=PREPARE`` (workers freeze at their next step boundary
        and ack with frozen progress), wait for every present worker's
        ack — re-preparing with a fresh epoch if the membership moves
        again mid-wait — then publish ``phase=0`` with the EXACT
        ``resume_step`` computed from the frozen values.  Initial
        bring-up (``kind=None``) skips the prepare: nobody is stepping
        yet."""
        while True:
            present = self._present()
            width = len(present)
            if width < max(self.min_width, 1):
                raise RuntimeError(
                    f"only {width} workers present (min_width="
                    f"{self.min_width}); cannot reform the fleet")
            mask = _mb.MembershipService.mask_of(present)
            self.epoch += 1
            if kind is None:
                self.resume_step = 0
                self.svc.publish_control(epoch=self.epoch, width=width,
                                         alive_mask=mask, resume_step=0)
                return
            self.svc.publish_control(epoch=self.epoch, width=width,
                                     alive_mask=mask, phase=1)
            deadline = time.monotonic() + 30.0
            moved = False
            while time.monotonic() < deadline:
                if any(k in ("lost", "join", "rejoin", "left")
                       for k, _ in self.svc.poll()):
                    moved = True  # membership moved again: re-prepare
                    break
                if all(self.svc.state_of(s).epoch_ack >= self.epoch
                       for s in self.svc.present_slots()):
                    break
                time.sleep(0.02)
            else:
                raise TimeoutError(
                    f"epoch {self.epoch} prepare not acked by "
                    f"{self.svc.present_slots()} within 30s")
            if moved:
                continue
            present = self._present()
            # the resume considers EVERY slot that ever reported progress
            # (present, left, lost — commits are barrier-atomic, so no
            # departed row can be ahead of a live one): a worker
            # rejoining a finished-and-departed fleet must resume AFTER
            # the work, not re-train the dataset alone from step 0
            frozen = [m.committed for m in self.svc.members
                      if m.state != "empty"]
            self.resume_step = max(max(frozen) + 1, 0)
            self.svc.publish_control(
                epoch=self.epoch, width=len(present),
                alive_mask=_mb.MembershipService.mask_of(present),
                resume_step=self.resume_step)
            dt = time.perf_counter() - (t0 if t0 is not None
                                        else time.perf_counter())
            self.resizes.append(ReshardRecord(
                epoch=self.epoch, kind=kind, slot=int(slot),
                width=len(present), resume_step=self.resume_step,
                downtime_s=dt, alive=tuple(present)))
            return

    def _present(self) -> list:
        """Membership minus straggler evictions: a slot resharded
        around for slowness is alive and beating — excluded from the
        published mask, not from the lease machine."""
        return [s for s in self.svc.present_slots()
                if s not in self._evicted]

    def poll(self) -> list:
        """One membership sweep: drives the injector by observed
        committed step, applies lease decisions as published epochs,
        and runs the straggler detector over the reported work times.
        Returns the membership events seen."""
        if self.injector is not None:
            cur = max((self.svc.state_of(s).committed
                       for s in range(self.n_workers)), default=-1)
            for t in range(self._fired_through + 1, cur + 1):
                self.injector.on_step(t)
            self._fired_through = max(self._fired_through, cur)
            # claim only the straggler events: serving-plane netem
            # kinds stay queued for whoever drives the pool
            for _, idx, dur in self.injector.pop_net_events(
                    kinds=("straggler",)):
                self.inject_straggler(int(idx) % self.n_workers, dur)
        # the heal runs HERE, serialized with every other control-row
        # write (see SupervisorStragglerPlane)
        self._stragglers.maybe_heal()
        if self._replica is not None and self._van_failover_pending:
            self._van_failover_pending = False
            t0 = time.perf_counter()
            with trace.span("elastic.reshard") as sp:
                sp.set("kind", "van_failover")
                sp.set("van_incarnation", self._replica.incarnation)
                if self._present():
                    # a finished-and-departed fleet needs no republish
                    # (and could not reform below min_width anyway)
                    self._publish(kind="van_failover", t0=t0)
                sp.set("width", len(self.svc.present_slots()))
        events = self.svc.poll()
        for kind, slot in events:
            if kind == "lost":
                t0 = time.perf_counter()
                with trace.span("elastic.reshard") as sp:
                    sp.set("kind", "shrink")
                    sp.set("worker", int(slot))
                    self._publish(kind="shrink", slot=slot, t0=t0)
                    sp.set("width", len(self.svc.present_slots()))
            elif kind in ("rejoin", "join"):
                t0 = time.perf_counter()
                with trace.span("elastic.reshard") as sp:
                    sp.set("kind", "grow")
                    sp.set("worker", int(slot))
                    self._publish(kind="grow", slot=slot, t0=t0)
                    sp.set("width", len(self.svc.present_slots()))
        self._check_stragglers()
        self._check_probation()
        return events

    # ---- straggler detection / policy ----
    def inject_straggler(self, slot: int, duration_s: float,
                         slow_ms: Optional[int] = None) -> None:
        """Apply the ``straggler`` chaos fault via the shared
        :class:`~hetu_tpu.resilience.straggler.
        SupervisorStragglerPlane` (injection + serialized-heal glue —
        one copy for both cross-process training planes)."""
        self._stragglers.inject(slot, duration_s, slow_ms)

    @property
    def straggle_records(self) -> list:
        """Closed ``train.straggler`` episodes (the shared detector's
        span args verbatim)."""
        return self._stragglers.records

    def _check_stragglers(self) -> None:
        """Per-phase timing turned into a slow-vs-dead decision: a
        worker whose reported WORK time (load field — barrier waits
        excluded) exceeds ``straggler_factor`` x the median of its
        peers' is a straggler — alive (its beats flow, the lease
        machine never fires) but pacing the whole lockstep fleet.
        Episode spans live in the shared detector plane; the POLICY is
        applied here: under ``straggler_policy="evict"`` the fleet
        reshards around the worker once it has been slow for
        ``straggler_evict_after`` committed steps."""
        slots = [s for s in self._present()
                 if self.svc.state_of(s).state == "alive"]
        for slot in self._stragglers.observe(slots):
            if self.straggler_policy == "evict" and \
                    slot not in self._evicted:
                self._evict_straggler(slot)

    def _evict_straggler(self, slot: int) -> None:
        """The evict policy: reshard the fleet AROUND the straggler.
        The slot stays in the lease machine (alive, beating — not
        lost) but leaves the published mask; survivors re-cover every
        global batch at the smaller width, byte-identical by the same
        complete-cover contract as any other shrink."""
        self._evicted.add(int(slot))
        self._stragglers.close(slot, resolution="evicted")
        t0 = time.perf_counter()
        with trace.span("elastic.reshard") as sp:
            sp.set("kind", "shrink")
            sp.set("worker", int(slot))
            sp.set("reason", "straggler_evict")
            self._publish(kind="shrink", slot=slot, t0=t0)
            sp.set("width", len(self._present()))

    def _check_probation(self) -> None:
        """Auto re-admission of evicted stragglers: an evicted slot
        stays alive and beating, and while excluded its worker probes
        the van link (a timed pull+push pair — a step's wire cost) and
        reports the probe time as load.  Each NEW beat carrying a probe
        under the RE-ADMISSION bar counts toward
        ``straggler_readmit_after`` consecutive healthy beats; a slow
        probe resets the count.  The re-admission bar is HALF the
        eviction bar (hysteresis): the probe measures only the wire
        share of a step while peers report wire+compute, so a
        borderline link that barely clears the eviction bar must not
        readmit only to be re-evicted — an indefinite evict/readmit
        flap, two reshard epochs per cycle.  Reaching the count lifts
        the eviction (the grow epoch re-covers every batch at the wider
        width — same byte-identity contract as any other grow)."""
        if self.straggler_readmit_after <= 0:
            return
        active = [self.svc.state_of(s).load for s in self._present()
                  if self.svc.state_of(s).state == "alive" and
                  self.svc.state_of(s).load > 0.0]
        for slot in list(self._evicted):
            m = self.svc.state_of(slot)
            st = self._probation.setdefault(slot, {"beat": m.beat,
                                                   "ok": 0})
            if m.state != "alive":
                st["ok"] = 0
                continue
            if m.beat == st["beat"]:
                continue  # no fresh evidence since the last look
            st["beat"] = m.beat
            med = float(np.median(active)) if active else 0.0
            healthy = m.load > 0.0 and (
                med <= 0.0 or m.load <= 0.5 * self.straggler_factor *
                max(med, 1e-3))
            st["ok"] = st["ok"] + 1 if healthy else 0
            if st["ok"] >= self.straggler_readmit_after:
                self._probation.pop(slot, None)
                self.readmit_straggler(slot)

    def readmit_straggler(self, slot: int) -> None:
        """Operator/test path: lift a straggler eviction (e.g. after
        the slow link healed); the next publish regrows the mesh."""
        if int(slot) in self._evicted:
            self._evicted.discard(int(slot))
            self._probation.pop(int(slot), None)
            t0 = time.perf_counter()
            with trace.span("elastic.reshard") as sp:
                sp.set("kind", "grow")
                sp.set("worker", int(slot))
                self._publish(kind="grow", slot=slot, t0=t0)
                sp.set("width", len(self._present()))

    def spawn_replacement(self, slot: int) -> None:
        """Re-admit a lost worker slot with a FRESH process: it joins
        with a new incarnation, the next poll publishes a grow epoch,
        and the worker re-places itself (weights come from the PS —
        rejoin ships zero parameter bytes from the controller)."""
        p = self.procs[slot]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        elif slot in self._member_pids:
            # a takeover-adopted worker (the dead controller's child):
            # the pid is the only handle
            try:
                os.kill(self._member_pids[slot], _signal.SIGKILL)
            except OSError:
                pass
        self._member_pids.pop(slot, None)
        self._spawn(slot)

    # ---- driving ----
    def run(self, *, deadline_s: float = 300.0,
            poll_s: float = 0.05) -> dict:
        """Poll until every present worker committed the final step (or
        left after doing so).  Returns a report dict."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            self.poll()
            states = [self.svc.state_of(s) for s in range(self.n_workers)]
            # an evicted straggler is alive-but-excluded: it will never
            # advance past its eviction point, so completion is judged
            # on the workers actually IN the published mask
            present = [m for m in states
                       if m.state in ("alive", "suspect") and
                       m.slot not in self._evicted]
            finished = [m for m in states
                        if m.state == "left" and
                        m.committed >= self.steps - 1]
            if present and all(m.committed >= self.steps - 1
                               for m in present):
                break
            if not present and finished:
                break
            time.sleep(poll_s)
        else:
            raise TimeoutError(
                f"fleet did not finish {self.steps} steps within "
                f"{deadline_s}s: "
                f"{[(m.slot, m.state, m.committed) for m in states]}")
        # a still-open straggle window at run end must land in the
        # trace (an unclosed span would silently drop the episode)
        self._stragglers.close_all(resolution="run_end")
        consumed = merge_consumed_logs(self.log_paths)
        return {
            "steps": self.steps,
            "epochs": self.epoch,
            "resizes": [asdict(r) for r in self.resizes],
            "consumed": consumed,
            "final_weights": self.table.dense_pull(),
        }

    def verify_consumed(self, consumed: Optional[dict] = None) -> None:
        """The chaos acceptance check: complete-cover-per-step,
        width-invariant, byte-identical global-batch consumption."""
        if consumed is None:
            consumed = merge_consumed_logs(self.log_paths)
        check_complete_cover(consumed, make_schedule(self.spec),
                             self.steps)

    def close(self) -> None:
        # a FENCED controller no longer owns the fleet: its close()
        # must not kill worker processes the new incarnation adopted
        # (the same rule as the serving pool's fenced close)
        svc = getattr(self, "svc", None)
        fenced = bool(getattr(svc, "fenced", False))
        for p in self.procs if not fenced else ():
            if p is None:
                continue
            try:
                if p.poll() is None:
                    p.kill()
                p.wait()
            except Exception:
                traceback.print_exc()
        # takeover-adopted workers have no Popen handle — the pid off
        # the lease row is the only one.  Only still-present slots are
        # signalled (a finished fleet left cleanly; killing a recycled
        # pid would hit an innocent process), and they were reparented
        # when their spawner died, so there is no zombie-reap concern
        for slot, pid in (() if fenced else
                          list(getattr(self, "_member_pids",
                                       {}).items())):
            if svc is not None and \
                    svc.state_of(slot).state not in ("alive", "suspect"):
                continue
            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                pass
        for t in (getattr(self, "table", None), getattr(self, "_bb", None),
                  getattr(self, "_staging", None)):
            if t is not None:
                try:
                    t.close()
                except Exception:
                    pass
        if getattr(self, "_own_van", True):
            self._van.stop()


# ---------------------------------------------------------------------------
# controller process harness (the chaos kill target)
# ---------------------------------------------------------------------------

def controller_main(config_path: str) -> int:
    """Entry point for a spawned CONTROLLER process: build the
    supervisor against an EXTERNAL van, drive the fleet, and print the
    progress markers the chaos harness keys on (``STEP k`` per
    committed high-water advance, ``PREPARED`` for the killed-mid-
    PREPARE edge mode, ``ALLDONE``, ``FENCED``).  ``prepare_hang_at``
    publishes a PREPARE freeze at the named committed step and then
    hangs — the takeover must finish the half-open epoch with an exact
    resume."""
    cfg = json.loads(open(config_path).read())
    trace.open_process_stream(cfg["workdir"],
                              f"controller_p{os.getpid()}")
    sup = MultiControllerElasticSupervisor(
        int(cfg["n_workers"]), workdir=cfg["workdir"],
        steps=int(cfg["steps"]), global_batch=int(cfg["global_batch"]),
        data_seed=int(cfg.get("data_seed", 0)),
        lease_s=float(cfg.get("lease_s", 0.6)),
        suspect_grace_s=float(cfg.get("suspect_grace_s", 0.4)),
        step_sleep_s=float(cfg.get("step_sleep_s", 0.0)),
        ctrl_lease_s=float(cfg.get("ctrl_lease_s", 0.0)),
        hb_ms=int(cfg.get("hb_ms", 80)),
        port=int(cfg["port"]), own_van=False)
    hang_at = cfg.get("prepare_hang_at")

    def progress():
        return max((sup.svc.state_of(s).committed
                    for s in range(sup.n_workers)), default=-1)

    def hang_mid_prepare(hw):
        if hang_at is None or hw < int(hang_at):
            return
        # die mid-transition: PREPARE published, acks never collected —
        # the takeover edge case
        sup.epoch += 1
        present = sup._present()
        sup.svc.publish_control(
            epoch=sup.epoch, width=len(present),
            alive_mask=_mb.MembershipService.mask_of(present), phase=1)
        print("PREPARED", flush=True)
        while True:
            time.sleep(3600)

    def done():
        states = [sup.svc.state_of(s) for s in range(sup.n_workers)]
        present = [m for m in states
                   if m.state in ("alive", "suspect") and
                   m.slot not in sup._evicted]
        finished = [m for m in states
                    if m.state == "left" and
                    m.committed >= sup.steps - 1]
        return bool((present and all(m.committed >= sup.steps - 1
                                     for m in present)) or
                    (not present and finished))

    rc = drive_controller_harness(
        sup.poll, progress, done,
        deadline_s=float(cfg.get("deadline_s", 300.0)),
        on_progress=hang_mid_prepare)
    return 0 if rc is None else rc


if __name__ == "__main__":
    import sys
    if sys.argv[1] == "--controller":
        sys.exit(controller_main(sys.argv[2]))
    sys.exit(worker_main(sys.argv[1]))
