"""Deterministic, seedable fault injection for chaos-testing training.

Reference context: ps-lite's reliability machinery (heartbeats, resender,
SaveParam/LoadParam) exists because servers DIE in production; the papers
this repo tracks (PAPERS.md — MPMD pipelines, cross-replica sharding)
assume preemptible fleets as table stakes.  A recovery path that is never
exercised is a recovery path that does not work — this module makes the
faults injectable, and crucially REPLAYABLE: every fault is drawn from a
seeded :class:`FaultSchedule`, so a chaos run that fails reproduces
byte-for-byte from its seed (``FaultSchedule.to_json`` is the evidence).

Fault kinds
-----------
``van_error``      next client-side van wire op raises :class:`TransientFault`
``van_delay``      next client-side van wire op sleeps ``arg`` seconds first
``data_error``     next dataloader fetch raises :class:`TransientDataError`
``nan_grad``       the step's batch gets a NaN poisoned into its first float
                   leaf — the loss/grads of a NaN input are NaN, exercising
                   the supervisor's nonfinite-step guard without reaching
                   inside jit
``kill_shard``     SIGKILL the PS shard subprocess ``arg`` (mid-step death)
``suspend_shard``  SIGSTOP shard ``arg`` for ``arg2`` seconds (GC-pause /
                   network-partition lookalike), then SIGCONT
``preempt``        deliver SIGTERM to the training process (simulated
                   preemption; the supervisor checkpoints and exits)
``worker_loss``    data-parallel worker ``arg`` is PERMANENTLY lost — the
                   elastic supervisor reforms the mesh at the surviving
                   width instead of aborting (resilience/elastic.py)
``worker_join``    worker ``arg`` (re)joins — the mesh regrows
``serve_preempt``  serving-pool member ``arg`` receives a preemption
                   notice: the pool drains it PLANNED — live KV slots
                   migrate to a peer (serve/pool.py, zero re-prefill)
``serve_engine_kill``  serving-pool member ``arg``'s engine dies
                   UNANNOUNCED (SIGKILL-alike, KV state lost); the pool
                   fails its queue over to a peer via re-prefill
``member_kill``    SIGKILL the serving-member PROCESS ``arg`` (real OS
                   death: the cross-process pool's lease expires and it
                   fails the member's requests over — serve/crosshost.py)
``member_suspend`` SIGSTOP member process ``arg`` for ``arg2`` seconds,
                   then SIGCONT — the partition lookalike the lease
                   machinery must NOT double-count as loss+rejoin
``worker_proc_kill``  SIGKILL training-worker PROCESS ``arg`` — the
                   multi-controller fleet resharding path
                   (resilience/multicontroller.py)
``netem_partition``  one-way partition of member/worker ``arg``'s
                   EGRESS for ``arg2`` seconds (ps/netem.py: its
                   writes black-hole, its reads still work — the
                   asymmetric gray failure the lease machine must
                   degrade-and-clear on, never lost+rejoin)
``netem_degrade``  member/worker ``arg``'s link turns gray for
                   ``arg2`` seconds: loss + latency + a bandwidth cap
                   (the pool's routing should penalize it; serving
                   degrades to bounded latency, not collapse)
``straggler``      worker ``arg`` runs behind an emulated slow link
                   for ``arg2`` seconds — alive, beating, 10x slow;
                   the straggler-aware barriers must detect it
                   (``train.straggler``) and apply the wait/evict
                   policy (resilience/multicontroller.py)
``stage_kill``     SIGKILL pipeline-stage PROCESS ``arg`` — the MPMD
                   pipeline's lease-expiry stage-replacement path
                   (parallel/mpmd_elastic.py: replacement pulls stage
                   weights from the PS, exact two-phase resume)
``stage_slow``     pipeline stage ``arg`` runs behind an emulated slow
                   link for ``arg2`` seconds — the pipeline straggler
                   the lockstep schedule must tolerate
                   (``train.straggler``, wait policy only: a stage is
                   not redundant)
``controller_kill``  SIGKILL the CONTROLLER process ``arg`` — the
                   control plane itself is the fault domain: members
                   park/queue, a new incarnation takes over from the
                   blackboard + ledger (``ctrl.takeover``), and the
                   fleet finishes token-exact / byte-identical
``controller_suspend``  SIGSTOP controller ``arg`` for ``arg2``
                   seconds, then SIGCONT — the ZOMBIE case: a takeover
                   during the pause must fence the resumed controller
                   (its writes rejected, fleet state unchanged)
``van_kill``       SIGKILL the primary VAN process ``arg`` — the
                   durable tier itself is the fault domain: clients'
                   ops fail transiently, the backup van is promoted
                   via the epoch-row CAS (``van.promote``), and every
                   table/channel re-resolves (ps/replica.py)
``van_suspend``    SIGSTOP van process ``arg`` for ``arg2`` seconds,
                   then SIGCONT — the durable-tier zombie: clients'
                   receive timeouts surface the hang, the backup
                   promotes, and the RESUMED old primary is fenced
                   (its epoch row names its successor)
``van_resilver_kill``  the SECOND-fault kind: once the previous van
                   fault's promotion has RE-SILVERED (a fresh backup
                   attached, pair bitwise-identical again), SIGKILL
                   the promoted primary — survival proves redundancy
                   was genuinely restored, not just reported.  Paced
                   by the driver (recovery-aware: injected only after
                   ``van.resilver`` closed), drained via
                   :meth:`FaultInjector.pop_campaign_events`
``controller_kill_mid_failover``  SIGKILL the controller WHILE a van
                   failover/re-silver is in flight — the takeover must
                   re-derive both the fleet AND the current van pair
                   from what survives (paced by the driver)
``member_kill_mid_resilver``  SIGKILL a serving-member process WHILE
                   the pair is re-silvering — the copy/catch-up stream
                   must stay consistent across a concurrent member
                   failover (paced by the driver)

The van hooks ride :func:`hetu_tpu.ps.van.set_fault_hook` (one-shot
faults) and :func:`hetu_tpu.ps.van.set_netem_hook` (link policies);
everything else is plain process/OS plumbing, so the harness needs no
native lib to import.  The netem/straggler kinds are RECORDED into
``net_events`` (like the worker/serve kinds) — the pool controller or
training supervisor drains them via :meth:`FaultInjector.
pop_net_events` and applies the link policy through its own control
plane, because the injector cannot reach into another process's wire.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import zlib
from collections import defaultdict, deque
from dataclasses import dataclass

import numpy as np

from hetu_tpu.telemetry import trace


class TransientFault(ConnectionError):
    """Injected transient van transport failure (send/recv)."""


class TransientDataError(RuntimeError):
    """Injected transient dataloader failure (flaky storage / decode)."""


KINDS = ("van_error", "van_delay", "data_error", "nan_grad",
         "kill_shard", "suspend_shard", "preempt",
         "worker_loss", "worker_join",
         "serve_preempt", "serve_engine_kill",
         "member_kill", "member_suspend", "worker_proc_kill",
         "netem_partition", "netem_degrade", "straggler",
         "stage_kill", "stage_slow",
         "controller_kill", "controller_suspend",
         "van_kill", "van_suspend",
         "van_resilver_kill", "controller_kill_mid_failover",
         "member_kill_mid_resilver")


@dataclass(frozen=True, order=True)
class FaultEvent:
    """One scheduled fault.  ``arg``/``arg2`` meaning depends on ``kind``:
    van_delay: arg=seconds; kill/suspend_shard: arg=shard index (arg2 =
    suspend duration seconds); others unused."""

    step: int
    kind: str
    arg: float = 0.0
    arg2: float = 0.0


class FaultSchedule:
    """An immutable, fully materialized list of :class:`FaultEvent`.

    Build one explicitly from events, or :meth:`generate` one from a seed —
    generation consumes a ``np.random.default_rng(seed)`` in a fixed order,
    so the same (seed, kwargs) always yields the identical schedule and
    ``to_json`` is byte-for-byte stable (the replay contract chaos tests
    assert on).
    """

    def __init__(self, events):
        events = list(events)
        bad = sorted({e.kind for e in events} - set(KINDS))
        if bad:
            raise ValueError(f"unknown fault kinds {bad}; known: {KINDS}")
        self.events = sorted(events)
        self._by_step = defaultdict(list)
        for e in self.events:
            self._by_step[int(e.step)].append(e)

    @classmethod
    def generate(cls, *, steps: int, seed: int,
                 van_errors: int = 0, van_delays: int = 0,
                 delay_s: float = 0.02, data_errors: int = 0,
                 nan_steps: int = 0, kill_shards: int = 0,
                 suspend_shards: int = 0, suspend_s: float = 0.3,
                 n_shards: int = 1,
                 preempt_at: int | None = None,
                 worker_losses: int = 0, worker_joins: int = 0,
                 n_workers: int = 1,
                 serve_preempts: int = 0, serve_engine_kills: int = 0,
                 n_members: int = 1,
                 member_kills: int = 0, member_suspends: int = 0,
                 member_suspend_s: float = 0.5,
                 worker_proc_kills: int = 0,
                 netem_partitions: int = 0, netem_partition_s: float = 0.8,
                 netem_degrades: int = 0, netem_degrade_s: float = 1.0,
                 stragglers: int = 0,
                 straggler_s: float = 1.0,
                 stage_kills: int = 0, stage_slows: int = 0,
                 stage_slow_s: float = 1.0,
                 n_stages: int = 1,
                 controller_kills: int = 0,
                 controller_suspends: int = 0,
                 controller_suspend_s: float = 1.0,
                 n_controllers: int = 1,
                 van_kills: int = 0, van_suspends: int = 0,
                 van_suspend_s: float = 1.5,
                 n_vans: int = 1,
                 van_resilver_kills: int = 0,
                 controller_mid_failover_kills: int = 0,
                 member_mid_resilver_kills: int = 0) -> "FaultSchedule":
        """Draw a schedule over training steps ``[1, steps)`` from ``seed``.

        Counts are clipped to the available steps.  Shard-targeted faults
        pick a victim shard uniformly from ``n_shards``.  ``preempt_at`` is
        explicit (a random preemption inside a bounded test run is rarely
        what you want — pass it when you do).

        Elastic membership: ``worker_losses`` permanent DP-worker losses
        (distinct victims drawn from ``n_workers``) and ``worker_joins``
        rejoins — each join revives an earlier-lost worker at a step
        strictly after its loss, so a generated schedule is always
        physically consistent (never joins a worker that is present).
        New draws consume the rng AFTER all pre-existing kinds, so
        schedules generated with the old kwargs are byte-identical.

        Serving-pool faults: ``serve_preempts`` planned member
        preemptions (the pool live-migrates the victim's KV slots) and
        ``serve_engine_kills`` abrupt engine deaths (re-prefill
        failover), each picking a victim member uniformly from
        ``n_members``.  Drawn after everything above — same
        byte-identity guarantee for pre-existing kwargs.

        Process-level faults (cross-process deployments):
        ``member_kills`` SIGKILL a serving-member process,
        ``member_suspends`` SIGSTOP one for ``member_suspend_s``
        seconds (then SIGCONT), ``worker_proc_kills`` SIGKILL a
        training-worker process — victims drawn uniformly from
        ``n_members`` / ``n_workers``, after ALL earlier kinds.

        Network-plane faults (gray failures, ps/netem.py):
        ``netem_partitions`` one-way egress partitions of a member for
        ``netem_partition_s`` seconds, ``netem_degrades`` gray-link
        windows (loss+latency+bandwidth cap) for ``netem_degrade_s``,
        ``stragglers`` slow-link windows on a training worker for
        ``straggler_s`` — victims uniform from ``n_members`` /
        ``n_members`` / ``n_workers``, drawn after EVERY pre-existing
        kind so old-seed schedules replay byte-identical (the frozen-
        bytes regression contract, third extension running).

        Pipeline-stage faults (parallel/mpmd_elastic.py):
        ``stage_kills`` SIGKILL a pipeline-stage process and
        ``stage_slows`` slow-link windows on a stage for
        ``stage_slow_s`` seconds — victims uniform from ``n_stages``,
        drawn after EVERY kind above (fourth extension of the
        frozen-bytes contract).

        Control-plane faults (the controller is just another fault
        domain): ``controller_kills`` SIGKILL a controller process,
        ``controller_suspends`` SIGSTOP one for
        ``controller_suspend_s`` seconds (the zombie-fencing path) —
        victims uniform from ``n_controllers``, drawn after EVERY kind
        above (FIFTH extension of the frozen-bytes contract).

        Durable-tier faults (the van itself): ``van_kills`` SIGKILL a
        primary van process, ``van_suspends`` SIGSTOP one for
        ``van_suspend_s`` seconds (the fenced-resume path) — victims
        uniform from ``n_vans``, drawn after EVERY kind above (SIXTH
        extension of the frozen-bytes contract).

        Sequential-campaign kinds (the SECOND-fault loop):
        ``van_resilver_kills`` kill the promoted primary only after the
        pair re-silvered, ``controller_mid_failover_kills`` kill the
        controller while a van failover is in flight,
        ``member_mid_resilver_kills`` kill a member mid-resilver —
        victims uniform from ``n_vans`` / ``n_controllers`` /
        ``n_members``, drawn after EVERY kind above (SEVENTH extension
        of the frozen-bytes contract).  These kinds are PACED: the
        injector records them (``pop_campaign_events``) and the driver
        applies each only once its precondition (recovery of the
        previous fault / an in-flight failover or resilver) holds.
        """
        rng = np.random.default_rng(seed)
        hi = max(int(steps), 2)

        def pick(n: int) -> list[int]:
            n = min(int(n), hi - 1)
            if n <= 0:
                return []
            return [int(s) for s in rng.choice(np.arange(1, hi), size=n,
                                               replace=False)]

        events = []
        for s in pick(van_errors):
            events.append(FaultEvent(s, "van_error"))
        for s in pick(van_delays):
            events.append(FaultEvent(s, "van_delay", float(delay_s)))
        for s in pick(data_errors):
            events.append(FaultEvent(s, "data_error"))
        for s in pick(nan_steps):
            events.append(FaultEvent(s, "nan_grad"))
        for s in pick(kill_shards):
            events.append(FaultEvent(s, "kill_shard",
                                     float(rng.integers(max(n_shards, 1)))))
        for s in pick(suspend_shards):
            events.append(FaultEvent(s, "suspend_shard",
                                     float(rng.integers(max(n_shards, 1))),
                                     float(suspend_s)))
        if preempt_at is not None:
            events.append(FaultEvent(int(preempt_at), "preempt"))
        n_loss = min(int(worker_losses), max(n_workers - 1, 0), hi - 2)
        if n_loss > 0:
            loss_steps = sorted(pick(n_loss))
            # a joined worker's loss must leave room for a STRICTLY later
            # join step (a same-step pair sorts join-first and the monitor
            # would drop it, silently losing the worker forever): clamp
            # those losses to hi-2.  With hi < 3 there is no such room —
            # the joins are dropped, not mis-scheduled.
            n_join = min(int(worker_joins), n_loss) if hi >= 3 else 0
            if n_join:
                for i in range(n_join):
                    loss_steps[i] = min(loss_steps[i], hi - 2)
                loss_steps.sort()
            victims = [int(v) for v in rng.choice(np.arange(max(n_workers,
                                                                1)),
                                                  size=n_loss,
                                                  replace=False)]
            for s, v in zip(loss_steps, victims):
                events.append(FaultEvent(s, "worker_loss", float(v)))
            for i in range(n_join):
                join_s = int(rng.integers(loss_steps[i] + 1, hi))
                events.append(FaultEvent(join_s, "worker_join",
                                         float(victims[i])))
        for s in pick(serve_preempts):
            events.append(FaultEvent(s, "serve_preempt",
                                     float(rng.integers(max(n_members,
                                                            1)))))
        for s in pick(serve_engine_kills):
            events.append(FaultEvent(s, "serve_engine_kill",
                                     float(rng.integers(max(n_members,
                                                            1)))))
        # process-level kinds: real SIGKILL/SIGSTOP on Popen handles.
        # Drawn after EVERYTHING above — schedules generated with the
        # pre-existing kwargs stay byte-identical (the frozen-bytes test)
        for s in pick(member_kills):
            events.append(FaultEvent(s, "member_kill",
                                     float(rng.integers(max(n_members,
                                                            1)))))
        for s in pick(member_suspends):
            events.append(FaultEvent(s, "member_suspend",
                                     float(rng.integers(max(n_members,
                                                            1))),
                                     float(member_suspend_s)))
        for s in pick(worker_proc_kills):
            events.append(FaultEvent(s, "worker_proc_kill",
                                     float(rng.integers(max(n_workers,
                                                            1)))))
        # network-plane kinds: drawn after everything above — the same
        # frozen-bytes guarantee the process-level kinds honored
        for s in pick(netem_partitions):
            events.append(FaultEvent(s, "netem_partition",
                                     float(rng.integers(max(n_members,
                                                            1))),
                                     float(netem_partition_s)))
        for s in pick(netem_degrades):
            events.append(FaultEvent(s, "netem_degrade",
                                     float(rng.integers(max(n_members,
                                                            1))),
                                     float(netem_degrade_s)))
        for s in pick(stragglers):
            events.append(FaultEvent(s, "straggler",
                                     float(rng.integers(max(n_workers,
                                                            1))),
                                     float(straggler_s)))
        # pipeline-stage kinds: drawn after everything above — the same
        # frozen-bytes guarantee every earlier extension honored
        for s in pick(stage_kills):
            events.append(FaultEvent(s, "stage_kill",
                                     float(rng.integers(max(n_stages,
                                                            1)))))
        for s in pick(stage_slows):
            events.append(FaultEvent(s, "stage_slow",
                                     float(rng.integers(max(n_stages,
                                                            1))),
                                     float(stage_slow_s)))
        # control-plane kinds: drawn after everything above — the same
        # frozen-bytes guarantee every earlier extension honored
        for s in pick(controller_kills):
            events.append(FaultEvent(s, "controller_kill",
                                     float(rng.integers(
                                         max(n_controllers, 1)))))
        for s in pick(controller_suspends):
            events.append(FaultEvent(s, "controller_suspend",
                                     float(rng.integers(
                                         max(n_controllers, 1))),
                                     float(controller_suspend_s)))
        # durable-tier kinds: drawn after everything above — the same
        # frozen-bytes guarantee every earlier extension honored
        for s in pick(van_kills):
            events.append(FaultEvent(s, "van_kill",
                                     float(rng.integers(max(n_vans,
                                                            1)))))
        for s in pick(van_suspends):
            events.append(FaultEvent(s, "van_suspend",
                                     float(rng.integers(max(n_vans,
                                                            1))),
                                     float(van_suspend_s)))
        # sequential-campaign kinds: drawn after everything above — the
        # same frozen-bytes guarantee every earlier extension honored
        for s in pick(van_resilver_kills):
            events.append(FaultEvent(s, "van_resilver_kill",
                                     float(rng.integers(max(n_vans,
                                                            1)))))
        for s in pick(controller_mid_failover_kills):
            events.append(FaultEvent(s, "controller_kill_mid_failover",
                                     float(rng.integers(
                                         max(n_controllers, 1)))))
        for s in pick(member_mid_resilver_kills):
            events.append(FaultEvent(s, "member_kill_mid_resilver",
                                     float(rng.integers(max(n_members,
                                                            1)))))
        return cls(events)

    def at(self, step: int) -> list[FaultEvent]:
        return self._by_step.get(int(step), [])

    def __len__(self) -> int:
        return len(self.events)

    def to_json(self) -> str:
        """Canonical serialization — two schedules are the same chaos run
        iff their to_json bytes are equal."""
        return json.dumps([[e.step, e.kind, e.arg, e.arg2]
                           for e in self.events], separators=(",", ":"))

    @property
    def schedule_id(self) -> str:
        """Stable 8-hex id of the canonical serialization: the tag every
        injected fault's trace instant carries, so a trace names the exact
        chaos run that produced it (same seed+kwargs → same id)."""
        return f"{zlib.crc32(self.to_json().encode()):08x}"

    @classmethod
    def from_json(cls, s: str) -> "FaultSchedule":
        return cls([FaultEvent(int(st), k, float(a), float(a2))
                    for st, k, a, a2 in json.loads(s)])


class FaultInjector:
    """Drives a :class:`FaultSchedule` against a live training run.

    The supervisor calls :meth:`on_step` at the top of every step (arming
    one-shot van/data faults, killing/suspending shard subprocesses,
    delivering the preemption signal) and :meth:`corrupt_batch` on the
    fetched batch.  ``install()`` hooks the van client ops; always pair
    with ``uninstall()`` (the supervisor does both).

    ``counters`` tallies everything injected — the supervisor merges them
    into its own counters so they flow out through ``MetricLogger``.
    """

    def __init__(self, schedule: FaultSchedule, *, shard_procs=(),
                 member_procs=None, worker_procs=None, stage_procs=None,
                 ctrl_procs=None, van_procs=None,
                 pid: int | None = None):
        self.schedule = schedule
        self.shard_procs = list(shard_procs)  # subprocess.Popen-likes
        # LIVE references (not copies): the cross-process pool /
        # multi-controller supervisor revive slots in place, and a fault
        # landing after a revive must target the CURRENT incarnation
        self.member_procs = member_procs if member_procs is not None else []
        self.worker_procs = worker_procs if worker_procs is not None else []
        self.stage_procs = stage_procs if stage_procs is not None else []
        self.ctrl_procs = ctrl_procs if ctrl_procs is not None else []
        self.van_procs = van_procs if van_procs is not None else []
        self.pid = int(pid) if pid is not None else os.getpid()
        self.counters = defaultdict(int)
        self._armed_van = deque()   # one-shot ("error"|"delay", arg)
        self._armed_data = 0
        self._nan_armed = False
        # membership events for the elastic supervisor: ("loss"|"join",
        # worker_idx), drained via pop_worker_events() at the top of each
        # step — the injector records, the supervisor decides
        self.worker_events = deque()
        # serving-pool events: (kind, member_idx), drained via
        # pop_serve_events() by the pool's chaos driver (same record/
        # decide split: the injector cannot reach into the pool's engines)
        self.serve_events = deque()
        # network-plane events: (kind, victim_idx, duration_s), drained
        # via pop_net_events() — the controller applies the link policy
        # through its own control plane (the injector cannot reach into
        # another PROCESS's van hooks)
        self.net_events = deque()
        # sequential-campaign events: (kind, victim_idx), drained via
        # pop_campaign_events() — these kinds are RECOVERY-PACED (kill
        # the promoted primary only after the resilver closed, kill the
        # controller only mid-failover), and only the driver can see
        # that state
        self.campaign_events = deque()
        self._lock = threading.Lock()
        self._prev_hook = None
        self._installed = False

    # ---- lifecycle ----
    def install(self) -> "FaultInjector":
        from hetu_tpu.ps import van
        if not self._installed:
            self._prev_hook = van.set_fault_hook(self._van_hook)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            from hetu_tpu.ps import van
            van.set_fault_hook(self._prev_hook)
            self._installed = False

    # ---- van hook ----
    def _van_hook(self, op: str) -> None:
        with self._lock:
            fault = self._armed_van.popleft() if self._armed_van else None
        if fault is None:
            if self._prev_hook is not None:
                self._prev_hook(op)
            return
        kind, arg = fault
        if kind == "delay":
            self.counters["van_delays_injected"] += 1
            time.sleep(arg)
        else:
            self.counters["van_errors_injected"] += 1
            raise TransientFault(f"injected transient van fault before {op}")

    # ---- per-step driver ----
    def on_step(self, step: int) -> None:
        for ev in self.schedule.at(step):
            self.counters["faults_injected"] += 1
            k = ev.kind
            # one instant per injection: schedule.at() returns a sorted
            # deterministic order, so two runs with the same seed emit the
            # identical instant sequence (the timeline pairing contract)
            trace.instant("fault." + k,
                          {"kind": k, "step": int(step), "arg": ev.arg,
                           "arg2": ev.arg2,
                           "schedule": self.schedule.schedule_id})
            if k == "van_error":
                with self._lock:
                    self._armed_van.append(("error", 0.0))
            elif k == "van_delay":
                with self._lock:
                    self._armed_van.append(("delay", ev.arg or 0.02))
            elif k == "data_error":
                with self._lock:
                    self._armed_data += 1
            elif k == "nan_grad":
                self._nan_armed = True
            elif k == "kill_shard":
                self._kill(int(ev.arg))
            elif k == "suspend_shard":
                self._suspend(int(ev.arg), ev.arg2 or 0.3)
            elif k == "preempt":
                self.counters["preempts_injected"] += 1
                os.kill(self.pid, signal.SIGTERM)
            elif k == "worker_loss":
                self.counters["worker_losses_injected"] += 1
                with self._lock:
                    self.worker_events.append(("loss", int(ev.arg)))
            elif k == "worker_join":
                self.counters["worker_joins_injected"] += 1
                with self._lock:
                    self.worker_events.append(("join", int(ev.arg)))
            elif k in ("serve_preempt", "serve_engine_kill"):
                self.counters[k + "s_injected"] += 1
                with self._lock:
                    self.serve_events.append((k, int(ev.arg)))
            elif k == "member_kill":
                self._proc_kill(self.member_procs, int(ev.arg),
                                "member_procs_killed")
            elif k == "member_suspend":
                self._proc_suspend(self.member_procs, int(ev.arg),
                                   ev.arg2 or 0.5,
                                   "member_procs_suspended")
            elif k == "worker_proc_kill":
                self._proc_kill(self.worker_procs, int(ev.arg),
                                "worker_procs_killed")
            elif k == "stage_kill":
                self._proc_kill(self.stage_procs, int(ev.arg),
                                "stage_procs_killed")
            elif k == "controller_kill":
                self._proc_kill(self.ctrl_procs, int(ev.arg),
                                "controller_procs_killed")
            elif k == "controller_suspend":
                self._proc_suspend(self.ctrl_procs, int(ev.arg),
                                   ev.arg2 or 1.0,
                                   "controller_procs_suspended")
            elif k == "van_kill":
                self._proc_kill(self.van_procs, int(ev.arg),
                                "van_procs_killed")
            elif k == "van_suspend":
                self._proc_suspend(self.van_procs, int(ev.arg),
                                   ev.arg2 or 1.5,
                                   "van_procs_suspended")
            elif k in ("van_resilver_kill", "controller_kill_mid_failover",
                       "member_kill_mid_resilver"):
                self.counters[k + "s_injected"] += 1
                with self._lock:
                    self.campaign_events.append((k, int(ev.arg)))
            elif k == "stage_slow":
                self.counters["stage_slows_injected"] += 1
                with self._lock:
                    self.net_events.append((k, int(ev.arg),
                                            float(ev.arg2) or 1.0))
            elif k in ("netem_partition", "netem_degrade", "straggler"):
                self.counters[k + "s_injected"] += 1
                with self._lock:
                    self.net_events.append((k, int(ev.arg),
                                            float(ev.arg2) or 1.0))

    def pop_serve_events(self) -> list:
        """Drain pending serving-pool events as
        ``[("serve_preempt"|"serve_engine_kill", member_idx)]`` — feed
        them to ``ServingPool.run_fault_events``."""
        with self._lock:
            out = list(self.serve_events)
            self.serve_events.clear()
        return out

    def pop_net_events(self, kinds=None) -> list:
        """Drain pending network-plane events as ``[("netem_partition"
        |"netem_degrade"|"straggler"|"stage_slow", victim_idx,
        duration_s)]`` — feed them to
        ``CrossProcessServingPool.run_net_events`` (serving),
        ``MultiControllerElasticSupervisor`` (stragglers), or
        ``MPMDPipelineSupervisor`` (stage_slow).

        ``kinds`` drains selectively: events of OTHER kinds stay queued
        for the driver that owns them.  A mixed schedule driven by the
        training supervisor (which applies only stragglers) must not
        silently swallow serving-plane partitions its injector already
        recorded as injected — an unclaimed event staying visible in
        the queue is the honest failure mode."""
        with self._lock:
            if kinds is None:
                out = list(self.net_events)
                self.net_events.clear()
            else:
                kinds = set(kinds)
                out = [e for e in self.net_events if e[0] in kinds]
                keep = [e for e in self.net_events if e[0] not in kinds]
                self.net_events.clear()
                self.net_events.extend(keep)
        return out

    def pop_campaign_events(self) -> list:
        """Drain pending sequential-campaign events as
        ``[("van_resilver_kill"|"controller_kill_mid_failover"|
        "member_kill_mid_resilver", victim_idx)]`` — the driver applies
        each once its recovery-aware precondition holds (see
        :class:`SequentialFaultCampaign`)."""
        with self._lock:
            out = list(self.campaign_events)
            self.campaign_events.clear()
        return out

    def pop_worker_events(self) -> list:
        """Drain pending membership events as [("loss"|"join", worker)].
        Called by the elastic supervisor once per step."""
        with self._lock:
            out = list(self.worker_events)
            self.worker_events.clear()
        return out

    def _proc(self, idx: int):
        if 0 <= idx < len(self.shard_procs):
            return self.shard_procs[idx]
        self.counters["shard_faults_skipped_no_proc"] += 1
        return None

    def _kill(self, idx: int) -> None:
        p = self._proc(idx)
        if p is None:
            return
        p.kill()
        p.wait()
        self.counters["shards_killed"] += 1

    def _suspend(self, idx: int, duration_s: float) -> None:
        p = self._proc(idx)
        if p is None:
            return
        p.send_signal(signal.SIGSTOP)
        self.counters["shards_suspended"] += 1
        t = threading.Timer(duration_s,
                            lambda: p.send_signal(signal.SIGCONT))
        t.daemon = True
        t.start()

    # ---- process-level faults (cross-process pools / fleets) ----
    def _pick_proc(self, procs, idx: int):
        """Index modulo the LIVE slot list (a kill drawn for slot k must
        hit a real process even after drains emptied some slots)."""
        live = [p for p in procs if p is not None and p.poll() is None]
        if not live:
            self.counters["proc_faults_skipped_no_proc"] += 1
            return None
        return live[int(idx) % len(live)]

    def _proc_kill(self, procs, idx: int, counter: str) -> None:
        p = self._pick_proc(procs, idx)
        if p is None:
            return
        p.kill()
        p.wait()
        self.counters[counter] += 1

    def _proc_suspend(self, procs, idx: int, duration_s: float,
                      counter: str) -> None:
        p = self._pick_proc(procs, idx)
        if p is None:
            return
        p.send_signal(signal.SIGSTOP)
        self.counters[counter] += 1
        t = threading.Timer(duration_s,
                            lambda: p.send_signal(signal.SIGCONT))
        t.daemon = True
        t.start()

    # ---- batch plumbing ----
    def corrupt_batch(self, step: int, batch):
        """Poison the first float leaf with NaN when a ``nan_grad`` fault
        is armed.  Returns the (possibly copied) batch."""
        if not self._nan_armed:
            return batch
        self._nan_armed = False
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        for i, leaf in enumerate(leaves):
            a = np.asarray(leaf)
            if np.issubdtype(a.dtype, np.floating):
                a = a.copy()
                a.flat[0] = np.nan
                leaves[i] = a
                self.counters["nan_injected"] += 1
                break
        else:
            self.counters["nan_skipped_no_float_leaf"] += 1
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def wrap_batch_fn(self, batch_fn):
        """Wrap a ``batch_fn(step)`` so armed data faults raise
        :class:`TransientDataError` once each (the retry then succeeds)."""
        def wrapped(step):
            with self._lock:
                armed = self._armed_data > 0
                if armed:
                    self._armed_data -= 1
            if armed:
                self.counters["data_errors_injected"] += 1
                raise TransientDataError(
                    f"injected dataloader fault at step {step}")
            return batch_fn(step)
        return wrapped


class SequentialFaultCampaign:
    """A seeded SEQUENCE of faults with recovery-aware pacing — the
    second-fault chaos loop.

    A :class:`FaultSchedule` answers "which faults, at which steps";
    a campaign answers the question one fault at a time: every fault
    after the first is injected into the system state the PREVIOUS
    fault's recovery left behind (van_kill → wait for the promotion to
    re-silver → kill the promoted primary; controller_kill while a van
    failover is in flight; member_kill mid-resilver).  The campaign
    owns the DRAW (seeded, replayable — ``to_json`` is the evidence);
    the driver owns injection, the recovery wait, and the invariant
    asserts, reporting each round back via :meth:`complete`.  Drawing
    the next round before completing the current one is a driver bug
    (the pacing contract IS the campaign), as is completing a round
    never drawn.

    The standing per-round invariants the soak driver asserts (see
    tests/test_soak.py): zero lost accepted requests, token-exact
    serving, byte-identical training, and REDUNDANCY RESTORED (pair
    not degraded) before the next draw.
    """

    KINDS = ("van_kill", "van_resilver_kill",
             "controller_kill_mid_failover", "member_kill_mid_resilver")

    def __init__(self, *, seed: int, rounds: int, kinds=None,
                 n_victims: int = 1):
        self.seed = int(seed)
        self.kinds = tuple(kinds if kinds is not None else self.KINDS)
        bad = sorted(set(self.kinds) - set(KINDS))
        if bad:
            raise ValueError(f"unknown campaign kinds {bad}")
        rng = np.random.default_rng(self.seed)
        # one (kind, victim) pair per round, drawn up front: the draw
        # order is the replay contract, so pacing (which happens at
        # drive time) can never perturb WHAT is injected
        self.draws = [(self.kinds[int(rng.integers(len(self.kinds)))],
                       int(rng.integers(max(int(n_victims), 1))))
                      for _ in range(int(rounds))]
        self._next = 0
        self._open = False
        self.results: list = []

    @property
    def campaign_id(self) -> str:
        return f"{zlib.crc32(self.to_json().encode()):08x}"

    def to_json(self) -> str:
        return json.dumps([[k, v] for k, v in self.draws],
                          separators=(",", ":"))

    def draw(self) -> tuple:
        """The next round's ``(kind, victim)``.  Emits the fault
        instant (``fault.<kind>``) so the timeline pairing sees the
        campaign exactly like a scheduled fault."""
        if self._open:
            raise ValueError(
                "previous round not completed — recovery-aware pacing "
                "means one fault in flight at a time")
        if self._next >= len(self.draws):
            raise IndexError("campaign exhausted")
        kind, victim = self.draws[self._next]
        self._open = True
        trace.instant("fault." + kind,
                      {"kind": kind, "step": self._next, "arg": victim,
                       "campaign": self.campaign_id})
        return kind, victim

    def complete(self, *, ok: bool, recovery_s: float = 0.0,
                 detail: dict | None = None) -> None:
        """Close the in-flight round: the driver verified recovery (or
        gave up).  ``recovery_s`` is fault→redundancy-restored wall
        time as the driver measured it."""
        if not self._open:
            raise ValueError("no round in flight")
        kind, victim = self.draws[self._next]
        self.results.append({"round": self._next, "kind": kind,
                             "victim": victim, "ok": bool(ok),
                             "recovery_s": float(recovery_s),
                             **(detail or {})})
        self._open = False
        self._next += 1

    @property
    def exhausted(self) -> bool:
        return self._next >= len(self.draws)

    def report(self) -> dict:
        """Rounds survived / drawn, plus per-kind recovery seconds —
        the ``bench.py soak`` headline inputs."""
        ok = [r for r in self.results if r["ok"]]
        per_kind: dict = defaultdict(list)
        for r in self.results:
            per_kind[r["kind"]].append(r["recovery_s"])
        return {"campaign_id": self.campaign_id,
                "rounds_drawn": len(self.results),
                "rounds_total": len(self.draws),
                "rounds_survived": len(ok),
                "recovery_s_by_kind": {k: sorted(v)
                                       for k, v in per_kind.items()}}
