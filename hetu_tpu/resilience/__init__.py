"""Fault-tolerant training runtime: deterministic chaos harness +
supervisor (checkpoint retention, retry, NaN guard, PS shard repair) +
elastic mesh resharding (survive permanent worker loss/rejoin) +
multi-controller elastic training across real process boundaries
(resilience/multicontroller.py — imported lazily there, not here: it
drags the van/membership plane in, and the in-process supervisors must
stay importable without it).

See README "Fault tolerance", "Elastic operation", and "Cross-host
deployment" for usage and guarantees/limits.
"""

from hetu_tpu.resilience.elastic import (
    ElasticReshardError, ElasticSupervisor, MembershipMonitor, ResizeEvent,
)
from hetu_tpu.resilience.faults import (
    FaultEvent, FaultInjector, FaultSchedule, TransientDataError,
    TransientFault,
)
from hetu_tpu.resilience.supervisor import (
    CheckpointManager, NonFiniteAbort, PSShardGuard, Supervisor,
    SupervisorReport, default_is_transient,
)

__all__ = [
    "FaultEvent", "FaultInjector", "FaultSchedule", "TransientDataError",
    "TransientFault", "CheckpointManager", "NonFiniteAbort", "PSShardGuard",
    "Supervisor", "SupervisorReport", "default_is_transient",
    "ElasticReshardError", "ElasticSupervisor", "MembershipMonitor",
    "ResizeEvent",
]
