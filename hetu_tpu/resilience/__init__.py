"""Fault-tolerant training runtime: deterministic chaos harness +
supervisor (checkpoint retention, retry, NaN guard, PS shard repair).

See README "Fault tolerance" for usage and guarantees/limits.
"""

from hetu_tpu.resilience.faults import (
    FaultEvent, FaultInjector, FaultSchedule, TransientDataError,
    TransientFault,
)
from hetu_tpu.resilience.supervisor import (
    CheckpointManager, NonFiniteAbort, PSShardGuard, Supervisor,
    SupervisorReport, default_is_transient,
)

__all__ = [
    "FaultEvent", "FaultInjector", "FaultSchedule", "TransientDataError",
    "TransientFault", "CheckpointManager", "NonFiniteAbort", "PSShardGuard",
    "Supervisor", "SupervisorReport", "default_is_transient",
]
