"""Process harness: spawn READY-handshaking subprocesses.

Started life as the chaos tests' throwaway PS-shard spawner; now the
generic bootstrap the whole cross-process control plane shares — PS van
shards, serving-member processes (``hetu_tpu.serve.crosshost``), and
multi-controller training workers (``hetu_tpu.resilience.
multicontroller``) all come up through here, so the handshake (spawn,
wait for a READY line, fail loudly with the process's output otherwise)
and the spawn environment (``launcher.spawn_local``: repo PYTHONPATH,
optional forced-CPU device world) live in ONE place.  The returned
``Popen`` handles are exactly what
:class:`~hetu_tpu.resilience.faults.FaultInjector`'s process-level fault
kinds (``kill_shard``, ``member_kill``, ``worker_proc_kill``, ...)
target.
"""

from __future__ import annotations

import socket
import subprocess
import sys
import time
from pathlib import Path

_REPO = Path(__file__).resolve().parents[2]

_SERVER_SRC = """\
import os, sys, time
sys.path.insert(0, {repo!r})
# the durable tier's own flight recorder (PR 14 instrumented members/
# workers/stages; the van was the gap): a SIGKILLed primary's final
# spans — and the serve instant below — survive on disk for
# tools/fleet_report.py.  HETU_OBS_STREAM=0 disables like everywhere.
from hetu_tpu.telemetry import trace
trace.open_process_stream({trace_dir!r}, "van_p%d" % os.getpid())
from hetu_tpu.ps import van
port = van.serve({port})
trace.instant("van.serve", {{"port": port, "pid": os.getpid()}},
              cat="van")
print("READY", port, flush=True)
time.sleep({lifetime})
"""

# a van server that REGISTERS with a scheduler (the postoffice server
# role) — the rejoin-at-a-new-address path the heartbeat tests exercise
_REGISTERED_SERVER_SRC = """\
import os, sys, time
sys.path.insert(0, {repo!r})
from hetu_tpu.telemetry import trace
trace.open_process_stream({trace_dir!r}, "van_p%d" % os.getpid())
from hetu_tpu.ps import van
port, rank = van.serve_and_register("127.0.0.1", {sched_port},
                                    port={port}, rank_hint={rank_hint},
                                    beat_ms={beat_ms})
trace.instant("van.serve", {{"port": port, "rank": rank,
                             "pid": os.getpid()}}, cat="van")
print("READY", port, rank, flush=True)
time.sleep({lifetime})
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_ready(workdir, tag: str, src: str, **fmt) -> subprocess.Popen:
    """Write ``src.format(repo=..., **fmt)`` as a script, spawn it, and
    block until it prints a READY line on stdout (stashed on the handle
    as ``proc.ready`` — e.g. the bound port).  The caller owns the
    returned ``Popen`` — kill()/wait() it; chaos does exactly that."""
    script = Path(workdir) / f"{tag}.py"
    script.write_text(src.format(repo=str(_REPO), **fmt))
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    if not line.startswith("READY"):
        proc.kill()
        proc.wait()
        raise RuntimeError(f"{tag}: process failed to start: {line!r}")
    proc.ready = line.split()[1:]
    return proc


def spawn_module(workdir, tag: str, module: str, args, *,
                 cpu_devices: int | None = None,
                 extra_env: dict | None = None,
                 timeout_s: float = 120.0) -> subprocess.Popen:
    """Spawn ``python -m module *args`` and wait for its READY line.

    Unlike :func:`spawn_ready`, stdout/stderr go to a LOG FILE in
    ``workdir`` (``<tag>.log``, path stashed as ``proc.log_path``) and
    READY is awaited by tailing it: long-lived member/worker processes
    print tracebacks and progress, and an unread stdout PIPE would
    eventually fill and wedge them — a deadlock indistinguishable from
    the very hangs the chaos harness injects on purpose."""
    from hetu_tpu.launcher import spawn_local
    log_path = Path(workdir) / f"{tag}.log"
    with open(log_path, "w") as log:
        # the child inherits its own copy of the fd; holding the parent's
        # open would leak one fd per spawn (revive/replacement loops)
        proc = spawn_local([sys.executable, "-m", module,
                            *map(str, args)],
                           cpu_devices=cpu_devices, extra_env=extra_env,
                           stdout=log, stderr=subprocess.STDOUT)
    proc.log_path = log_path
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if "READY" in log_path.read_text(errors="replace"):
            proc.ready = True
            return proc
        if proc.poll() is not None:
            raise RuntimeError(
                f"{tag}: process exited rc={proc.returncode} before "
                f"READY:\n{log_path.read_text(errors='replace')[-2000:]}")
        time.sleep(0.05)
    proc.kill()
    proc.wait()
    raise TimeoutError(
        f"{tag}: no READY within {timeout_s}s:\n"
        f"{log_path.read_text(errors='replace')[-2000:]}")


def spawn_shard_server(workdir, port: int, tag: str = "s", *,
                       lifetime_s: int = 600) -> subprocess.Popen:
    """Start a van server subprocess on ``port``; blocks until READY
    (the server is accepting connections)."""
    return spawn_ready(workdir, f"shard_server_{tag}", _SERVER_SRC,
                       port=int(port), lifetime=int(lifetime_s),
                       trace_dir=str(workdir))


def spawn_registered_server(workdir, sched_port: int, tag: str = "r", *,
                            port: int = 0, rank_hint: int = -1,
                            beat_ms: int = 200,
                            lifetime_s: int = 600) -> subprocess.Popen:
    """Start a van server that registers with the scheduler at
    ``sched_port`` (native beat thread keeps the registration live);
    ``proc.ready`` holds ``[bound_port, rank]``."""
    return spawn_ready(workdir, f"reg_server_{tag}",
                       _REGISTERED_SERVER_SRC, sched_port=int(sched_port),
                       port=int(port), rank_hint=int(rank_hint),
                       beat_ms=int(beat_ms), lifetime=int(lifetime_s),
                       trace_dir=str(workdir))
