"""Spawn throwaway PS van server subprocesses.

Shared by the chaos tests and ``bench.py resilience`` — the
:class:`~hetu_tpu.resilience.faults.FaultInjector`'s kill/suspend targets
are exactly these ``Popen`` handles, so keeping the bootstrap (inline
script, READY handshake, port allocation) in ONE place keeps the harness
and the bench from drifting apart.
"""

from __future__ import annotations

import socket
import subprocess
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[2]

_SERVER_SRC = """\
import sys, time
sys.path.insert(0, {repo!r})
from hetu_tpu.ps import van
port = van.serve({port})
print("READY", port, flush=True)
time.sleep({lifetime})
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_shard_server(workdir, port: int, tag: str = "s", *,
                       lifetime_s: int = 600) -> subprocess.Popen:
    """Start a van server subprocess on ``port``; blocks until it prints
    READY (the server is accepting connections).  The caller owns the
    returned ``Popen`` — kill()/wait() it (chaos does exactly that)."""
    script = Path(workdir) / f"shard_server_{tag}.py"
    script.write_text(_SERVER_SRC.format(repo=str(_REPO), port=int(port),
                                         lifetime=int(lifetime_s)))
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    if not line.startswith("READY"):
        proc.kill()
        proc.wait()
        raise RuntimeError(f"shard server failed to start: {line!r}")
    return proc
