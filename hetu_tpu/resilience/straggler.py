"""Shared straggler detection: the slow-vs-dead split the lease machine
cannot make.

A straggler's beats FLOW while its reported WORK time grows — the lease
state machine never fires, yet the lockstep barriers pace the whole
fleet at its speed.  Both cross-process training planes (the dp
multi-controller fleet, ``resilience/multicontroller.py``, and the MPMD
pipeline, ``parallel/mpmd_elastic.py``) detect it the same way: a
member whose reported work time exceeds ``factor`` x the median of its
peers' opens a retroactive ``train.straggler`` span (closed when it
recovers, departs, or the policy acts).  This module is the ONE copy of
that episode machinery; the POLICY (wait / evict-and-reshard /
probation re-admission) stays with each supervisor — a pipeline stage
is not redundant, so only the dp plane can evict.
"""

from __future__ import annotations

import time

import numpy as np

from hetu_tpu.telemetry import trace


class StragglerDetector:
    """Median-of-peers slow-member detection with per-episode
    ``train.straggler`` spans.

    ``observe(loads, present=..., committed=...)`` runs one sweep:
    ``loads`` maps candidate slot -> reported work ms (callers exclude
    members whose loads must not count — evicted, suspect);
    ``present`` lists slots still around (an open episode whose slot
    left both closes as ``departed``); ``committed`` (optional) maps
    slot -> committed step for evict-threshold accounting.  Returns the
    slots whose episode crossed ``evict_after`` slow committed steps
    this sweep (empty when ``evict_after`` is 0) — the CALLER decides
    what crossing means.
    """

    def __init__(self, *, factor: float, subject: str = "worker",
                 policy: str = "wait", evict_after: int = 0):
        self.factor = float(factor)
        self.subject = subject
        self.policy = policy
        self.evict_after = int(evict_after)
        self.records: list = []   # closed episodes, span args verbatim
        self._open: dict = {}     # slot -> episode state

    def observe(self, loads: dict, *, present=(),
                committed=None) -> list:
        present = set(present)
        for slot in list(self._open):
            if slot not in loads and slot not in present:
                self.close(slot, resolution="departed")
        if len(loads) < 2:
            return []
        crossed = []
        for slot, work_ms in loads.items():
            others = [v for s, v in loads.items() if s != slot]
            med = float(np.median(others))
            slow = work_ms > self.factor * max(med, 1e-3)
            st = self._open.get(slot)
            c = int(committed.get(slot, 0)) if committed else 0
            if slow and st is None:
                self._open[slot] = {
                    "t0_us": trace.now_us(),
                    "detected_at_step": c,
                    "last_step": c, "slow_steps": 0,
                    "ratio": work_ms / max(med, 1e-3)}
            elif slow and st is not None:
                st["ratio"] = max(st["ratio"], work_ms / max(med, 1e-3))
                if c > st["last_step"]:
                    st["slow_steps"] += c - st["last_step"]
                    st["last_step"] = c
                if self.evict_after and \
                        st["slow_steps"] >= self.evict_after:
                    crossed.append(slot)
            elif not slow and st is not None:
                # back under the bar: the episode closes as tolerated
                self.close(slot, resolution="recovered")
        return crossed

    def close(self, slot, *, resolution: str) -> None:
        st = self._open.pop(slot, None)
        if st is None:
            return
        rec = {self.subject: int(slot), "policy": self.policy,
               "resolution": resolution,
               "ratio": round(float(st["ratio"]), 2),
               "slow_steps": int(st["slow_steps"])}
        trace.complete("train.straggler", st["t0_us"], rec, cat="train")
        self.records.append(rec)

    def close_all(self, *, resolution: str = "run_end") -> None:
        """Flush every still-open episode (run end: an unclosed span
        would silently drop the episode from the trace)."""
        for slot in list(self._open):
            self.close(slot, resolution=resolution)

    def open_slots(self) -> list:
        return list(self._open)


class SupervisorStragglerPlane:
    """Supervisor-side straggler glue, shared by the cross-process
    training planes (the dp multi-controller fleet and the MPMD
    pipeline) so the two copies cannot drift: slow-link INJECTION via
    the control row's ``C_SLOW_*`` fields with a scheduled self-heal,
    and the per-sweep load/committed extraction feeding the shared
    :class:`StragglerDetector`.

    The heal is applied by :meth:`maybe_heal` from the supervisor's
    ``poll()`` — NOT by a timer thread — so every control-row write
    stays serialized with the two-phase epoch publishes (a concurrent
    ``set_slow`` could republish a stale snapshot — e.g. re-expose a
    mid-PREPARE ``phase=1`` row after the supervisor already committed
    ``phase=0`` — and stall the whole fleet on an epoch that will
    never commit).  The POLICY on a crossed threshold stays with each
    supervisor: only the dp plane has a redundant member to evict.
    """

    def __init__(self, svc, *, factor: float, subject: str,
                 policy: str = "wait", evict_after: int = 0,
                 slow_ms: int = 120):
        self.svc = svc
        self.slow_ms = int(slow_ms)
        self.detector = StragglerDetector(
            factor=float(factor), subject=subject, policy=policy,
            evict_after=int(evict_after))
        self._heal_at = None

    def inject(self, slot: int, duration_s: float,
               slow_ms=None) -> None:
        """Apply the slow-link chaos fault: publish the control row's
        slow fields (no epoch bump — a slow link is not a membership
        change) and schedule the heal for the next poll past its due
        time."""
        ms = self.slow_ms if slow_ms is None else int(slow_ms)
        self.svc.set_slow(int(slot), ms)
        self._heal_at = time.monotonic() + float(duration_s)

    def maybe_heal(self) -> None:
        if self._heal_at is not None and \
                time.monotonic() >= self._heal_at:
            self._heal_at = None
            self.svc.set_slow(-1, 0)

    def observe(self, candidate_slots) -> list:
        """One sweep over the supervisors' candidate slots (callers
        pass alive, non-excluded membership): loads are the reported
        WORK-only ms from the heartbeat load field (zero = no evidence,
        excluded), committed feeds the evict-threshold accounting.
        Returns the slots whose episode crossed the evict bar."""
        loads = {s: self.svc.state_of(s).load for s in candidate_slots
                 if self.svc.state_of(s).load > 0.0}
        committed = {s: self.svc.state_of(s).committed
                     for s in candidate_slots}
        return self.detector.observe(loads, present=candidate_slots,
                                     committed=committed)

    @property
    def records(self) -> list:
        return self.detector.records

    def close(self, slot, *, resolution: str) -> None:
        self.detector.close(slot, resolution=resolution)

    def close_all(self, *, resolution: str = "run_end") -> None:
        self.detector.close_all(resolution=resolution)
