"""Initializers.

Reference: python/hetu/initializers.py (Constant/Zeros/Ones/Uniform/Normal/
TruncatedNormal/Xavier(Glorot)/He variants, 433 LoC).  Functional: each
initializer is `fn(key, shape, dtype) -> array`, composable with the module
system; `init_on_ps` semantics (server-side seeded init) are reproduced by the
PS plane reusing the same functions with the same (seed, seqnum) stream.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def constant(value=0.0):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return init


def zeros():
    return constant(0.0)


def ones():
    return constant(1.0)


def uniform(minval=-0.05, maxval=0.05):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval, maxval)
    return init


def normal(mean=0.0, stddev=0.05):
    def init(key, shape, dtype=jnp.float32):
        return mean + stddev * jax.random.normal(key, shape, dtype)
    return init


def truncated_normal(mean=0.0, stddev=0.05):
    def init(key, shape, dtype=jnp.float32):
        return mean + stddev * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype)
    return init


def _fans(shape):
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) == 4:  # OIHW conv
        rf = shape[2] * shape[3]
        return shape[1] * rf, shape[0] * rf
    fan = int(math.sqrt(math.prod(shape)))
    return fan, fan


def xavier_uniform(gain: float = 1.0):
    def init(key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        limit = gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -limit, limit)
    return init


def xavier_normal(gain: float = 1.0):
    def init(key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        std = gain * math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    return init


def he_uniform(gain: float = 1.0):
    def init(key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        limit = gain * math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -limit, limit)
    return init


def he_normal(gain: float = 1.0):
    def init(key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        std = gain * math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    return init


# aliases matching the reference's naming
glorot_uniform = xavier_uniform
glorot_normal = xavier_normal
kaiming_uniform = he_uniform
kaiming_normal = he_normal
