"""Platform forcing + dead-backend watchdog, shared by every entry point.

The harness presets ``JAX_PLATFORMS=axon`` (a tunneled TPU) and a
sitecustomize pre-imports jax, which creates two recurring hazards:

1. env vars alone cannot switch platforms after import — only a post-import
   ``jax.config.update("jax_platforms", ...)`` works (before the first
   backend query);
2. the first backend touch (``jax.devices()`` / ``jax.device_count()``)
   blocks *forever* when the tunnel is down, so unguarded entry points hang
   until an external timeout kills them.

Counterpart of the reference's device bootstrap in
``python/hetu/gpu_ops/executor.py`` (wrapped_mpi_nccl_init) — there the
failure mode is an MPI abort; here it is a silent hang, hence the watchdog.
"""

from __future__ import annotations

import os
import re
import threading

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def device_watchdog(timeout_s: float = 180.0, *, exit_code: int = 3,
                    label: str = "device backend", exit_on_fail: bool = True):
    """Touch the backend under a timeout; exit ``exit_code`` fast on a hang.

    Returns the device list on success.  A dead tunnel otherwise hangs the
    process until the driver's own timeout fires (rc=124) — exiting nonzero
    quickly is strictly better for any batch runner.  With
    ``exit_on_fail=False`` a failure returns ``None`` instead, for callers
    with their own degradation path (:func:`wait_for_devices`).
    """
    import sys

    import jax

    found = {}

    def probe():
        try:
            found["devs"] = jax.devices()
        except Exception as e:  # pragma: no cover - backend-specific
            found["err"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devs" not in found:
        msg = (f"{label} error: {found['err']!r}" if "err" in found
               else f"{label} unreachable within {timeout_s}s — tunnel down?")
        print(msg, file=sys.stderr, flush=True)
        if exit_on_fail:
            os._exit(exit_code)
        return None
    return found["devs"]


def wait_for_devices(deadline_s: float = 600.0, *,
                     probe_timeout_s: float = 90.0, poll_s: float = 5.0,
                     label: str = "device backend"):
    """Poll for a live backend with subprocess probes, then bind in-process.

    :func:`device_watchdog` is right for a fail-fast gate but wrong for a
    once-per-round benchmark: a single tunnel blip at capture time wastes the
    whole round's perf evidence.  This waits up to ``deadline_s`` for the
    backend to answer.  Probes run in SUBPROCESSES because a hung in-process
    ``jax.devices()`` wedges backend-init state for every later attempt in
    the same interpreter; a killed subprocess leaves this process clean.

    Returns the in-process device list on success, ``None`` if the deadline
    expires without a live backend (caller decides how to degrade).
    """
    import subprocess
    import sys
    import time

    env = os.environ.copy()
    if env.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # the tunnel plugin's sitecustomize blocks at interpreter start when
        # the tunnel is down, even though the probe only wants CPU — drop the
        # plugin's site dir so a CPU probe cannot hang on a dead tunnel.
        # HETU_TUNNEL_SITE overrides; the default matches only a path
        # *component* named for the plugin, not any substring (a user dir
        # like .../taxonomy must survive).
        plug = os.environ.get("HETU_TUNNEL_SITE")

        def _is_plugin_dir(p):
            if plug:
                return os.path.abspath(p) == os.path.abspath(plug)
            # a component NAMED for the plugin (.axon_site, axon, axon-*);
            # 'taxonomy' has no component whose name starts with 'axon'
            return any(part.lstrip(".").startswith("axon")
                       for part in p.split(os.sep))

        env["PYTHONPATH"] = os.pathsep.join(
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and not _is_plugin_dir(p))
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, timeout=probe_timeout_s, text=True,
                env=env)
            ok = r.returncode == 0 and r.stdout.strip().isdigit()
        except subprocess.TimeoutExpired:
            ok = False
        if ok:
            # the tunnel answers; bind this process's backend (still under a
            # watchdog in case it dropped again between probe and bind —
            # a bind failure degrades to None, never a hard exit, so the
            # caller's own fallback still runs)
            left = max(probe_timeout_s, deadline_s - (time.monotonic() - start))
            devs = device_watchdog(left, label=label, exit_on_fail=False)
            if devs is not None:
                return devs
            # bind failed after a good probe: fall through to retry/deadline
        waited = time.monotonic() - start
        if waited >= deadline_s:
            print(f"{label} unreachable after {attempt} probes over "
                  f"{waited:.0f}s — tunnel down?", file=sys.stderr, flush=True)
            return None
        time.sleep(poll_s)


def apply_env_platform() -> None:
    """Re-assert ``JAX_PLATFORMS`` from the environment.

    The tunnel plugin's sitecustomize force-sets the platform config at
    interpreter start, so the env var alone is ignored once jax is
    imported; entry points (examples, bench) call this so
    ``JAX_PLATFORMS=cpu python examples/...`` runs anywhere — including
    with the TPU tunnel down.  A no-op when the var is unset or a backend
    already initialized."""
    want = os.environ.get("JAX_PLATFORMS", "").strip()
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backend already initialized: too late, leave it


def default_virtual_devices(n: int = 8) -> None:
    """Give the HOST platform ``n`` virtual devices unless the user already
    chose a count — examples that build multi-device meshes call this
    before importing jax so a bare ``python examples/foo.py`` works on a
    1-CPU box.  Harmless on real-TPU runs: the flag only affects the cpu
    platform, which a live TPU backend never selects."""
    flags = os.environ.get("XLA_FLAGS", "")
    if _COUNT_FLAG not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} {_COUNT_FLAG}={n}".strip()


def bootstrap_example(n_devices: int = 8) -> None:
    """The shared example preamble: give the host platform ``n_devices``
    virtual devices (bare CPU runs still build multi-device meshes) and
    re-assert JAX_PLATFORMS past the tunnel sitecustomize.  Call BEFORE
    importing jax."""
    default_virtual_devices(n_devices)
    apply_env_platform()


def default_backend_is_tpu() -> bool:
    """Whether the default backend is a real TPU (cached after first call).

    Used by kernels to auto-select compiled vs interpret mode.  Callers are
    expected to be on an execution path where the backend is already live
    (inside/around jit) — entry points that might race a dead tunnel should
    go through :func:`device_watchdog` first.
    """
    global _IS_TPU
    if _IS_TPU is None:
        import jax

        _IS_TPU = jax.default_backend() == "tpu"
    return _IS_TPU


_IS_TPU = None


def force_cpu_devices(n_devices: int, timeout_s: float = 120.0):
    """Force an ``n_devices``-virtual-device CPU backend, safely.

    Sets/repairs ``XLA_FLAGS`` (replacing a stale smaller count), forces the
    CPU platform via config (env alone is too late once jax is imported),
    then touches the backend under a watchdog.  Returns the jax module.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None or int(m.group(1)) < n_devices:
        if m is not None:
            flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
        else:
            flags = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    global _IS_TPU
    _IS_TPU = False  # invalidate the backend-kind cache: we just switched

    import jax

    jax.config.update("jax_platforms", "cpu")
    devs = device_watchdog(timeout_s, label="cpu backend")
    if len(devs) < n_devices:
        # a backend initialized before we could force flags; one retry after
        # dropping it (re-init reads the updated XLA_FLAGS + platform config)
        try:
            import jax.extend.backend
            jax.extend.backend.clear_backends()
        except Exception:
            pass
        if jax.device_count() < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {jax.device_count()}; set "
                f"XLA_FLAGS={_COUNT_FLAG}=N and JAX_PLATFORMS=cpu before "
                "importing jax")
    return jax


def auto_interpret(interpret):
    """Pallas kernels' shared interpret default: compiled on a real TPU
    backend, interpret elsewhere (CPU tests).  Pass an explicit bool to
    override."""
    if interpret is None:
        return not default_backend_is_tpu()
    return interpret
