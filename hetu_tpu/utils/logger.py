"""Training metric logger.

Reference: python/hetu/logger.py (HetuLogger with NCCL-allreduced scalars,
WandbLogger wired in executor.py:402-415).  Here scalar aggregation across
shards already happened inside the jitted step (psum/pmean), so the logger
is host-side bookkeeping: running means per key, step timing, optional
wandb passthrough when the package + env are present.
"""

from __future__ import annotations

import json
import time
from collections import defaultdict
from pathlib import Path
from typing import Optional


class MetricLogger:
    def __init__(self, log_path: Optional[str] = None, *,
                 use_wandb: bool = False, wandb_kwargs: Optional[dict] = None):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self.counters = defaultdict(int)  # monotonic event counters
        self.step = 0
        self.t0 = time.perf_counter()
        self.log_file = open(log_path, "a") if log_path else None
        self.wandb = None
        if use_wandb:  # pragma: no cover - optional dependency
            try:
                import wandb
                wandb.init(**(wandb_kwargs or {}))
                self.wandb = wandb
            except Exception:
                self.wandb = None

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        self.step = step if step is not None else self.step + 1
        for k, v in metrics.items():
            self.totals[k] += float(v)
            self.counts[k] += 1
        if self.wandb is not None:  # pragma: no cover
            self.wandb.log({k: float(v) for k, v in metrics.items()},
                           step=self.step)
        if self.log_file:
            rec = {"step": self.step,
                   "t": round(time.perf_counter() - self.t0, 3),
                   **{k: float(v) for k, v in metrics.items()}}
            self.log_file.write(json.dumps(rec) + "\n")
            self.log_file.flush()

    def inc(self, name: str, n: int = 1) -> int:
        """Bump a monotonic event counter (fault injected, retry, shard
        repair, ...) — unlike ``log`` scalars these are never averaged;
        ``counters_snapshot`` folds them into one loggable record."""
        self.counters[name] += int(n)
        return self.counters[name]

    def counters_snapshot(self) -> dict:
        return dict(self.counters)

    def means(self) -> dict:
        return {k: self.totals[k] / max(self.counts[k], 1)
                for k in self.totals}

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()

    def close(self) -> None:
        if self.log_file:
            self.log_file.close()
