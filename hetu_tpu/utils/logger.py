"""Training metric logger.

Reference: python/hetu/logger.py (HetuLogger with NCCL-allreduced scalars,
WandbLogger wired in executor.py:402-415).  Here scalar aggregation across
shards already happened inside the jitted step (psum/pmean), so the logger
is host-side bookkeeping: running means per key, step timing, optional
wandb passthrough when the package + env are present.

Since the telemetry tier landed, the logger is a thin facade over a
:class:`~hetu_tpu.telemetry.registry.MetricsRegistry` — ``inc`` counters
are typed :class:`Counter` objects and ``log`` scalars mirror into
gauges, so a run's metrics come out EITHER the historical way
(``means()``/``counters_snapshot()``/the JSONL log file) or as a
Prometheus text exposition (``prometheus_text()``).  The public API is
unchanged: every pre-telemetry call site keeps working.
"""

from __future__ import annotations

import json
import time
from collections import defaultdict
from pathlib import Path
from typing import Optional

from hetu_tpu.telemetry.registry import MetricsRegistry


class MetricLogger:
    def __init__(self, log_path: Optional[str] = None, *,
                 use_wandb: bool = False, wandb_kwargs: Optional[dict] = None,
                 registry: Optional[MetricsRegistry] = None):
        # counters and log() scalars are SEPARATE namespaces (historically
        # two dicts): the supervisor both inc()s "checkpoints" and log()s
        # a "checkpoints" scalar in its final counter snapshot, so they
        # get separate registries and prometheus_text() merges them
        # (counters render with the _total suffix, so names never clash)
        self.registry = registry if registry is not None else MetricsRegistry()
        self._scalar_registry = MetricsRegistry()
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self.step = 0
        self.t0 = time.perf_counter()
        self.log_file = None
        if log_path:
            p = Path(log_path)
            # a log path in a not-yet-created run directory must not crash
            # the run it was supposed to observe
            p.parent.mkdir(parents=True, exist_ok=True)
            self.log_file = open(p, "a")
        self.wandb = None
        if use_wandb:  # pragma: no cover - optional dependency
            try:
                import wandb
                wandb.init(**(wandb_kwargs or {}))
                self.wandb = wandb
            except Exception:
                self.wandb = None

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        self.step = step if step is not None else self.step + 1
        for k, v in metrics.items():
            v = float(v)
            self.totals[k] += v
            self.counts[k] += 1
            self._scalar_registry.gauge(k).set(v)
        if self.wandb is not None:  # pragma: no cover
            self.wandb.log({k: float(v) for k, v in metrics.items()},
                           step=self.step)
        if self.log_file:
            rec = {"step": self.step,
                   "t": round(time.perf_counter() - self.t0, 3),
                   **{k: float(v) for k, v in metrics.items()}}
            self.log_file.write(json.dumps(rec) + "\n")
            self.log_file.flush()

    def inc(self, name: str, n: int = 1) -> int:
        """Bump a monotonic event counter (fault injected, retry, shard
        repair, ...) — unlike ``log`` scalars these are never averaged;
        ``counters_snapshot`` folds them into one loggable record."""
        return self.registry.counter(name).inc(int(n))

    def counters_snapshot(self) -> dict:
        from hetu_tpu.telemetry.registry import Counter
        return {name: m.value for name, m in self.registry.metrics().items()
                if isinstance(m, Counter)}

    @property
    def counters(self) -> dict:
        """Historical attribute shape (was a defaultdict): the live
        counter values by name."""
        return self.counters_snapshot()

    def means(self) -> dict:
        return {k: self.totals[k] / max(self.counts[k], 1)
                for k in self.totals}

    def reset(self, counters: bool = False) -> None:
        """Clear the running means.  Monotonic counters survive by
        default — chaos tests that deliberately zero them between phases
        pass ``counters=True`` (an explicit choice, never a side effect
        of resetting scalar means)."""
        self.totals.clear()
        self.counts.clear()
        if counters:
            from hetu_tpu.telemetry.registry import Counter
            for m in self.registry.metrics().values():
                if isinstance(m, Counter):
                    m.reset()

    def prometheus_text(self) -> str:
        """Text exposition of everything this logger holds: counters
        (``inc``, rendered with the conventional ``_total`` suffix, so an
        inc()/log() name shared across the two namespaces stays unique)
        plus gauges for the latest ``log`` scalars.  A SHARED registry
        (``registry=`` at construction) may hold non-counter metrics from
        other instrumentation — those render with their real types."""
        from hetu_tpu.telemetry.registry import (
            Counter, MetricsRegistry, _prom_name,
        )
        lines = []
        others = MetricsRegistry()
        for name, m in sorted(self.registry.metrics().items()):
            if isinstance(m, Counter):
                pname = _prom_name(name) + "_total"
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {m.value}")
            else:
                others._metrics[name] = m
        return "\n".join(lines) + ("\n" if lines else "") \
            + others.prometheus_text() \
            + self._scalar_registry.prometheus_text()

    def close(self) -> None:
        if self.log_file:
            self.log_file.close()
