"""Evaluation metrics.

Reference: python/hetu/metrics.py (359 LoC — Accuracy/AUC/F1 etc. used by the
CTR examples).  numpy implementations; the executor aggregates per-batch
values and (in distributed runs) means across dp shards — cross-rank metric
reduction is one jnp.mean under SPMD rather than the reference's
NCCL-allreduce logger plumbing (logger.py:14+).
"""

from __future__ import annotations

import numpy as np


def accuracy(pred, label) -> float:
    """pred: logits/probs [N, C] or binary scores [N]; label ints."""
    pred = np.asarray(pred)
    label = np.asarray(label)
    if pred.ndim > 1:
        hat = pred.argmax(-1)
    else:
        hat = (pred > 0.5).astype(label.dtype)
    return float((hat == label).mean())


def auc(scores, labels) -> float:
    """Binary ROC-AUC via the rank-sum (Mann-Whitney) statistic, matching the
    reference's AUC metric for CTR."""
    scores = np.asarray(scores, np.float64).reshape(-1)
    labels = np.asarray(labels).reshape(-1)
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    if len(pos) == 0 or len(neg) == 0:
        return 0.5
    order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
    ranks = np.empty_like(order, np.float64)
    ranks[order] = np.arange(1, len(order) + 1)
    # average ranks for ties
    allv = np.concatenate([pos, neg])
    sorted_v = allv[order]
    i = 0
    while i < len(sorted_v):
        j = i
        while j + 1 < len(sorted_v) and sorted_v[j + 1] == sorted_v[i]:
            j += 1
        if j > i:
            avg = ranks[order[i:j + 1]].mean()
            ranks[order[i:j + 1]] = avg
        i = j + 1
    r_pos = ranks[:len(pos)].sum()
    n_p, n_n = len(pos), len(neg)
    return float((r_pos - n_p * (n_p + 1) / 2) / (n_p * n_n))


def precision_recall_f1(pred, label, threshold: float = 0.5):
    pred = np.asarray(pred).reshape(-1)
    label = np.asarray(label).reshape(-1)
    hat = (pred > threshold).astype(np.int64)
    tp = int(((hat == 1) & (label == 1)).sum())
    fp = int(((hat == 1) & (label == 0)).sum())
    fn = int(((hat == 0) & (label == 1)).sum())
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    f1 = 2 * prec * rec / max(prec + rec, 1e-12)
    return prec, rec, f1


def confusion_matrix(pred, label, num_classes: int):
    pred = np.asarray(pred)
    hat = pred.argmax(-1) if pred.ndim > 1 else pred.astype(np.int64)
    label = np.asarray(label).astype(np.int64)
    cm = np.zeros((num_classes, num_classes), np.int64)
    np.add.at(cm, (label, hat), 1)
    return cm
