from hetu_tpu.utils import metrics
from hetu_tpu.utils.logger import MetricLogger
