"""jaxpr -> ONNX model bytes (the hetu2onnx.export analog).

Reference: python/hetu/onnx/hetu2onnx.py:27 walks the hetu op graph and
emits ONNX nodes through per-op opset handlers (onnx_opset/*); here the
traced jaxpr is walked and each primitive lowered through `_EMITTERS`,
writing the wire format directly via `hetu_tpu.onnx.proto` (no `onnx`
package in this environment).

Weights (jaxpr consts) become graph initializers, as ONNX stores them.
pjit / custom_jvp / closed_call sub-jaxprs are inlined; `scan` (RNNs,
scan-stacked layers) is UNROLLED — the static trip count is in the jaxpr,
and the unrolled form round-trips through any consumer without Loop/Scan
subgraph support (size-capped; see _unroll_scan).  Target opset 13.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from hetu_tpu.onnx import proto as P


class _Ctx:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.counter = 0
        self._literal_cache: Dict = {}

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def init_tensor(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(P.tensor_proto(name, np.asarray(arr)))
        return name

    def init_literal(self, arr):
        """Deduped initializer for jaxpr Literals: the same scalar (an
        epsilon repeated per layer) serializes once."""
        a = np.asarray(arr)
        key = (a.tobytes(), str(a.dtype), a.shape)
        if key not in self._literal_cache:
            self._literal_cache[key] = self.init_tensor(a, "lit")
        return self._literal_cache[key]

    def emit(self, op_type, inputs, outputs, attrs=None):
        self.nodes.append(P.node_proto(op_type, inputs, outputs,
                                       attrs=attrs))


def _std_matmul(dn, lhs_nd, rhs_nd) -> bool:
    """dot_general patterns ONNX MatMul covers: [..., M, K] x [..., K, N]
    — one contraction (lhs LAST dim with rhs first non-batch dim), batch
    dims leading and aligned, and exactly ONE free dim on each side."""
    (lc, rc), (lb, rb) = dn
    if len(lc) != 1 or len(rc) != 1:
        return False
    nb = len(lb)
    if tuple(lb) != tuple(range(nb)) or tuple(rb) != tuple(range(nb)):
        return False
    return (rc[0] == nb and lc[0] == lhs_nd - 1
            and lhs_nd - nb == 2 and rhs_nd - nb == 2)


def _einsum_eq(dn, lhs_ndim, rhs_ndim) -> str:
    (lc, rc), (lb, rb) = dn
    letters = "abcdefghijklmnopqrstuvwxyz"
    it = iter(letters)
    lhs = [None] * lhs_ndim
    rhs = [None] * rhs_ndim
    for i, j in zip(lb, rb):
        c = next(it)
        lhs[i] = c
        rhs[j] = c
    for i, j in zip(lc, rc):
        c = next(it)
        lhs[i] = c
        rhs[j] = c
    for i in range(lhs_ndim):
        if lhs[i] is None:
            lhs[i] = next(it)
    for j in range(rhs_ndim):
        if rhs[j] is None:
            rhs[j] = next(it)
    out = [lhs[i] for i in lb]
    out += [lhs[i] for i in range(lhs_ndim) if i not in lb and i not in lc]
    out += [rhs[j] for j in range(rhs_ndim) if j not in rb and j not in rc]
    return f"{''.join(lhs)},{''.join(rhs)}->{''.join(out)}"


# ---- per-primitive emitters: fn(ctx, eqn, ins, outs) ----

_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "sqrt": "Sqrt",
    "abs": "Abs", "sign": "Sign", "floor": "Floor", "ceil": "Ceil",
    "max": "Max", "min": "Min", "pow": "Pow", "logistic": "Sigmoid",
    "erf": "Erf", "stop_gradient": "Identity", "copy": "Identity",
    "and": "And", "or": "Or", "not": "Not", "eq": "Equal",
}
_COMPARE = {"lt": ("Less", False), "le": ("LessOrEqual", False),
            "gt": ("Greater", False), "ge": ("GreaterOrEqual", False)}


def _emit_eqn(ctx: _Ctx, eqn, ins, outs):
    prim = eqn.primitive.name
    p = eqn.params
    if prim in _SIMPLE:
        ctx.emit(_SIMPLE[prim], ins, outs)
    elif prim in _COMPARE:
        ctx.emit(_COMPARE[prim][0], ins, outs)
    elif prim == "rsqrt":
        mid = ctx.fresh("sqrt")
        ctx.emit("Sqrt", ins, [mid])
        ctx.emit("Reciprocal", [mid], outs)
    elif prim == "is_finite":
        # finite == Not(Or(IsInf, IsNaN))
        m1, m2, m3 = ctx.fresh("inf"), ctx.fresh("nan"), ctx.fresh("or")
        ctx.emit("IsInf", ins, [m1])
        ctx.emit("IsNaN", ins, [m2])
        ctx.emit("Or", [m1, m2], [m3])
        ctx.emit("Not", [m3], outs)
    elif prim == "square":
        ctx.emit("Mul", [ins[0], ins[0]], outs)
    elif prim == "cube":
        mid = ctx.fresh("sq")
        ctx.emit("Mul", [ins[0], ins[0]], [mid])
        ctx.emit("Mul", [mid, ins[0]], outs)
    elif prim == "integer_pow":
        dt = np.dtype(eqn.invars[0].aval.dtype)
        y = ctx.init_tensor(np.asarray(p["y"], dt), "pow_exp")
        ctx.emit("Pow", [ins[0], y], outs)
    elif prim == "dot_general":
        dn = p["dimension_numbers"]
        lhs_nd = len(eqn.invars[0].aval.shape)
        rhs_nd = len(eqn.invars[1].aval.shape)
        if _std_matmul(dn, lhs_nd, rhs_nd):
            ctx.emit("MatMul", ins, outs)
        else:
            ctx.emit("Einsum", ins, outs,
                     {"equation": _einsum_eq(dn, lhs_nd, rhs_nd)})
    elif prim == "conv_general_dilated":
        dn = p["dimension_numbers"]
        if (dn.lhs_spec[0], dn.lhs_spec[1]) != (0, 1) or \
                (dn.rhs_spec[0], dn.rhs_spec[1]) != (0, 1) or \
                (dn.out_spec[0], dn.out_spec[1]) != (0, 1):
            raise ValueError("ONNX export: conv must be NCHW/OIHW")
        if any(d != 1 for d in p["lhs_dilation"]):
            raise ValueError("ONNX export: transposed conv unsupported")
        pads = [lo for lo, _ in p["padding"]] + \
               [hi for _, hi in p["padding"]]
        ctx.emit("Conv", ins, outs, {
            "strides": list(p["window_strides"]),
            "pads": pads,
            "dilations": list(p["rhs_dilation"]),
            "group": int(p["feature_group_count"]),
        })
    elif prim == "reshape":
        if p.get("dimensions") is not None:
            raise ValueError("ONNX export: reshape with permutation")
        shape = ctx.init_tensor(np.asarray(p["new_sizes"], np.int64),
                                "shape")
        ctx.emit("Reshape", [ins[0], shape], outs)
    elif prim == "transpose":
        ctx.emit("Transpose", ins, outs, {"perm": list(p["permutation"])})
    elif prim == "broadcast_in_dim":
        shape = p["shape"]
        bdims = p["broadcast_dimensions"]
        inter = [1] * len(shape)
        for src, dst in enumerate(bdims):
            inter[dst] = eqn.invars[0].aval.shape[src]
        cur = ins[0]
        if tuple(inter) != tuple(eqn.invars[0].aval.shape):
            rs = ctx.init_tensor(np.asarray(inter, np.int64), "shape")
            mid = ctx.fresh("rshp")
            ctx.emit("Reshape", [cur, rs], [mid])
            cur = mid
        tgt = ctx.init_tensor(np.asarray(shape, np.int64), "shape")
        ctx.emit("Expand", [cur, tgt], outs)
    elif prim == "reduce_sum":
        axes = ctx.init_tensor(np.asarray(p["axes"], np.int64), "axes")
        ctx.emit("ReduceSum", [ins[0], axes], outs, {"keepdims": 0})
    elif prim in ("reduce_max", "reduce_min", "reduce_prod"):
        op = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
              "reduce_prod": "ReduceProd"}[prim]
        ctx.emit(op, ins, outs, {"axes": list(p["axes"]), "keepdims": 0})
    elif prim == "reduce_and":
        # bool all(): Cast -> ReduceMin -> Cast (opset-13 has no ReduceAnd)
        m1, m2 = ctx.fresh("c"), ctx.fresh("r")
        ctx.emit("Cast", ins, [m1], {"to": P.INT32})
        ctx.emit("ReduceMin", [m1], [m2],
                 {"axes": list(p["axes"]), "keepdims": 0})
        ctx.emit("Cast", [m2], outs, {"to": P.BOOL})
    elif prim == "convert_element_type":
        dt = P.NP_TO_ONNX.get(np.dtype(p["new_dtype"]))
        if dt is None:
            raise ValueError(f"ONNX export: no dtype for {p['new_dtype']}")
        ctx.emit("Cast", ins, outs, {"to": dt})
    elif prim == "select_n":
        if len(ins) != 3:
            raise ValueError("ONNX export: select_n with >2 cases")
        # select_n(pred, a, b) -> b where pred else a
        ctx.emit("Where", [ins[0], ins[2], ins[1]], outs)
    elif prim == "squeeze":
        axes = ctx.init_tensor(np.asarray(p["dimensions"], np.int64),
                               "axes")
        ctx.emit("Squeeze", [ins[0], axes], outs)
    elif prim == "concatenate":
        ctx.emit("Concat", ins, outs, {"axis": int(p["dimension"])})
    elif prim == "split":
        st = ctx.init_tensor(np.asarray(p["sizes"], np.int64), "split")
        ctx.emit("Split", [ins[0], st], outs, {"axis": int(p["axis"])})
    elif prim == "slice":
        if p.get("strides") and any(s != 1 for s in p["strides"]):
            steps = list(p["strides"])
        else:
            steps = [1] * len(p["start_indices"])
        starts = ctx.init_tensor(
            np.asarray(p["start_indices"], np.int64), "starts")
        ends = ctx.init_tensor(
            np.asarray(p["limit_indices"], np.int64), "ends")
        axes = ctx.init_tensor(
            np.arange(len(p["start_indices"]), dtype=np.int64), "axes")
        st = ctx.init_tensor(np.asarray(steps, np.int64), "steps")
        ctx.emit("Slice", [ins[0], starts, ends, axes, st], outs)
    elif prim == "pad":
        cfg = p["padding_config"]
        if any(i != 0 for _, _, i in cfg):
            raise ValueError("ONNX export: interior padding unsupported")
        pads = [lo for lo, _, _ in cfg] + [hi for _, hi, _ in cfg]
        pt = ctx.init_tensor(np.asarray(pads, np.int64), "pads")
        ctx.emit("Pad", [ins[0], pt, ins[1]], outs, {"mode": "constant"})
    elif prim == "clamp":
        # Clip needs scalars; Max(Min(x, hi), lo) is universal
        mid = ctx.fresh("clip")
        ctx.emit("Min", [ins[1], ins[2]], [mid])
        ctx.emit("Max", [mid, ins[0]], outs)
    elif prim == "iota":
        dt = np.dtype(p["dtype"])
        dim = p["dimension"]
        shape = p["shape"]
        ar = np.arange(shape[dim], dtype=dt)
        ar = np.broadcast_to(
            ar.reshape([-1 if i == dim else 1
                        for i in range(len(shape))]), shape)
        name = ctx.init_tensor(ar, "iota")
        ctx.emit("Identity", [name], outs)
    elif prim == "gather":
        _emit_gather(ctx, eqn, ins, outs)
    elif prim == "argmax":
        axes = p["axes"]
        if len(axes) != 1:
            raise ValueError("ONNX export: multi-axis argmax")
        mid = ctx.fresh("am")
        ctx.emit("ArgMax", ins, [mid],
                 {"axis": int(axes[0]), "keepdims": 0})
        dt = P.NP_TO_ONNX[np.dtype(p["index_dtype"])]
        ctx.emit("Cast", [mid], outs, {"to": dt})
    else:
        raise ValueError(f"ONNX export: unsupported primitive '{prim}'")


def _emit_gather(ctx, eqn, ins, outs):
    """lax.gather -> ONNX Gather for the embedding/take pattern:
    one collapsed slice dim indexed by the (squeezed) indices, full slices
    elsewhere."""
    p = eqn.params
    dn = p["dimension_numbers"]
    operand = eqn.invars[0].aval
    slice_sizes = tuple(p["slice_sizes"])
    if len(dn.start_index_map) != 1 or \
            dn.collapsed_slice_dims != dn.start_index_map:
        raise ValueError("ONNX export: general lax.gather unsupported")
    axis = dn.start_index_map[0]
    for d, s in enumerate(slice_sizes):
        want = 1 if d == axis else operand.shape[d]
        if s != want:
            raise ValueError("ONNX export: partial-slice gather")
    # indices usually carry a trailing length-1 coordinate dim: squeeze it.
    # Decide by rank arithmetic, not shape[-1]==1 — output rank is
    # batch-dims + offset-dims, so a coordinate dim is present exactly when
    # out.ndim == (idx.ndim - 1) + len(offset_dims); a data dim that merely
    # happens to be size 1 fails this and must NOT be squeezed.
    idx = eqn.invars[1].aval
    out_rank = eqn.outvars[0].aval.ndim
    has_coord_dim = (idx.shape and idx.shape[-1] == 1
                     and out_rank == (idx.ndim - 1) + len(dn.offset_dims))
    idx_in = ins[1]
    if has_coord_dim:
        ax = ctx.init_tensor(np.asarray([idx.ndim - 1], np.int64), "axes")
        mid = ctx.fresh("sq")
        ctx.emit("Squeeze", [idx_in, ax], [mid])
        idx_in = mid
    ctx.emit("Gather", [ins[0], idx_in], outs, {"axis": int(axis)})


_UNROLL_NODE_CAP = 20_000  # unrolled-scan size guard (nodes)

_CALL_PRIMS = ("pjit", "jit", "closed_call", "custom_jvp_call",
               "custom_vjp_call", "custom_vjp_call_jaxpr",
               "remat", "checkpoint")


def _est_nodes(jaxpr) -> int:
    """Recursive node-count estimate for the unroll cap: nested scans
    multiply by their trip count and call sub-jaxprs count at their true
    size, so a scan-of-scans cannot sneak under the guard as one eqn."""
    total = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "scan":
            inner = getattr(eqn.params["jaxpr"], "jaxpr",
                            eqn.params["jaxpr"])
            total += int(eqn.params["length"]) * max(1, _est_nodes(inner))
        elif prim in _CALL_PRIMS:
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
                or eqn.params.get("fun_jaxpr")
            if sub is not None:
                total += max(1, _est_nodes(getattr(sub, "jaxpr", sub)))
            else:
                total += 1
        else:
            total += 1
    return total


def _unroll_scan(ctx, env, eqn):
    """Inline a lax.scan by unrolling its body `length` times (static trip
    count — jax guarantees it).  Reference round-trips RNNs through ONNX
    (tests/onnx); the unrolled form is the most portable encoding (no Loop/
    Scan subgraph support required of the consumer) at the cost of model
    size, hence the node cap.  xs are sliced per step with a scalar Gather
    (drops axis 0), ys re-stacked with Unsqueeze+Concat; `reverse` scans
    iterate back-to-front but ys keep index order (lax semantics).
    """
    p = eqn.params
    closed = p["jaxpr"]
    inner = getattr(closed, "jaxpr", closed)
    nc, nk = p["num_consts"], p["num_carry"]
    length, reverse = int(p["length"]), bool(p["reverse"])
    est = length * max(1, _est_nodes(inner))
    if est > _UNROLL_NODE_CAP:
        raise ValueError(
            f"ONNX export: scan unroll would emit ~{est} nodes "
            f"(cap {_UNROLL_NODE_CAP}); shorten the sequence for export or "
            "export the per-layer variant (e.g. HeteroGPT)")
    if length == 0 and len(inner.outvars) > nk:
        # ys would need a zero-input Concat — invalid ONNX.  A 0-length
        # scan in an exported model is a degenerate trace; reject loudly.
        raise ValueError(
            "ONNX export: cannot unroll a length-0 scan with scan outputs "
            "(the empty ys has no ONNX encoding); trace with a non-empty "
            "sequence")
    const_names = [_name_of(ctx, env, v) for v in eqn.invars[:nc]]
    carries = [_name_of(ctx, env, v) for v in eqn.invars[nc:nc + nk]]
    xs_names = [_name_of(ctx, env, v) for v in eqn.invars[nc + nk:]]
    n_ys = len(inner.outvars) - nk
    ys_names: List[List] = [[] for _ in range(n_ys)]
    ax0 = ctx.init_tensor(np.asarray([0], np.int64), "axes")
    order = range(length - 1, -1, -1) if reverse else range(length)
    for t in order:
        # each iteration gets a FRESH env: the body's internal vars (same
        # jaxpr objects every iteration) must resolve to fresh node names,
        # or all iterations would write the same outputs
        body_env: Dict[int, str] = {}
        for iv, nm in zip(inner.invars[:nc], const_names):
            body_env[id(iv)] = nm
        for iv, cname in zip(inner.invars[nc:nc + nk], carries):
            body_env[id(iv)] = cname
        for iv, xname in zip(inner.invars[nc + nk:], xs_names):
            # 1-D index + Squeeze (not a 0-d index: scalar TensorProtos
            # don't survive every codec; [t] then squeeze is equivalent)
            idx = ctx.init_literal(np.asarray([t], np.int64))
            gat = ctx.fresh("xg")
            ctx.emit("Gather", [xname, idx], [gat], {"axis": 0})
            sl = ctx.fresh("xt")
            ctx.emit("Squeeze", [gat, ax0], [sl])
            body_env[id(iv)] = sl
        for cv, c in zip(inner.constvars, getattr(closed, "consts", [])):
            body_env[id(cv)] = ctx.init_literal(np.asarray(c))
        _emit_jaxpr(inner, ctx, body_env)
        carries = [_name_of(ctx, body_env, ov)
                   for ov in inner.outvars[:nk]]
        for j, ov in enumerate(inner.outvars[nk:]):
            ys_names[j].append((t, _name_of(ctx, body_env, ov)))
    for souter, name in zip(eqn.outvars[:nk], carries):
        env[id(souter)] = name
    if n_ys:
        for j, pairs in enumerate(ys_names):
            pairs.sort()  # ys keep index order even for reverse scans
            stacked = []
            for _, nm in pairs:
                u = ctx.fresh("yt")
                ctx.emit("Unsqueeze", [nm, ax0], [u])
                stacked.append(u)
            out_name = _name_of(ctx, env, eqn.outvars[nk + j])
            if len(stacked) == 1:
                ctx.emit("Identity", stacked, [out_name])
            else:
                ctx.emit("Concat", stacked, [out_name], {"axis": 0})


def _emit_jaxpr(jaxpr, ctx, env):
    """Emit every eqn of `jaxpr`: pjit/custom_jvp/closed_call sub-jaxprs
    are inlined with a FRESH scoped env per call site (jax caches traces,
    so two calls of one jitted helper share the same sub-jaxpr objects — a
    shared env would make the second call overwrite the first call's
    output names and silently miscompute), and scan bodies are unrolled
    the same way (fresh env per iteration)."""
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "scan":
            _unroll_scan(ctx, env, eqn)
            continue
        if prim in _CALL_PRIMS:
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
                or eqn.params.get("fun_jaxpr")
            if sub is None:
                raise ValueError(f"ONNX export: opaque call '{prim}'")
            consts = getattr(sub, "consts", [])
            inner = getattr(sub, "jaxpr", sub)
            sub_env: Dict[int, str] = {}
            for iv, ov in zip(inner.invars, eqn.invars):
                sub_env[id(iv)] = _name_of(ctx, env, ov)
            for cv, c in zip(inner.constvars, consts):
                sub_env[id(cv)] = ctx.init_tensor(np.asarray(c), "w")
            _emit_jaxpr(inner, ctx, sub_env)
            for souter, sinner in zip(eqn.outvars, inner.outvars):
                env[id(souter)] = _name_of(ctx, sub_env, sinner)
            continue
        ins = [_name_of(ctx, env, v) for v in eqn.invars]
        outs = [_name_of(ctx, env, v) for v in eqn.outvars]
        _emit_eqn(ctx, eqn, ins, outs)


def _name_of(ctx, env, var):
    from jax.extend.core import Literal
    if isinstance(var, Literal):
        return ctx.init_literal(np.asarray(var.val))
    key = id(var)
    if key not in env:
        env[key] = ctx.fresh("v")
    return env[key]


def jaxpr_to_onnx(fn, *example_args, graph_name="hetu_tpu") -> bytes:
    """Trace `fn` and lower the jaxpr to ONNX model bytes (opset 13)."""
    import jax

    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr
    try:
        # make_jaxpr does not DCE: inference traces often carry dead
        # training-only machinery (threaded-but-unused PRNG keys inside
        # scan bodies, etc.) whose primitives have no ONNX lowering.
        # dce_jaxpr prunes them — including inside scan params.
        from jax._src.interpreters.partial_eval import dce_jaxpr

        # instantiate=True keeps ALL invars so the ONNX graph signature
        # still matches example_args even when an arg is unused
        jaxpr, _ = dce_jaxpr(jaxpr, [True] * len(jaxpr.outvars),
                             instantiate=True)
    except Exception:
        pass  # private API moved: export the un-DCE'd jaxpr as before
    ctx = _Ctx()
    env: Dict[int, str] = {}

    graph_inputs = []
    for v in jaxpr.invars:
        name = _name_of(ctx, env, v)
        dt = P.NP_TO_ONNX.get(np.dtype(v.aval.dtype))
        if dt is None:
            raise ValueError(f"ONNX export: input dtype {v.aval.dtype}")
        graph_inputs.append(P.value_info_proto(name, dt,
                                               list(v.aval.shape)))
    for cv, c in zip(jaxpr.constvars, closed.consts):
        env[id(cv)] = ctx.init_tensor(np.asarray(c), "w")

    _emit_jaxpr(jaxpr, ctx, env)

    graph_outputs = []
    for v in jaxpr.outvars:
        name = _name_of(ctx, env, v)
        aval = getattr(v, "aval", None)
        dt = P.NP_TO_ONNX.get(np.dtype(aval.dtype)) if aval is not None \
            else P.FLOAT
        shape = list(aval.shape) if aval is not None else []
        graph_outputs.append(P.value_info_proto(name, dt, shape))

    graph = P.graph_proto(ctx.nodes, graph_name, ctx.initializers,
                          graph_inputs, graph_outputs)
    return P.model_proto(graph)
