"""Minimal protobuf wire codec + the ONNX message subset, zero-dep.

Reference: python/hetu/onnx/ emits real ONNX models via the `onnx` package;
this environment has no `onnx`, so the stable protobuf wire format of the
public onnx.proto schema is implemented directly (field numbers below are
from that schema).  The reader tolerates both packed and unpacked repeated
scalars and both raw_data and typed-array tensor payloads, so files written
by other producers (e.g. torch.onnx) parse too — which is exactly how the
codec is cross-validated in tests/test_onnx.py.

Writer surface: `model_proto(...)` -> bytes.  Reader: `parse_model(bytes)`
-> nested dicts.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# ---- ONNX enums (onnx.proto TensorProto.DataType / AttributeProto.Type)
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL = range(1, 10)
FLOAT16, DOUBLE, UINT32, UINT64 = 10, 11, 12, 13
BFLOAT16 = 16

AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR = 1, 2, 3, 4
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8

NP_TO_ONNX = {
    np.dtype(np.float32): FLOAT, np.dtype(np.float64): DOUBLE,
    np.dtype(np.float16): FLOAT16, np.dtype(np.int8): INT8,
    np.dtype(np.uint8): UINT8, np.dtype(np.int16): INT16,
    np.dtype(np.uint16): UINT16, np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64, np.dtype(np.uint32): UINT32,
    np.dtype(np.uint64): UINT64, np.dtype(np.bool_): BOOL,
}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}


# -------------------------------------------------------------- wire write

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement int64/enum negatives
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_varint(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(int(v))


def f_bytes(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def f_string(field: int, s: str) -> bytes:
    return f_bytes(field, s.encode())


def f_float32(field: int, v: float) -> bytes:
    return _tag(field, 5) + np.float32(v).tobytes()


def f_packed_ints(field: int, vs: Sequence[int]) -> bytes:
    body = b"".join(_varint(int(v)) for v in vs)
    return f_bytes(field, body)


def f_packed_floats(field: int, vs: Sequence[float]) -> bytes:
    return f_bytes(field, np.asarray(vs, np.float32).tobytes())


# ------------------------------------------------------------ ONNX writers

def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = np.ascontiguousarray(arr)
    dt = NP_TO_ONNX.get(arr.dtype)
    if dt is None:
        raise ValueError(f"no ONNX dtype for {arr.dtype}")
    out = f_packed_ints(1, arr.shape)
    out += f_varint(2, dt)
    out += f_string(8, name)
    out += f_bytes(9, arr.tobytes())
    return out


def attribute_proto(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    type=20."""
    out = f_string(1, name)
    if isinstance(value, bool):
        out += f_varint(3, int(value)) + f_varint(20, AT_INT)
    elif isinstance(value, int):
        out += f_varint(3, value) + f_varint(20, AT_INT)
    elif isinstance(value, float):
        out += f_float32(2, value) + f_varint(20, AT_FLOAT)
    elif isinstance(value, str):
        out += f_bytes(4, value.encode()) + f_varint(20, AT_STRING)
    elif isinstance(value, np.ndarray):
        out += f_bytes(5, tensor_proto("", value)) + f_varint(20, AT_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            out += f_packed_floats(7, value) + f_varint(20, AT_FLOATS)
        else:
            out += f_packed_ints(8, value) + f_varint(20, AT_INTS)
    else:
        raise TypeError(f"unsupported attribute value: {value!r}")
    return out


def node_proto(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
               name: str = "", attrs: Optional[Dict] = None) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    out = b"".join(f_string(1, i) for i in inputs)
    out += b"".join(f_string(2, o) for o in outputs)
    if name:
        out += f_string(3, name)
    out += f_string(4, op_type)
    for k, v in (attrs or {}).items():
        out += f_bytes(5, attribute_proto(k, v))
    return out


def value_info_proto(name: str, elem_type: int,
                     shape: Sequence[int]) -> bytes:
    """ValueInfoProto{name=1, type=2}; TypeProto{tensor_type=1};
    Tensor{elem_type=1, shape=2}; TensorShapeProto{dim=1{dim_value=1}}."""
    dims = b"".join(f_bytes(1, f_varint(1, d)) for d in shape)
    tensor_type = f_varint(1, elem_type) + f_bytes(2, dims)
    type_proto = f_bytes(1, tensor_type)
    return f_string(1, name) + f_bytes(2, type_proto)


def graph_proto(nodes: Sequence[bytes], name: str,
                initializers: Sequence[bytes],
                inputs: Sequence[bytes], outputs: Sequence[bytes]) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    out = b"".join(f_bytes(1, n) for n in nodes)
    out += f_string(2, name)
    out += b"".join(f_bytes(5, t) for t in initializers)
    out += b"".join(f_bytes(11, i) for i in inputs)
    out += b"".join(f_bytes(12, o) for o in outputs)
    return out


def model_proto(graph: bytes, *, opset: int = 13,
                producer: str = "hetu_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8;
    OperatorSetIdProto: domain=1, version=2."""
    out = f_varint(1, 8)  # IR version 8 (opset 13+ era)
    out += f_string(2, producer)
    out += f_bytes(7, graph)
    out += f_bytes(8, f_string(1, "") + f_varint(2, opset))
    return out


# -------------------------------------------------------------- wire read

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse_fields(buf: bytes) -> Dict[int, List]:
    """Generic message parse: field -> list of raw values (varint ints or
    bytes for length-delimited; 4/8-byte scalars as bytes)."""
    fields: Dict[int, List] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(v)
    return fields


def _ints(fields: Dict[int, List], field: int) -> List[int]:
    """Repeated int64: accept packed (bytes) and unpacked (varints)."""
    out: List[int] = []
    for v in fields.get(field, []):
        if isinstance(v, (bytes, bytearray)):
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(_signed64(x))
        else:
            out.append(_signed64(v))
    return out


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _str(fields, field, default=""):
    vs = fields.get(field)
    return vs[0].decode() if vs else default


def parse_tensor(buf: bytes) -> Dict:
    f = parse_fields(buf)
    dims = _ints(f, 1)
    dt = f.get(2, [FLOAT])[0]
    name = _str(f, 8)
    np_dt = ONNX_TO_NP.get(dt)
    if np_dt is None:
        raise ValueError(f"unsupported tensor data_type {dt}")
    if 9 in f:  # raw_data
        arr = np.frombuffer(f[9][0], dtype=np_dt).reshape(dims)
    elif 4 in f and dt == FLOAT:  # float_data (packed floats)
        arr = np.frombuffer(f[4][0], np.float32).reshape(dims)
    elif 7 in f and dt == INT64:  # int64_data
        arr = np.asarray(_ints(f, 7), np.int64).reshape(dims)
    elif 5 in f:  # int32_data (also holds bool/int8/... payloads)
        arr = np.asarray(_ints(f, 5), np.int32).astype(np_dt).reshape(dims)
    else:
        arr = np.zeros(dims, np_dt)
    return {"name": name, "array": arr}


def parse_attribute(buf: bytes) -> Tuple[str, object]:
    f = parse_fields(buf)
    name = _str(f, 1)
    at = f.get(20, [0])[0]
    if at == AT_INT:
        return name, _signed64(f.get(3, [0])[0])
    if at == AT_FLOAT:
        return name, float(np.frombuffer(f[2][0], np.float32)[0])
    if at == AT_STRING:
        return name, f[4][0].decode()
    if at == AT_TENSOR:
        return name, parse_tensor(f[5][0])["array"]
    if at == AT_INTS:
        return name, _ints(f, 8)
    if at == AT_FLOATS:
        out = []
        for v in f.get(7, []):
            if isinstance(v, (bytes, bytearray)) and len(v) != 4:
                out.extend(np.frombuffer(v, np.float32).tolist())
            else:
                out.append(float(np.frombuffer(v, np.float32)[0]))
        return name, out
    # untyped fallback (some writers omit type=20): infer from presence
    if 3 in f:
        return name, _signed64(f[3][0])
    if 8 in f:
        return name, _ints(f, 8)
    if 2 in f:
        return name, float(np.frombuffer(f[2][0], np.float32)[0])
    if 4 in f:
        return name, f[4][0].decode()
    if 5 in f:
        return name, parse_tensor(f[5][0])["array"]
    return name, None


def parse_node(buf: bytes) -> Dict:
    f = parse_fields(buf)
    attrs = dict(parse_attribute(a) for a in f.get(5, []))
    return {
        "inputs": [v.decode() for v in f.get(1, [])],
        "outputs": [v.decode() for v in f.get(2, [])],
        "name": _str(f, 3),
        "op_type": _str(f, 4),
        "attrs": attrs,
    }


def parse_value_info(buf: bytes) -> Dict:
    f = parse_fields(buf)
    name = _str(f, 1)
    elem_type, shape = None, []
    if 2 in f:
        tp = parse_fields(f[2][0])
        if 1 in tp:  # tensor_type
            tt = parse_fields(tp[1][0])
            elem_type = tt.get(1, [None])[0]
            if 2 in tt:
                for dim in parse_fields(tt[2][0]).get(1, []):
                    df = parse_fields(dim)
                    shape.append(df.get(1, [None])[0])
    return {"name": name, "elem_type": elem_type, "shape": shape}


def parse_graph(buf: bytes) -> Dict:
    f = parse_fields(buf)
    return {
        "nodes": [parse_node(n) for n in f.get(1, [])],
        "name": _str(f, 2),
        "initializers": [parse_tensor(t) for t in f.get(5, [])],
        "inputs": [parse_value_info(v) for v in f.get(11, [])],
        "outputs": [parse_value_info(v) for v in f.get(12, [])],
    }


def parse_model(buf: bytes) -> Dict:
    f = parse_fields(buf)
    opsets = []
    for o in f.get(8, []):
        of = parse_fields(o)
        opsets.append({"domain": _str(of, 1),
                       "version": of.get(2, [0])[0]})
    if 7 not in f:
        raise ValueError("not an ONNX model (no graph)")
    return {
        "ir_version": f.get(1, [0])[0],
        "producer": _str(f, 2),
        "graph": parse_graph(f[7][0]),
        "opsets": opsets,
    }
