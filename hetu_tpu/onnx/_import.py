"""ONNX model bytes -> executable jax function (the onnx2hetu analog).

Reference: python/hetu/onnx/onnx2hetu.py:32 builds a hetu graph from an
onnx ModelProto through per-op handlers; here the wire format is parsed by
`hetu_tpu.onnx.proto` and each node dispatched through `_OPS` to jax —
covering both hetu_tpu's own exporter output and the common ops real-world
producers emit (torch.onnx: Gemm/Relu/Flatten/BatchNormalization/pools),
which is how the codec is cross-validated without the `onnx` package.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.onnx import proto as P


def _onnx_pads_to_jax(pads, spatial):
    if pads is None:
        return [(0, 0)] * spatial
    half = len(pads) // 2
    return list(zip(pads[:half], pads[half:]))


def _conv(node, ins):
    x, w = ins[0], ins[1]
    at = node["attrs"]
    spatial = x.ndim - 2
    strides = at.get("strides", [1] * spatial)
    dil = at.get("dilations", [1] * spatial)
    groups = at.get("group", 1)
    if at.get("auto_pad", "NOTSET") not in ("NOTSET", ""):
        raise ValueError("ONNX import: auto_pad unsupported; use explicit "
                         "pads")
    pads = _onnx_pads_to_jax(at.get("pads"), spatial)
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads, rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW")[:3] if spatial == 2
        else None)
    if len(ins) > 2 and ins[2] is not None:  # bias
        y = y + ins[2].reshape((1, -1) + (1,) * spatial)
    return y


def _gemm(node, ins):
    at = node["attrs"]
    a, b = ins[0], ins[1]
    if at.get("transA"):
        a = a.T
    if at.get("transB"):
        b = b.T
    y = at.get("alpha", 1.0) * (a @ b)
    if len(ins) > 2 and ins[2] is not None:
        y = y + at.get("beta", 1.0) * ins[2]
    return y


def _batchnorm(node, ins):
    x, scale, bias, mean, var = ins[:5]
    eps = node["attrs"].get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean.reshape(shape)) / jnp.sqrt(
        var.reshape(shape) + eps) * scale.reshape(shape) + \
        bias.reshape(shape)


def _pool(node, ins, kind):
    x = ins[0]
    at = node["attrs"]
    if at.get("ceil_mode"):
        raise ValueError("ONNX import: pool ceil_mode=1 unsupported")
    if at.get("auto_pad", "NOTSET") not in ("NOTSET", ""):
        raise ValueError("ONNX import: pool auto_pad unsupported; use "
                         "explicit pads")
    k = at["kernel_shape"]
    strides = at.get("strides", [1] * len(k))
    pads = _onnx_pads_to_jax(at.get("pads"), len(k))
    window = (1, 1) + tuple(k)
    st = (1, 1) + tuple(strides)
    pd = [(0, 0), (0, 0)] + pads
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, st,
                                     pd)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, st, pd)
    if at.get("count_include_pad", 0) or not any(
            lo or hi for lo, hi in pads):
        return s / float(np.prod(k))
    # default count_include_pad=0: divide by the VALID cell count per window
    ones = jnp.ones_like(x)
    cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, st, pd)
    return s / cnt


def _reduce(node, ins, fn):
    at = node["attrs"]
    axes = at.get("axes")
    if axes is None and len(ins) > 1 and ins[1] is not None:
        axes = [int(a) for a in np.asarray(ins[1]).ravel()]
    axes = None if axes is None else tuple(axes)
    keep = bool(at.get("keepdims", 1))
    return fn(ins[0], axis=axes, keepdims=keep)


def _slice(node, ins):
    x = ins[0]
    at = node["attrs"]
    if len(ins) > 1:
        starts = np.asarray(ins[1]).ravel()
        ends = np.asarray(ins[2]).ravel()
        axes = np.asarray(ins[3]).ravel() \
            if len(ins) > 3 and ins[3] is not None \
            else np.arange(len(starts))
        steps = np.asarray(ins[4]).ravel() \
            if len(ins) > 4 and ins[4] is not None \
            else np.ones(len(starts), np.int64)
    else:  # opset<10: attributes
        starts = np.asarray(at["starts"])
        ends = np.asarray(at["ends"])
        axes = np.asarray(at.get("axes", range(len(starts))))
        steps = np.ones(len(starts), np.int64)
    idx = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        dim = x.shape[int(a)]
        s, e, st = int(s), int(e), int(st)
        if st > 0:
            s = max(s + dim, 0) if s < 0 else min(s, dim)
            e = max(e + dim, 0) if e < 0 else min(e, dim)
            idx[int(a)] = slice(s, e, st)
        else:
            # ONNX: start clamps to [0, dim-1]; end < -dim means
            # "include index 0" (python needs end=None for that)
            s = max(s + dim, 0) if s < 0 else min(s, dim - 1)
            e = e + dim if e >= -dim and e < 0 else e
            idx[int(a)] = slice(s, None if e < 0 else min(e, dim), st)
    return x[tuple(idx)]


_OPS = {
    "Add": lambda n, i: i[0] + i[1], "Sub": lambda n, i: i[0] - i[1],
    "Mul": lambda n, i: i[0] * i[1], "Div": lambda n, i: i[0] / i[1],
    "Neg": lambda n, i: -i[0], "Exp": lambda n, i: jnp.exp(i[0]),
    "Log": lambda n, i: jnp.log(i[0]), "Tanh": lambda n, i: jnp.tanh(i[0]),
    "Sqrt": lambda n, i: jnp.sqrt(i[0]),
    "Reciprocal": lambda n, i: 1.0 / i[0],
    "Abs": lambda n, i: jnp.abs(i[0]), "Sign": lambda n, i: jnp.sign(i[0]),
    "Floor": lambda n, i: jnp.floor(i[0]),
    "Ceil": lambda n, i: jnp.ceil(i[0]),
    "Max": lambda n, i: jnp.maximum(i[0], i[1]) if len(i) == 2
    else jnp.maximum(jnp.maximum(i[0], i[1]), i[2]),
    "Min": lambda n, i: jnp.minimum(i[0], i[1]) if len(i) == 2
    else jnp.minimum(jnp.minimum(i[0], i[1]), i[2]),
    "Pow": lambda n, i: jnp.power(i[0], i[1]),
    "Sigmoid": lambda n, i: jax.nn.sigmoid(i[0]),
    "Erf": lambda n, i: jax.scipy.special.erf(i[0]),
    "Relu": lambda n, i: jax.nn.relu(i[0]),
    "Identity": lambda n, i: i[0],
    "MatMul": lambda n, i: i[0] @ i[1],
    "Einsum": lambda n, i: jnp.einsum(n["attrs"]["equation"], *i),
    "Gemm": _gemm,
    "Conv": _conv,
    "BatchNormalization": _batchnorm,
    "MaxPool": lambda n, i: _pool(n, i, "max"),
    "AveragePool": lambda n, i: _pool(n, i, "avg"),
    "GlobalAveragePool": lambda n, i: jnp.mean(
        i[0], axis=tuple(range(2, i[0].ndim)), keepdims=True),
    "Flatten": lambda n, i: i[0].reshape(
        int(np.prod(i[0].shape[:n["attrs"].get("axis", 1)])), -1),
    "Reshape": lambda n, i: i[0].reshape(
        [i[0].shape[d] if s == 0 else int(s)
         for d, s in enumerate(np.asarray(i[1]).ravel())]
        if 0 in np.asarray(i[1]).ravel() else
        [int(s) for s in np.asarray(i[1]).ravel()]),
    "Transpose": lambda n, i: jnp.transpose(
        i[0], n["attrs"].get("perm")),
    "Expand": lambda n, i: jnp.broadcast_to(
        i[0], _expand_shape(i[0].shape,
                            [int(s) for s in np.asarray(i[1]).ravel()])),
    "Squeeze": lambda n, i: jnp.squeeze(
        i[0], axis=tuple(int(a) for a in np.asarray(i[1]).ravel())
        if len(i) > 1 else tuple(n["attrs"].get("axes", []))or None),
    "Unsqueeze": lambda n, i: jnp.expand_dims(
        i[0], tuple(int(a) for a in np.asarray(i[1]).ravel())
        if len(i) > 1 else tuple(n["attrs"]["axes"])),
    "Concat": lambda n, i: jnp.concatenate(i, axis=n["attrs"]["axis"]),
    "Split": lambda n, i: tuple(jnp.split(
        i[0],
        (np.cumsum(np.asarray(
            i[1] if len(i) > 1 else n["attrs"]["split"]))[:-1].tolist()
         if len(i) > 1 or "split" in n["attrs"]
         else len(n["outputs"])),  # neither form: equal sectioning
        axis=n["attrs"].get("axis", 0))),
    "Cast": lambda n, i: i[0].astype(P.ONNX_TO_NP[n["attrs"]["to"]]),
    "Where": lambda n, i: jnp.where(i[0].astype(bool), i[1], i[2]),
    "Gather": lambda n, i: _gather(n, i),
    "ReduceSum": lambda n, i: _reduce(n, i, jnp.sum),
    "ReduceMax": lambda n, i: _reduce(n, i, jnp.max),
    "ReduceMin": lambda n, i: _reduce(n, i, jnp.min),
    "ReduceProd": lambda n, i: _reduce(n, i, jnp.prod),
    "ReduceMean": lambda n, i: _reduce(n, i, jnp.mean),
    "Slice": _slice,
    "Pad": lambda n, i: _pad(n, i),
    "Clip": lambda n, i: jnp.clip(
        i[0], i[1] if len(i) > 1 and i[1] is not None else None,
        i[2] if len(i) > 2 and i[2] is not None else None),
    "Softmax": lambda n, i: jax.nn.softmax(
        i[0], axis=n["attrs"].get("axis", -1)),
    "Constant": lambda n, i: jnp.asarray(n["attrs"]["value"]),
    "IsInf": lambda n, i: jnp.isinf(i[0]),
    "IsNaN": lambda n, i: jnp.isnan(i[0]),
    "And": lambda n, i: jnp.logical_and(i[0], i[1]),
    "Or": lambda n, i: jnp.logical_or(i[0], i[1]),
    "Not": lambda n, i: jnp.logical_not(i[0]),
    "Equal": lambda n, i: i[0] == i[1],
    "Less": lambda n, i: i[0] < i[1],
    "LessOrEqual": lambda n, i: i[0] <= i[1],
    "Greater": lambda n, i: i[0] > i[1],
    "GreaterOrEqual": lambda n, i: i[0] >= i[1],
    "ArgMax": lambda n, i: _argmax(n, i),
}


def _gather(node, ins):
    axis = node["attrs"].get("axis", 0)
    idx = ins[1].astype(jnp.int32)
    dim = ins[0].shape[axis]
    # ONNX allows negative indices (wrap-around); jnp.take would CLAMP them
    idx = jnp.where(idx < 0, idx + dim, idx)
    return jnp.take(ins[0], idx, axis=axis)


def _argmax(node, ins):
    at = node["attrs"]
    r = jnp.argmax(ins[0], axis=at.get("axis", 0))
    if at.get("keepdims", 1):
        r = jnp.expand_dims(r, at.get("axis", 0))
    return r


def _expand_shape(in_shape, target):
    """ONNX Expand: numpy broadcast of in_shape against target (target may
    have -1-like 1s where input is larger)."""
    t = list(target)
    pad = len(t) - len(in_shape)
    full = [1] * pad + list(in_shape) if pad > 0 else list(in_shape)
    return tuple(max(a, b) for a, b in zip(full, t)) if len(full) == len(t) \
        else tuple(t)


def _pad(node, ins):
    mode = node["attrs"].get("mode", "constant")
    if mode not in ("constant", b"constant"):
        raise ValueError(f"ONNX import: Pad mode {mode!r} unsupported")
    pads = [int(v) for v in np.asarray(ins[1]).ravel()]
    half = len(pads) // 2
    cfg = [(lo, hi, 0) for lo, hi in zip(pads[:half], pads[half:])]
    cval = ins[2] if len(ins) > 2 and ins[2] is not None \
        else jnp.zeros((), ins[0].dtype)
    return jax.lax.pad(ins[0], jnp.asarray(cval, ins[0].dtype), cfg)


def import_onnx(path):
    """Load an .onnx file into an executable jax function.

    Returns (fn, meta): fn takes the graph inputs positionally; meta has
    input/output names and shapes."""
    buf = Path(path).read_bytes()
    model = P.parse_model(buf)
    g = model["graph"]
    missing = sorted({n["op_type"] for n in g["nodes"]
                      if n["op_type"] not in _OPS})
    if missing:
        raise ValueError(f"ONNX import: unsupported ops {missing}")
    inits: Dict[str, np.ndarray] = {
        t["name"]: t["array"] for t in g["initializers"]}
    input_names = [i["name"] for i in g["inputs"]
                   if i["name"] not in inits]

    def fn(*args):
        if len(args) != len(input_names):
            raise TypeError(
                f"expected {len(input_names)} inputs {input_names}")
        env: Dict[str, jnp.ndarray] = {k: jnp.asarray(v)
                                       for k, v in inits.items()}
        for name, a in zip(input_names, args):
            env[name] = jnp.asarray(a)
        for node in g["nodes"]:
            # ONNX marks omitted OPTIONAL inputs with an empty name; keep
            # the positional slot (None) so later inputs don't shift
            ins = [env[nm] if nm else None for nm in node["inputs"]]
            while ins and ins[-1] is None:
                ins.pop()
            out = _OPS[node["op_type"]](node, ins)
            outs = out if isinstance(out, tuple) else (out,)
            for nm, val in zip(node["outputs"], outs):
                env[nm] = val
        res = [env[o["name"]] for o in g["outputs"]]
        return res[0] if len(res) == 1 else tuple(res)

    meta = {
        "inputs": input_names,
        "outputs": [o["name"] for o in g["outputs"]],
        "producer": model["producer"],
        "opsets": model["opsets"],
        "n_nodes": len(g["nodes"]),
    }
    return fn, meta
