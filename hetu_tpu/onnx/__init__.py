"""Model import/export.

Reference: python/hetu/onnx/ (2,337 LoC — hetu2onnx.export, onnx2hetu.
load_onnx, per-op opset handlers, tested against TF round trips).

This environment has no `onnx` package (and no egress to fetch one), so the
portable interchange format here is a self-contained JSON graph serialized
from the traced jaxpr ("HTIR"), with ONNX proto emission gated behind the
optional dependency: when `onnx` is importable, `export_onnx` maps the same
traced graph onto ONNX operators.

    export_graph(fn, args, path)   -> HTIR json (always available)
    load_graph(path)               -> dict graph
    export_onnx(fn, args, path)    -> .onnx (requires the onnx package)
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import numpy as np

# jax primitive name → ONNX op type (the opset-handler table analog,
# reference onnx/onnx_opset/*)
_PRIM_TO_ONNX = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "sqrt": "Sqrt", "abs": "Abs",
    "sign": "Sign", "floor": "Floor", "ceil": "Ceil", "max": "Max",
    "min": "Min", "pow": "Pow", "dot_general": "MatMul",
    "conv_general_dilated": "Conv", "reshape": "Reshape",
    "transpose": "Transpose", "concatenate": "Concat", "slice": "Slice",
    "pad": "Pad", "broadcast_in_dim": "Expand", "reduce_sum": "ReduceSum",
    "reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
    "logistic": "Sigmoid", "erf": "Erf", "rsqrt": "Reciprocal",
    "gather": "Gather", "dynamic_slice": "Slice", "select_n": "Where",
    "convert_element_type": "Cast", "stop_gradient": "Identity",
    "custom_jvp_call": "Identity", "integer_pow": "Pow", "squeeze": "Squeeze",
    "argmax": "ArgMax", "iota": "Range", "clamp": "Clip",
}


def trace_graph(fn, *example_args) -> dict:
    """Serialize the traced dataflow graph to a portable dict."""
    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr
    consts = [np.asarray(c).tolist() if np.asarray(c).size <= 64 else
              {"shape": list(np.shape(c)), "dtype": str(np.asarray(c).dtype)}
              for c in closed.consts]
    nodes = []
    for eqn in jaxpr.eqns:
        nodes.append({
            "op": eqn.primitive.name,
            "onnx_op": _PRIM_TO_ONNX.get(eqn.primitive.name),
            "inputs": [str(v) for v in eqn.invars],
            "outputs": [str(v) for v in eqn.outvars],
            "attrs": {k: repr(v) for k, v in eqn.params.items()},
        })
    return {
        "format": "hetu_tpu.htir.v1",
        "inputs": [{"name": str(v), "shape": list(v.aval.shape),
                    "dtype": str(v.aval.dtype)} for v in jaxpr.invars],
        "outputs": [str(v) for v in jaxpr.outvars],
        "constants": consts,
        "nodes": nodes,
    }


def export_graph(fn, example_args, path) -> str:
    g = trace_graph(fn, *example_args)
    Path(path).write_text(json.dumps(g, indent=1))
    return str(path)


def load_graph(path) -> dict:
    g = json.loads(Path(path).read_text())
    if g.get("format") != "hetu_tpu.htir.v1":
        raise ValueError(f"not an HTIR graph: {path}")
    return g


def unsupported_ops(graph: dict) -> list:
    """Primitives with no ONNX mapping — what export_onnx would reject."""
    return sorted({n["op"] for n in graph["nodes"] if n["onnx_op"] is None})


def export_onnx(fn, example_args, path):  # pragma: no cover - optional dep
    """Emit a real .onnx file; requires the `onnx` package."""
    try:
        import onnx  # noqa: F401
        from onnx import helper
    except ImportError as e:
        raise ImportError(
            "the `onnx` package is not installed in this environment; "
            "use export_graph (HTIR json) or install onnx") from e
    g = trace_graph(fn, *example_args)
    missing = unsupported_ops(g)
    if missing:
        raise ValueError(f"no ONNX mapping for primitives: {missing}")
    nodes = [helper.make_node(n["onnx_op"], n["inputs"], n["outputs"])
             for n in g["nodes"]]
    graph = helper.make_graph(
        nodes, "hetu_tpu",
        [helper.make_tensor_value_info(i["name"], 1, i["shape"])
         for i in g["inputs"]],
        [helper.make_tensor_value_info(o, 1, None) for o in g["outputs"]])
    model = helper.make_model(graph)
    onnx.save(model, str(path))
    return str(path)
