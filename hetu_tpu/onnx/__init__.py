"""Model import/export.

Reference: python/hetu/onnx/ (2,337 LoC — hetu2onnx.export, onnx2hetu.
load_onnx, per-op opset handlers, tested against TF round trips).

Two interchange formats, neither needing the `onnx` package (absent here):

    export_graph(fn, args, path)   -> HTIR json (lossless jaxpr dump)
    load_graph(path)               -> dict graph
    import_graph(path)             -> executable fn from HTIR
    export_onnx(fn, args, path)    -> real .onnx, opset 13: the protobuf
                                      wire format is written directly
                                      (proto.py) and the jaxpr lowered per
                                      primitive (_export.py)
    import_onnx(path)              -> (fn, meta) from a real .onnx file,
                                      including ones written by other
                                      producers (_import.py)

The wire codec is cross-validated against the canonical google.protobuf
implementation in tests/test_onnx.py; the op semantics by zoo round trips
(ResNet-18, HeteroGPT) against the traced original.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend.core import Literal as _Literal

# jax primitive name → ONNX op type (the opset-handler table analog,
# reference onnx/onnx_opset/*)
_PRIM_TO_ONNX = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "sqrt": "Sqrt", "abs": "Abs",
    "sign": "Sign", "floor": "Floor", "ceil": "Ceil", "max": "Max",
    "min": "Min", "pow": "Pow", "dot_general": "MatMul",
    "conv_general_dilated": "Conv", "reshape": "Reshape",
    "transpose": "Transpose", "concatenate": "Concat", "slice": "Slice",
    "pad": "Pad", "broadcast_in_dim": "Expand", "reduce_sum": "ReduceSum",
    "reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
    "logistic": "Sigmoid", "erf": "Erf", "rsqrt": "Reciprocal",
    "gather": "Gather", "dynamic_slice": "Slice", "select_n": "Where",
    "convert_element_type": "Cast", "stop_gradient": "Identity",
    "custom_jvp_call": "Identity", "integer_pow": "Pow", "squeeze": "Squeeze",
    "argmax": "ArgMax", "iota": "Range", "clamp": "Clip",
}


def trace_graph(fn, *example_args, max_inline_const=None) -> dict:
    """Serialize the traced dataflow graph to a portable dict.

    Closure-captured arrays (model WEIGHTS) become jaxpr constants and are
    inlined by default — that is the point of exporting a trained model
    (ONNX stores weights the same way).  Pass max_inline_const=N to elide
    constants above N elements (shape/dtype stub only; the file then can't
    be imported as executable).
    """
    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr
    def enc_const(c):
        a = np.asarray(c)
        if max_inline_const is not None and a.size > max_inline_const:
            return {"elided": True, "shape": list(a.shape),
                    "dtype": str(a.dtype)}
        return {"data": a.tolist(), "dtype": str(a.dtype)}

    consts = [enc_const(c) for c in closed.consts]
    const_names = [str(v) for v in jaxpr.constvars]
    literals = {}

    def vname(v):
        if isinstance(v, _Literal):
            key = f"__lit_{len(literals)}"
            a = np.asarray(v.val)
            literals[key] = {"data": a.tolist(), "dtype": str(a.dtype)}
            return key
        return str(v)

    nodes = []
    for eqn in jaxpr.eqns:
        nodes.append({
            "op": eqn.primitive.name,
            "onnx_op": _PRIM_TO_ONNX.get(eqn.primitive.name),
            "inputs": [vname(v) for v in eqn.invars],
            "outputs": [str(v) for v in eqn.outvars],
            # repr for humans, plus machine-decodable fields for import
            "attrs": {k: repr(v) for k, v in eqn.params.items()},
            "raw_attrs": _encode_params(eqn.params),
        })
    return {
        "format": "hetu_tpu.htir.v1",
        "inputs": [{"name": str(v), "shape": list(v.aval.shape),
                    "dtype": str(v.aval.dtype)} for v in jaxpr.invars],
        "outputs": [vname(v) for v in jaxpr.outvars],
        "constants": consts,
        "const_names": const_names,
        "literals": literals,
        "nodes": nodes,
    }


def _encode_params(params: dict) -> dict:
    """JSON-encode the primitive params the importer understands."""
    out = {}
    for k, v in params.items():
        if v is None:
            continue  # genuinely absent: nothing to consume
        if isinstance(v, (int, float, str, bool)):
            out[k] = v
        elif isinstance(v, (tuple, list)) and all(
                isinstance(x, (int, float, tuple, list)) for x in v):
            out[k] = json.loads(json.dumps(v))  # nested tuples → lists
        elif hasattr(v, "name"):  # dtypes etc.
            out[k] = str(getattr(v, "name", v))
        else:
            out[k] = "__unencodable__"  # import rejects the node
    return out


def export_graph(fn, example_args, path, *, max_inline_const=None) -> str:
    g = trace_graph(fn, *example_args, max_inline_const=max_inline_const)
    Path(path).write_text(json.dumps(g, indent=1))
    return str(path)


def load_graph(path) -> dict:
    g = json.loads(Path(path).read_text())
    if g.get("format") != "hetu_tpu.htir.v1":
        raise ValueError(f"not an HTIR graph: {path}")
    return g


# executable interpreters for the common primitive subset — the onnx2hetu
# per-op handler table analog (reference onnx/onnx_opset/*)
def _mk_dot(attrs):
    dn = attrs.get("dimension_numbers")
    # honor the EXPORTED accumulation dtype: inventing one would change the
    # original model's output dtype/numerics
    pet = attrs.get("preferred_element_type")

    def run(a, b):
        return jax.lax.dot_general(
            a, b, tuple(map(lambda t: tuple(map(tuple, t)), dn))
            if dn else (((a.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=pet)
    return run


# params each handler consumes; anything else present (beyond the harmless
# metadata set) makes import REJECT the node rather than silently drop
# semantics (e.g. lax.reshape's `dimensions` permutation)
_IGNORABLE_PARAMS = {"sharding", "precision", "preferred_element_type",
                     "out_sharding", "weak_type", "accuracy"}
_HANDLER_PARAMS = {
    "dot_general": {"dimension_numbers"},
    "reshape": {"new_sizes"},
    "transpose": {"permutation"},
    "broadcast_in_dim": {"shape", "broadcast_dimensions"},
    "reduce_sum": {"axes"}, "reduce_max": {"axes"}, "reduce_min": {"axes"},
    "convert_element_type": {"new_dtype"},
    "integer_pow": {"y"},
    "squeeze": {"dimensions"},
    "concatenate": {"dimension"},
}

_IMPORT_HANDLERS = {
    "add": lambda at: jnp.add, "sub": lambda at: jnp.subtract,
    "mul": lambda at: jnp.multiply, "div": lambda at: jnp.divide,
    "neg": lambda at: jnp.negative, "exp": lambda at: jnp.exp,
    "log": lambda at: jnp.log, "tanh": lambda at: jnp.tanh,
    "sqrt": lambda at: jnp.sqrt, "abs": lambda at: jnp.abs,
    "sign": lambda at: jnp.sign, "floor": lambda at: jnp.floor,
    "ceil": lambda at: jnp.ceil, "max": lambda at: jnp.maximum,
    "min": lambda at: jnp.minimum, "pow": lambda at: jnp.power,
    "logistic": lambda at: jax.nn.sigmoid,
    "erf": lambda at: jax.scipy.special.erf,
    "rsqrt": lambda at: jax.lax.rsqrt,
    "dot_general": _mk_dot,
    "reshape": lambda at: (lambda x: jnp.reshape(x, at["new_sizes"])),
    "transpose": lambda at: (lambda x: jnp.transpose(x, at["permutation"])),
    "broadcast_in_dim": lambda at: (lambda x: jax.lax.broadcast_in_dim(
        x, at["shape"], at["broadcast_dimensions"])),
    "reduce_sum": lambda at: (lambda x: jnp.sum(x, axis=tuple(at["axes"]))),
    "reduce_max": lambda at: (lambda x: jnp.max(x, axis=tuple(at["axes"]))),
    "reduce_min": lambda at: (lambda x: jnp.min(x, axis=tuple(at["axes"]))),
    "convert_element_type": lambda at: (
        lambda x: x.astype(at["new_dtype"])),
    "stop_gradient": lambda at: (lambda x: jax.lax.stop_gradient(x)),
    "integer_pow": lambda at: (lambda x: jnp.power(x, at["y"])),
    "squeeze": lambda at: (lambda x: jnp.squeeze(
        x, axis=tuple(at["dimensions"]))),
    "concatenate": lambda at: (lambda *xs: jnp.concatenate(
        xs, axis=at["dimension"])),
    "select_n": lambda at: (lambda c, *xs: jnp.select(
        [c == i for i in range(len(xs))], list(xs)) if len(xs) > 2
        else jnp.where(c.astype(bool), xs[1], xs[0])),
    "clamp": lambda at: (lambda lo, x, hi: jnp.clip(x, lo, hi)),
}


def import_graph(path):
    """Rebuild an executable python function from an HTIR file — the
    onnx2hetu.load_onnx analog.  Raises on primitives outside the handler
    table (same contract as the reference's unsupported-op errors)."""
    g = load_graph(path)
    missing = sorted({n["op"] for n in g["nodes"]
                      if n["op"] not in _IMPORT_HANDLERS})
    if missing:
        raise ValueError(f"HTIR import: unsupported primitives {missing}")
    for n in g["nodes"]:
        accepted = _HANDLER_PARAMS.get(n["op"], set()) | _IGNORABLE_PARAMS
        ra = n.get("raw_attrs", {})
        extra = sorted(k for k, v in ra.items()
                       if k not in accepted or v == "__unencodable__")
        if extra:
            raise ValueError(
                f"HTIR import: node {n['op']} carries params the handler "
                f"does not consume: {extra} — refusing to silently drop "
                "semantics")
    const_names = g.get("const_names", [])
    const_vals = []
    for c in g["constants"]:
        if not isinstance(c, dict):        # legacy files: bare list
            const_vals.append(jnp.asarray(c))
            continue
        if c.get("elided"):
            raise ValueError(
                "HTIR import: constants were elided at export "
                "(max_inline_const was set); re-export with the default "
                "inline-all to get an executable graph")
        const_vals.append(jnp.asarray(c["data"], dtype=c["dtype"]))

    def fn(*args):
        if len(args) != len(g["inputs"]):
            raise TypeError(f"expected {len(g['inputs'])} args")
        env = {}
        for spec, a in zip(g["inputs"], args):
            env[spec["name"]] = jnp.asarray(a)
        for name, v in zip(const_names, const_vals):
            env[name] = v
        for name, v in g.get("literals", {}).items():
            if isinstance(v, dict):
                env[name] = jnp.asarray(v["data"], dtype=v["dtype"])
            else:  # legacy
                env[name] = jnp.asarray(v)

        def lookup(name):
            if name in env:
                return env[name]
            raise KeyError(f"HTIR import: unbound value {name!r}")

        for node in g["nodes"]:
            handler = _IMPORT_HANDLERS[node["op"]](node.get("raw_attrs", {}))
            ins = [lookup(nm) for nm in node["inputs"]]
            outs = handler(*ins)
            if not isinstance(outs, tuple):
                outs = (outs,)
            for nm, val in zip(node["outputs"], outs):
                env[nm] = val
        res = [env[nm] for nm in g["outputs"]]
        return res[0] if len(res) == 1 else tuple(res)

    return fn


def unsupported_ops(graph: dict) -> list:
    """Primitives with no ONNX mapping — what export_onnx would reject."""
    return sorted({n["op"] for n in graph["nodes"] if n["onnx_op"] is None})


def export_onnx(fn, example_args, path) -> str:
    """Emit a real .onnx file (opset 13) — no `onnx` package needed: the
    protobuf wire format is written directly (hetu_tpu.onnx.proto), the
    jaxpr lowered per primitive (hetu_tpu.onnx._export), mirroring the
    reference's hetu2onnx.export (python/hetu/onnx/hetu2onnx.py:27)."""
    from hetu_tpu.onnx._export import jaxpr_to_onnx
    data = jaxpr_to_onnx(fn, *example_args)
    Path(path).write_bytes(data)
    return str(path)


# onnx2hetu.load_onnx analog: .onnx file -> executable jax fn
from hetu_tpu.onnx._import import import_onnx  # noqa: E402
