"""PS-backed embedding for training loops — the Hybrid comm-mode path.

Reference: gpu_ops/ParameterServerCommunicate.py + EmbeddingLookUp with PS
(executor prefetch pipeline, executor.py:384): dense params ride the
allreduce plane while embeddings live on the parameter server; workers pull
the touched rows before the step and push IndexedSlices after.

TPU shape of the same idea: the jitted step takes the pulled rows as a
regular input (so XLA sees a small dense tensor, not the trillion-row
table), returns the rows' gradient as an output, and the host pushes it to
the PS between steps.  `pull` can overlap the previous step (prefetch) since
it's pure host work.
"""

from __future__ import annotations

from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

import numpy as np

from hetu_tpu.ps.client import CacheSparseTable, PSTable


class PSEmbedding:
    """num_embeddings x dim table on the PS, with optional HET cache tier.

    This is the TRAINING-side embedding front-end (pull → step → push);
    the ONLINE-SERVING counterpart over the same PS tables — read-mostly,
    bounded-staleness, degrade-capable — is
    :class:`hetu_tpu.serve.recsys.ServingEmbeddingCache`, and a trainer
    using this class can serve concurrently from the same ``table``
    (the serving cache observes every ``push`` within its ``pull_bound``).

    Tiers (same pull/push/prefetch surface for all three):
      * default — in-process C++ table (single TPU-VM host);
      * ``endpoints=`` — the table key-range-partitioned over remote van
        servers ("host:port,host:port" or [(host, port), ...]);
      * ``scheduler=(host, port, n_servers)`` — endpoints resolved from
        the PS scheduler (servers may rejoin at new addresses).
    With ``cache_capacity`` the worker fronts the table with the HET cache
    (in-process or the multi-host RemoteCacheTable, matching the tier).
    """

    def __init__(self, num_embeddings: int, dim: int, *,
                 optimizer: str = "sgd", lr: float = 0.01,
                 cache_capacity: Optional[int] = None,
                 cache_policy: str = "lfuopt", pull_bound: int = 0,
                 init: str = "normal", init_b: float = 0.01, seed: int = 0,
                 endpoints=None, scheduler=None, table_id=None,
                 dtype: str = "f32"):
        # dtype: row storage + wire encoding — "bf16" halves, "int8"
        # quarters embedding memory/traffic while optimizer state and
        # every pulled row stay f32 (ALL tiers: in-process, endpoints=,
        # scheduler=, incl. the HET cache sync ops)
        if table_id is not None and endpoints is None and scheduler is None:
            raise ValueError(
                "table_id applies to the remote tiers only (the in-process "
                "PSTable assigns its own id); pass endpoints= or "
                "scheduler=, or drop table_id")
        if endpoints is not None and scheduler is not None:
            raise ValueError(
                "pass endpoints= OR scheduler=, not both (the scheduler "
                "resolves the endpoints itself)")
        if endpoints is not None or scheduler is not None:
            from hetu_tpu.ps.van import PartitionedPSTable, RemoteCacheTable
            if scheduler is not None:
                host, port, n_servers = scheduler
                self.table = PartitionedPSTable.from_scheduler(
                    host, port, n_servers, num_embeddings, dim, init=init,
                    init_b=init_b, seed=seed, optimizer=optimizer, lr=lr,
                    table_id=table_id, dtype=dtype)
            else:
                self.table = PartitionedPSTable(
                    endpoints, num_embeddings, dim, init=init,
                    init_b=init_b, seed=seed, optimizer=optimizer, lr=lr,
                    table_id=table_id, dtype=dtype)
            cache_cls = RemoteCacheTable
        else:
            self.table = PSTable(num_embeddings, dim, init=init,
                                 init_b=init_b, seed=seed,
                                 optimizer=optimizer, lr=lr, dtype=dtype)
            cache_cls = CacheSparseTable
        try:
            self.cache = (cache_cls(self.table, cache_capacity,
                                    cache_policy, pull_bound=pull_bound)
                          if cache_capacity else None)
        except Exception:
            # don't leak the just-created native group/heartbeat thread on
            # a failed cache construction (mirrors van.py's discipline)
            if hasattr(self.table, "close"):
                self.table.close()
            raise
        self.dim = dim
        # one worker thread: prefetch overlaps the NEXT batch's pull with
        # the current device step (reference prefetch pipeline,
        # executor.py:384 + PSEvent discipline)
        self._prefetcher = ThreadPoolExecutor(max_workers=1)
        self._pending: Optional[Future] = None
        self._layered: dict = {}  # layer_idx -> Future (prefetch_layered)

    def pull(self, indices) -> np.ndarray:
        """rows for this batch: [*indices.shape, dim] float32."""
        if self.cache is not None:
            return self.cache.embedding_lookup(indices)
        return self.table.sparse_pull(
            np.asarray(indices).reshape(-1)).reshape(
                *np.asarray(indices).shape, self.dim)

    def prefetch(self, indices) -> None:
        """Start pulling `indices` on the worker thread; pull_prefetched()
        collects.  Note: push() for rows being prefetched should happen
        BEFORE the prefetch to keep the reference's bounded-staleness
        semantics (the cache tier tolerates the race within its bound)."""
        if self._pending is not None:
            raise RuntimeError(
                "previous prefetch not collected; call pull_prefetched() "
                "first (silently dropping it would misalign the pipeline)")
        idx = np.array(indices, copy=True)
        self._pending = self._prefetcher.submit(self.pull, idx)

    def prefetch_layered(self, segments) -> None:
        """Priority prefetch (reference ps-lite/src/p3_van.h): issue the
        batch's pulls as SEGMENTS ordered by first-use layer index, so the
        rows the model consumes first land first and compute starts while
        later segments are still on the wire.

        ``segments``: iterable of ``(layer_idx, indices)``.  Pulls are
        submitted in ascending ``layer_idx`` on the single prefetch worker
        (issue order = completion order on an in-order tier, exactly P3's
        priority scheduling); collect each with ``pull_layered(layer_idx)``
        in ANY order — only that segment's future blocks.
        """
        if self._layered:
            raise RuntimeError(
                "previous layered prefetch not fully collected; call "
                "pull_layered() for every segment first")
        segs = [(int(li), np.array(idx, copy=True)) for li, idx in segments]
        if len({li for li, _ in segs}) != len(segs):
            raise ValueError("duplicate segment layer index")
        for li, idx in sorted(segs, key=lambda t: t[0]):
            self._layered[li] = self._prefetcher.submit(self.pull, idx)

    def pull_layered(self, layer_idx: int) -> np.ndarray:
        """Collect one segment of :meth:`prefetch_layered` (blocks only on
        that segment — earlier-priority segments were issued first)."""
        fut = self._layered.pop(int(layer_idx), None)
        if fut is None:
            raise RuntimeError(
                f"no layered prefetch in flight for layer {layer_idx}")
        return fut.result()

    def close(self) -> None:
        # wait=True: an in-flight prefetch still holds the native cache /
        # group handles — freeing them under it would be a use-after-free
        self._prefetcher.shutdown(wait=True)
        self._pending = None
        self._layered.clear()
        try:
            self.flush()  # dirty cached grads must reach the servers;
            # ps_rcache_close only retries already-SENT pushes
        except Exception:
            pass  # servers already gone: nothing durable left to save
        if self.cache is not None and hasattr(self.cache, "close"):
            self.cache.close()
        if hasattr(self.table, "close"):
            self.table.close()

    def __del__(self):  # pragma: no cover - interpreter-shutdown best effort
        try:
            self._prefetcher.shutdown(wait=False)
        except Exception:
            pass

    def pull_prefetched(self) -> np.ndarray:
        if self._pending is None:
            raise RuntimeError("no prefetch in flight")
        out = self._pending.result()
        self._pending = None
        return out

    def push(self, indices, row_grads) -> None:
        """apply d(loss)/d(rows) on the server (or into the cache tier)."""
        if self.cache is not None:
            self.cache.embedding_update(indices, row_grads)
        else:
            self.table.sparse_push(indices, row_grads)

    def flush(self) -> None:
        if self.cache is not None:
            self.cache.flush()

    # checkpoint plumbing (reference PS SaveParam/LoadParam)
    def save(self, path) -> None:
        self.flush()
        self.table.save(path)

    def load(self, path) -> None:
        self.table.load(path)
        # server bumped row versions on load, so bounded-staleness lookups
        # re-pull; the old hit ratios describe a dead epoch
        if self.cache is not None:
            self.cache.reset_stats()
